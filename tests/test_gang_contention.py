"""Gang contention: two TPUJobs racing for one slice's capacity
(BASELINE config 5, examples/tpujob-gang-pair.yml; VERDICT round-1 item 9).

The apiserver's pod-create path is wrapped with a capacity-limited fake
kubelet: at most 4 *active* (non-terminal) pods exist at once — one slice.
Two 4-worker jobs are created simultaneously against the real operator
binary (threadiness 2, so their reconciles genuinely interleave). Required
behavior of sync_pods_gang's all-or-none create-with-rollback:

- exactly one job acquires the full slice; the other holds ZERO pods while
  it waits (no stranded partial gang — the deadlock the reference's
  create-if-absent loop would produce);
- when the winner's pods reach a terminal phase, the loser's rate-limited
  requeue acquires the slice and completes too — no livelock.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import threading
import time

import pytest

from tpu_operator.client import errors
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for

CAPACITY = 4


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=90.0, interval=0.25)


def _limit_pod_capacity(fake, capacity: int):
    """Wrap the fake's pod create with a capacity-counting kubelet stand-in:
    active (non-terminal) pods are bounded, extra creates get 403 — what a
    quota'd/device-exhausted slice answers."""
    real_create = fake.pods.create
    lock = threading.Lock()

    def limited_create(namespace, obj):
        with lock:
            active = [
                p for p in fake.pods.list(namespace, "")
                if p.get("status", {}).get("phase")
                not in ("Succeeded", "Failed")
            ]
            if len(active) >= capacity:
                raise errors.ApiError(
                    403, "Forbidden",
                    f"insufficient TPU capacity: {len(active)}/{capacity} "
                    f"chips in use")
            return real_create(namespace, obj)

    fake.pods.create = limited_create


def _job(name: str):
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1",
        "kind": "TPUJob",
        "metadata": {"name": name},
        "spec": {
            "replicaSpecs": [{
                "replicas": CAPACITY,
                "tpuReplicaType": "WORKER",
                "template": {"spec": {"containers": [
                    {"name": "tpu", "image": "payload:test"}]}},
            }],
        },
    }


def _pods_of(cs, job_name):
    return [p for p in cs.pods.list("default", f"job_name={job_name}")]


def _succeed_pods(cs, pods):
    for pod in pods:
        pod["status"] = {
            "phase": "Succeeded",
            "containerStatuses": [{"name": "tpu", "state": {
                "terminated": {"exitCode": 0}}}],
        }
        cs.pods.update("default", pod)


@pytest.fixture
def contended_env():
    harness = ApiServerHarness().start()
    _limit_pod_capacity(harness.clientset, CAPACITY)
    cs = Clientset(RestConfig(host=harness.url, timeout=5.0))
    op = subprocess.Popen(
        [sys.executable, "-m", "tpu_operator.cmd.main", "--master",
         harness.url, "--namespace", "default", "--threadiness", "2"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    yield cs
    op.send_signal(signal.SIGINT)
    try:
        op.wait(timeout=10)
    except subprocess.TimeoutExpired:
        op.kill()
    harness.stop()


def test_two_jobs_one_slice_no_partial_no_livelock(contended_env):
    cs = contended_env
    cs.tpujobs.create("default", _job("gang-a"))
    cs.tpujobs.create("default", _job("gang-b"))

    # One job must acquire the FULL slice.
    def one_winner():
        a, b = len(_pods_of(cs, "gang-a")), len(_pods_of(cs, "gang-b"))
        return sorted((a, b)) == [0, CAPACITY]

    assert wait_for(one_winner), (
        f"no clean winner: gang-a={len(_pods_of(cs, 'gang-a'))} "
        f"gang-b={len(_pods_of(cs, 'gang-b'))}")

    winner = "gang-a" if len(_pods_of(cs, "gang-a")) == CAPACITY else "gang-b"
    loser = "gang-b" if winner == "gang-a" else "gang-a"

    # While the winner holds the slice, the loser must keep holding ZERO
    # pods (all-or-none rollback) across repeated reconcile attempts.
    for _ in range(8):
        assert len(_pods_of(cs, loser)) == 0, "loser stranded a partial gang"
        time.sleep(0.25)

    # Winner completes → slice frees → loser's requeue acquires it.
    _succeed_pods(cs, _pods_of(cs, winner))
    assert wait_for(lambda: (cs.tpujobs.get("default", winner)
                             .get("status", {}).get("phase") == "Done"))
    assert wait_for(
        lambda: len(_pods_of(cs, loser)) == CAPACITY,
        timeout=120.0), "loser never acquired the freed slice (livelock?)"

    _succeed_pods(cs, _pods_of(cs, loser))
    assert wait_for(lambda: (cs.tpujobs.get("default", loser)
                             .get("status", {}).get("phase") == "Done"))
