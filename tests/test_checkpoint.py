"""Checkpoint/resume tests (payload/checkpoint.py) on the CPU mesh.

The whole-group-restart resume path end-to-end: train → save → simulate a
group restart (fresh state, fresh process-side objects) → restore → the run
continues from the saved step and the restored pytree matches exactly.
Plus the operator side of the contract: spec.checkpointDir →
TPU_CHECKPOINT_DIR injection.
"""

import numpy as np
import pytest

import jax

from tpu_operator.apis.tpujob.v1alpha1 import types
from tpu_operator.payload import checkpoint, data as data_mod, train


def tiny_build(seed=0):
    from tpu_operator.payload.cifar import build, parse_args

    args = parse_args([
        "--steps", "6", "--batch", "16", "--blocks", "1",
        "--widths", "8", "8", "8", "--log-every", "0",
    ])
    return args, build(args)


def test_from_env_or_args_unconfigured_is_none():
    assert checkpoint.from_env_or_args("", env={}) is None


def test_from_env_or_args_env_fallback(tmp_path):
    ck = checkpoint.from_env_or_args(
        "", env={"TPU_CHECKPOINT_DIR": str(tmp_path / "ck")})
    assert ck is not None
    assert ck.directory == str(tmp_path / "ck")
    ck.close()


def test_save_restore_roundtrip(tmp_path):
    args, (mesh, _m, state, step, batches) = tiny_build()
    for _ in range(3):
        arrays = data_mod.put_global_batch(mesh, *next(batches))
        state, _metrics = step(state, *arrays)

    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(3, state)
    ck.close()

    # Simulated whole-group restart: fresh everything.
    _args2, (mesh2, _m2, fresh, _step2, _b2) = tiny_build()
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    restored, start = ck2.restore(fresh)
    ck2.close()
    assert start == 3
    assert int(jax.device_get(restored.step)) == 3
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_without_checkpoint_is_identity(tmp_path):
    _args, (_mesh, _m, state, _step, _b) = tiny_build()
    ck = checkpoint.Checkpointer(str(tmp_path / "empty"), save_every=1)
    same, start = ck.restore(state)
    ck.close()
    assert start == 0
    assert same is state


def test_train_loop_resumes_to_target_total(tmp_path):
    """train_loop treats `steps` as target total: a restarted job with a
    step-4 checkpoint runs only the remaining steps and lands on step 6."""
    ckdir = str(tmp_path / "ck")
    args, (mesh, _m, state, step, batches) = tiny_build()
    ck = checkpoint.Checkpointer(ckdir, save_every=2)
    state, _ = train.train_loop(mesh, step, state, batches, steps=4,
                                checkpointer=ck)
    ck.close()
    assert int(jax.device_get(state.step)) == 4

    # Restart: fresh state, new checkpointer over the same dir.
    _args2, (mesh2, _m2, fresh, step2, batches2) = tiny_build()
    ck2 = checkpoint.Checkpointer(ckdir, save_every=2)
    assert ck2.latest_step() == 4
    final, _ = train.train_loop(mesh2, step2, fresh, batches2, steps=6,
                                checkpointer=ck2)
    ck2.close()
    assert int(jax.device_get(final.step)) == 6

    # The final state is also checkpointed (end-of-run save).
    ck3 = checkpoint.Checkpointer(ckdir)
    assert ck3.latest_step() == 6
    ck3.close()


def test_resume_fast_forwards_data_stream(tmp_path):
    """The resumed run must consume batches start..steps-1, not 0..remaining:
    the seed-deterministic stream is advanced past what attempt 0 trained on."""
    ckdir = str(tmp_path / "ck")
    args, (mesh, _m, state, step, batches) = tiny_build()
    ck = checkpoint.Checkpointer(ckdir, save_every=1)
    train.train_loop(mesh, step, state, batches, steps=4, checkpointer=ck)
    ck.close()

    consumed = []

    def counting_stream():
        import itertools
        for i, b in enumerate(tiny_build()[1][4]):
            consumed.append(i)
            yield b

    _args2, (mesh2, _m2, fresh, step2, _b2) = tiny_build()
    ck2 = checkpoint.Checkpointer(ckdir, save_every=1)
    train.train_loop(mesh2, step2, fresh, counting_stream(), steps=6,
                     checkpointer=ck2)
    ck2.close()
    # 4 skipped on fast-forward + 2 trained, in order; the input pipeline
    # may read a bounded look-ahead past the last trained batch (prefetch
    # depth 2) — extra *consumption* is fine, extra *training* is not,
    # and ck2's saved step (6, asserted via resume elsewhere) pins that.
    assert consumed[:6] == [0, 1, 2, 3, 4, 5]
    assert len(consumed) <= 6 + 2
    assert consumed == sorted(consumed)


def test_interval_policy_skips_off_interval_steps(tmp_path):
    _args, (mesh, _m, state, step, batches) = tiny_build()
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    state, _ = step(state, *arrays)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=10)
    assert ck.maybe_save(1, state)       # first save always lands
    assert not ck.maybe_save(2, state)   # off-interval → skipped
    assert not ck.maybe_save(9, state)
    assert ck.maybe_save(10, state)      # step % interval == 0 → saved
    ck.close()


def test_spec_checkpoint_dir_roundtrip_and_env_injection():
    from tpu_operator.trainer import replicas

    spec = types.TPUJobSpec.from_dict({
        "replicaSpecs": [{
            "replicas": 2,
            "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu"}]}},
        }],
        "checkpointDir": "/ckpt/run1",
    })
    assert spec.checkpoint_dir == "/ckpt/run1"
    assert spec.to_dict()["checkpointDir"] == "/ckpt/run1"

    env = replicas.build_replica_env("job", "ab12", spec,
                                     types.TPUReplicaType.WORKER, 0)
    assert env["TPU_CHECKPOINT_DIR"] == "/ckpt/run1"


def test_spec_profile_dir_roundtrip_and_env_injection():
    from tpu_operator.trainer import replicas

    spec = types.TPUJobSpec.from_dict({
        "replicaSpecs": [{
            "replicas": 2,
            "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu"}]}},
        }],
        "profileDir": "/traces/run1",
    })
    assert spec.profile_dir == "/traces/run1"
    assert spec.to_dict()["profileDir"] == "/traces/run1"

    env = replicas.build_replica_env("job", "ab12", spec,
                                     types.TPUReplicaType.WORKER, 0)
    assert env["TPU_PROFILE_DIR"] == "/traces/run1"
    # unset -> not injected
    spec2 = types.TPUJobSpec.from_dict(
        {"replicaSpecs": spec.to_dict()["replicaSpecs"]})
    env2 = replicas.build_replica_env("job", "ab12", spec2,
                                      types.TPUReplicaType.WORKER, 0)
    assert "TPU_PROFILE_DIR" not in env2


def test_sigterm_drain_checkpoints_current_step(tmp_path):
    # First SIGTERM → cooperative drain: train_loop saves the *current*
    # step (not the last interval save) and exits retryable (143).
    import pytest

    from tpu_operator.payload import bootstrap, checkpoint as ckpt_mod
    from tpu_operator.payload import data as data_mod, models, train

    import jax
    import jax.numpy as jnp
    import optax

    mesh = train.make_mesh(4)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((16, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)
    batches = data_mod.synthetic_linear(0, 16, 8)

    ckpt = ckpt_mod.Checkpointer(str(tmp_path / "ck"), save_every=1000)

    def drain_after_step_7(i, _metrics):
        # Step-indexed trigger (the signal handler's moral equivalent),
        # independent of input-pipeline prefetch look-ahead.
        if i == 7:
            bootstrap.request_drain()

    try:
        with pytest.raises(SystemExit) as exc:
            train.train_loop(mesh, step, state, batches, 50,
                             checkpointer=ckpt, log_every=1,
                             log_fn=drain_after_step_7)
        assert exc.value.code == bootstrap.EXIT_RETRYABLE
        ckpt.close()
        # drain fired entering step index 7 (7 steps completed)
        assert ckpt.manager.latest_step() == 7
    finally:
        bootstrap.reset_drain()


def test_drain_without_checkpointer_still_exits_retryable():
    import pytest

    from tpu_operator.payload import bootstrap
    from tpu_operator.payload import data as data_mod, models, train

    import jax
    import jax.numpy as jnp
    import optax

    mesh = train.make_mesh(2)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((8, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)
    bootstrap.request_drain()
    try:
        with pytest.raises(SystemExit) as exc:
            train.train_loop(mesh, step, state,
                             data_mod.synthetic_linear(0, 8, 8), 10)
        assert exc.value.code == bootstrap.EXIT_RETRYABLE
    finally:
        bootstrap.reset_drain()
