"""Checkpoint/resume tests (payload/checkpoint.py) on the CPU mesh.

The whole-group-restart resume path end-to-end: train → save → simulate a
group restart (fresh state, fresh process-side objects) → restore → the run
continues from the saved step and the restored pytree matches exactly.
Plus the operator side of the contract: spec.checkpointDir →
TPU_CHECKPOINT_DIR injection.
"""

import numpy as np
import pytest

import jax

from tpu_operator.apis.tpujob.v1alpha1 import types
from tpu_operator.payload import checkpoint, data as data_mod, train


def tiny_build(seed=0):
    from tpu_operator.payload.cifar import build, parse_args

    args = parse_args([
        "--steps", "6", "--batch", "16", "--blocks", "1",
        "--widths", "8", "8", "8", "--log-every", "0",
    ])
    return args, build(args)


def test_from_env_or_args_unconfigured_is_none():
    assert checkpoint.from_env_or_args("", env={}) is None


def test_from_env_or_args_env_fallback(tmp_path):
    ck = checkpoint.from_env_or_args(
        "", env={"TPU_CHECKPOINT_DIR": str(tmp_path / "ck")})
    assert ck is not None
    assert ck.directory == str(tmp_path / "ck")
    ck.close()


def test_save_restore_roundtrip(tmp_path):
    args, (mesh, _m, state, step, batches) = tiny_build()
    for _ in range(3):
        arrays = data_mod.put_global_batch(mesh, *next(batches))
        state, _metrics = step(state, *arrays)

    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(3, state)
    ck.close()

    # Simulated whole-group restart: fresh everything.
    _args2, (mesh2, _m2, fresh, _step2, _b2) = tiny_build()
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    restored, start = ck2.restore(fresh)
    ck2.close()
    assert start == 3
    assert int(jax.device_get(restored.step)) == 3
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_without_checkpoint_is_identity(tmp_path):
    _args, (_mesh, _m, state, _step, _b) = tiny_build()
    ck = checkpoint.Checkpointer(str(tmp_path / "empty"), save_every=1)
    same, start = ck.restore(state)
    ck.close()
    assert start == 0
    assert same is state


def test_train_loop_resumes_to_target_total(tmp_path):
    """train_loop treats `steps` as target total: a restarted job with a
    step-4 checkpoint runs only the remaining steps and lands on step 6."""
    ckdir = str(tmp_path / "ck")
    args, (mesh, _m, state, step, batches) = tiny_build()
    ck = checkpoint.Checkpointer(ckdir, save_every=2)
    state, _ = train.train_loop(mesh, step, state, batches, steps=4,
                                checkpointer=ck)
    ck.close()
    assert int(jax.device_get(state.step)) == 4

    # Restart: fresh state, new checkpointer over the same dir.
    _args2, (mesh2, _m2, fresh, step2, batches2) = tiny_build()
    ck2 = checkpoint.Checkpointer(ckdir, save_every=2)
    assert ck2.latest_step() == 4
    final, _ = train.train_loop(mesh2, step2, fresh, batches2, steps=6,
                                checkpointer=ck2)
    ck2.close()
    assert int(jax.device_get(final.step)) == 6

    # The final state is also checkpointed (end-of-run save).
    ck3 = checkpoint.Checkpointer(ckdir)
    assert ck3.latest_step() == 6
    ck3.close()


def test_resume_fast_forwards_data_stream(tmp_path):
    """The resumed run must consume batches start..steps-1, not 0..remaining:
    the seed-deterministic stream is advanced past what attempt 0 trained on."""
    ckdir = str(tmp_path / "ck")
    args, (mesh, _m, state, step, batches) = tiny_build()
    ck = checkpoint.Checkpointer(ckdir, save_every=1)
    train.train_loop(mesh, step, state, batches, steps=4, checkpointer=ck)
    ck.close()

    consumed = []

    def counting_stream():
        for i, b in enumerate(tiny_build()[1][4]):
            consumed.append(i)
            yield b

    _args2, (mesh2, _m2, fresh, step2, _b2) = tiny_build()
    ck2 = checkpoint.Checkpointer(ckdir, save_every=1)
    train.train_loop(mesh2, step2, fresh, counting_stream(), steps=6,
                     checkpointer=ck2)
    ck2.close()
    # 4 skipped on fast-forward + 2 trained, in order; the input pipeline
    # may read a bounded look-ahead past the last trained batch (prefetch
    # depth 2) — extra *consumption* is fine, extra *training* is not,
    # and ck2's saved step (6, asserted via resume elsewhere) pins that.
    assert consumed[:6] == [0, 1, 2, 3, 4, 5]
    assert len(consumed) <= 6 + 2
    assert consumed == sorted(consumed)


def _pipe_build(schedule="1f1b"):
    from tpu_operator.payload import pipeline

    args = pipeline.parse_args([
        "--batch", "16", "--seq-len", "32", "--dim", "32", "--heads", "2",
        "--layers", "4", "--pipeline", "4", "--microbatches", "4",
        "--dtype", "f32", "--lr", "1e-2", "--schedule", schedule,
        "--log-every", "0"])
    mesh = pipeline.make_pipe_mesh(8, pipeline=4)
    return args, pipeline.build(args, mesh=mesh)


def test_sharded_checkpoint_roundtrip_pipeline(tmp_path):
    """orbax save/restore of a (data, pipe)-stacked TrainState: the state
    every real pipeline job resumes after a group restart. The restored
    leaves must equal the saved ones AND land on the live state's pipe
    shardings (not device-0 arrays)."""
    from jax.sharding import NamedSharding

    _args, (mesh, _s, state, step, batches) = _pipe_build()
    for _ in range(3):
        (tok,) = data_mod.put_global_batch(mesh, *next(batches))
        state, _m = step(state, tok)

    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(3, state)
    ck.close()

    _args2, (mesh2, _s2, fresh, _step2, _b2) = _pipe_build()
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    restored, start = ck2.restore(fresh)
    ck2.close()
    assert start == 3
    blk = restored.params["stages"]["block0"]["mlp_up"]["kernel"]
    assert isinstance(blk.sharding, NamedSharding)
    assert tuple(blk.sharding.spec) == ("pipe", None, None)
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_checkpoint_roundtrip_moe_ep_tp(tmp_path):
    """Same round-trip for a (data, expert, model)-sharded MoE TrainState —
    expert stacks on `expert`, FFN hidden dims on `model`."""
    from tpu_operator.payload import moe

    def build():
        args = moe.parse_args([
            "--batch", "8", "--seq-len", "32", "--dim", "32", "--heads",
            "2", "--layers", "2", "--experts", "4", "--expert-parallel",
            "2", "--tensor-parallel", "2", "--dtype", "f32",
            "--log-every", "0"])
        mesh = moe.make_moe_mesh(8, expert_parallel=2, tensor_parallel=2)
        return moe.build(args, mesh=mesh)

    from jax.sharding import PartitionSpec as P

    mesh, _m, state, step, batches = build()
    for _ in range(2):
        (tok,) = data_mod.put_global_batch(mesh, *next(batches),
                                           spec=P("data", None))
        state, _metrics = step(state, tok)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(2, state)
    ck.close()

    mesh2, _m2, fresh, _step2, _b2 = build()
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    restored, start = ck2.restore(fresh)
    ck2.close()
    assert start == 2
    w1 = restored.params["block1"]["moe"]["w1"]
    assert tuple(w1.sharding.spec) == ("expert", None, "model")
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipeline_group_restart_resumes_identical_trajectory(tmp_path):
    """The e2e restart contract on a sharded pipeline job: run A trains 8
    uninterrupted steps; run B trains 4, group-restarts (fresh build),
    resumes from the drained checkpoint and finishes. B's post-restart
    losses must match A's steps 5-8 exactly (f32, deterministic stream +
    fast-forward)."""
    ckdir = str(tmp_path / "ck")

    _a, (mesh_a, _sa, st_a, step_a, bat_a) = _pipe_build()
    losses_a = []
    for _ in range(8):
        (tok,) = data_mod.put_global_batch(mesh_a, *next(bat_a))
        st_a, m = step_a(st_a, tok)
        losses_a.append(float(m["loss"]))

    _b, (mesh_b, _sb, st_b, step_b, bat_b) = _pipe_build()
    ck = checkpoint.Checkpointer(ckdir, save_every=4)
    st_b, _ = train.train_loop(mesh_b, step_b, st_b, bat_b, steps=4,
                               checkpointer=ck)
    ck.close()

    _c, (mesh_c, _sc, fresh, step_c, bat_c) = _pipe_build()
    ck2 = checkpoint.Checkpointer(ckdir, save_every=100)
    restored, start = ck2.restore(fresh)
    assert start == 4
    for _ in range(start):
        next(bat_c)  # train_loop's fast-forward, inlined for loss capture
    losses_c = []
    for _ in range(4):
        (tok,) = data_mod.put_global_batch(mesh_c, *next(bat_c))
        restored, m = step_c(restored, tok)
        losses_c.append(float(m["loss"]))
    ck2.close()
    np.testing.assert_allclose(losses_c, losses_a[4:], rtol=1e-6, atol=1e-6)


def test_interval_policy_skips_off_interval_steps(tmp_path):
    _args, (mesh, _m, state, step, batches) = tiny_build()
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    state, _ = step(state, *arrays)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=10)
    assert ck.maybe_save(1, state)       # first save always lands
    assert not ck.maybe_save(2, state)   # off-interval → skipped
    assert not ck.maybe_save(9, state)
    assert ck.maybe_save(10, state)      # step % interval == 0 → saved
    ck.close()


def test_spec_checkpoint_dir_roundtrip_and_env_injection():
    from tpu_operator.trainer import replicas

    spec = types.TPUJobSpec.from_dict({
        "replicaSpecs": [{
            "replicas": 2,
            "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu"}]}},
        }],
        "checkpointDir": "/ckpt/run1",
    })
    assert spec.checkpoint_dir == "/ckpt/run1"
    assert spec.to_dict()["checkpointDir"] == "/ckpt/run1"

    env = replicas.build_replica_env("job", "ab12", spec,
                                     types.TPUReplicaType.WORKER, 0)
    assert env["TPU_CHECKPOINT_DIR"] == "/ckpt/run1"


def test_spec_profile_dir_roundtrip_and_env_injection():
    from tpu_operator.trainer import replicas

    spec = types.TPUJobSpec.from_dict({
        "replicaSpecs": [{
            "replicas": 2,
            "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu"}]}},
        }],
        "profileDir": "/traces/run1",
    })
    assert spec.profile_dir == "/traces/run1"
    assert spec.to_dict()["profileDir"] == "/traces/run1"

    env = replicas.build_replica_env("job", "ab12", spec,
                                     types.TPUReplicaType.WORKER, 0)
    assert env["TPU_PROFILE_DIR"] == "/traces/run1"
    # unset -> not injected
    spec2 = types.TPUJobSpec.from_dict(
        {"replicaSpecs": spec.to_dict()["replicaSpecs"]})
    env2 = replicas.build_replica_env("job", "ab12", spec2,
                                      types.TPUReplicaType.WORKER, 0)
    assert "TPU_PROFILE_DIR" not in env2


def test_sigterm_drain_checkpoints_current_step(tmp_path):
    # First SIGTERM → cooperative drain: train_loop saves the *current*
    # step (not the last interval save) and exits retryable (143).
    import pytest

    from tpu_operator.payload import bootstrap, checkpoint as ckpt_mod
    from tpu_operator.payload import data as data_mod, models, train

    import jax
    import jax.numpy as jnp
    import optax

    mesh = train.make_mesh(4)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((16, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)
    batches = data_mod.synthetic_linear(0, 16, 8)

    ckpt = ckpt_mod.Checkpointer(str(tmp_path / "ck"), save_every=1000)

    def drain_after_step_7(i, _metrics):
        # Step-indexed trigger (the signal handler's moral equivalent),
        # independent of input-pipeline prefetch look-ahead.
        if i == 7:
            bootstrap.request_drain()

    try:
        with pytest.raises(SystemExit) as exc:
            train.train_loop(mesh, step, state, batches, 50,
                             checkpointer=ckpt, log_every=1,
                             log_fn=drain_after_step_7)
        assert exc.value.code == bootstrap.EXIT_RETRYABLE
        ckpt.close()
        # drain fired entering step index 7 (7 steps completed)
        assert ckpt.manager.latest_step() == 7
    finally:
        bootstrap.reset_drain()


def test_drain_without_checkpointer_still_exits_retryable():
    import pytest

    from tpu_operator.payload import bootstrap
    from tpu_operator.payload import data as data_mod, models, train

    import jax
    import jax.numpy as jnp
    import optax

    mesh = train.make_mesh(2)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((8, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)
    bootstrap.request_drain()
    try:
        with pytest.raises(SystemExit) as exc:
            train.train_loop(mesh, step, state,
                             data_mod.synthetic_linear(0, 8, 8), 10)
        assert exc.value.code == bootstrap.EXIT_RETRYABLE
    finally:
        bootstrap.reset_drain()
