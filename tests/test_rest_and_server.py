"""REST client ↔ in-process apiserver tests, leader election, kubeconfig
resolution, chaos injection, and the full binary path (cmd.server.run driven
over real HTTP) — the envtest tier SURVEY.md §4 calls for.
"""

import threading
import time

import pytest

from tpu_operator.client import errors
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.controller.chaos import ChaosMonkey
from tpu_operator.controller.leaderelection import LeaderElector
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.util import k8sutil
from tests.test_informer_controller import wait_for, worker_job_dict


@pytest.fixture
def api():
    with ApiServerHarness() as srv:
        yield srv, Clientset(RestConfig(host=srv.url, timeout=5.0))


# --- REST CRUD over the wire -------------------------------------------------

def test_rest_crud_roundtrip(api):
    srv, cs = api
    created = cs.pods.create("default", {
        "metadata": {"name": "p1", "labels": {"app": "x"}}, "spec": {}})
    assert created["metadata"]["uid"]
    got = cs.pods.get("default", "p1")
    assert got["metadata"]["name"] == "p1"

    got["spec"]["nodeName"] = "node-a"
    updated = cs.pods.update("default", got)
    assert updated["spec"]["nodeName"] == "node-a"

    assert len(cs.pods.list("default")) == 1
    assert cs.pods.list("default", label_selector="app=x")
    assert cs.pods.list("default", label_selector="app=y") == []

    cs.pods.delete("default", "p1")
    with pytest.raises(errors.ApiError) as exc:
        cs.pods.get("default", "p1")
    assert errors.is_not_found(exc.value)


def test_rest_error_mapping(api):
    _srv, cs = api
    cs.pods.create("default", {"metadata": {"name": "dup"}})
    with pytest.raises(errors.ApiError) as exc:
        cs.pods.create("default", {"metadata": {"name": "dup"}})
    assert errors.is_already_exists(exc.value)


def test_rest_update_status_subresource(api):
    _srv, cs = api
    cs.tpujobs.create("default", worker_job_dict())
    obj = cs.tpujobs.get("default", "train")
    obj["status"] = {"phase": "Running"}
    out = cs.tpujobs.update_status("default", obj)
    assert out["status"]["phase"] == "Running"


def test_rest_delete_collection(api):
    _srv, cs = api
    for i in range(3):
        cs.pods.create("default", {"metadata": {"name": f"p{i}", "labels": {"g": "1"}}})
    cs.pods.create("default", {"metadata": {"name": "other"}})
    n = cs.pods.delete_collection("default", label_selector="g=1")
    assert n == 3
    assert [p["metadata"]["name"] for p in cs.pods.list("default")] == ["other"]


def test_rest_watch_stream(api):
    srv, cs = api
    watch = cs.tpujobs.watch("default")
    seen = []
    consumer = threading.Thread(
        target=lambda: [seen.append(ev) for ev in watch], daemon=True
    )
    consumer.start()
    try:
        # Wait for the server-side watcher registration (a fixed sleep flaked
        # under CPU contention: events fired before the GET was processed and
        # were lost, starving both ends).
        assert wait_for(lambda: srv.clientset.tpujobs._watchers)
        srv.clientset.tpujobs.create("default", worker_job_dict("w1"))
        srv.clientset.tpujobs.delete("default", "w1")
        assert wait_for(lambda: len(seen) >= 2)
        assert seen[0][0] == "ADDED" and seen[0][1]["metadata"]["name"] == "w1"
        assert seen[1][0] == "DELETED"
    finally:
        watch.stop()
    consumer.join(timeout=5.0)
    assert not consumer.is_alive()


def test_rest_watch_survives_server_death(api):
    """Killing the apiserver mid-watch must end the stream cleanly — the
    consumer thread exits without an unhandled exception (the chunked read
    surfaces IncompleteRead, an HTTPException the iterator must swallow so
    the reflector above re-lists instead of dying)."""
    srv, cs = api
    watch = cs.tpujobs.watch("default")
    seen, errs = [], []

    def consume():
        try:
            for ev in watch:
                seen.append(ev)
        except BaseException as exc:  # noqa: BLE001 — the assertion target
            errs.append(exc)

    consumer = threading.Thread(target=consume, daemon=True)
    consumer.start()
    try:
        assert wait_for(lambda: srv.clientset.tpujobs._watchers)
        srv.clientset.tpujobs.create("default", worker_job_dict("w1"))
        assert wait_for(lambda: len(seen) >= 1)
        # kill(), not stop(): stop() lets handlers write the terminal chunk
        # (clean EOF — doesn't exercise this path); kill() severs the socket
        # mid-stream so the client's chunked reader raises IncompleteRead.
        srv.kill()
        consumer.join(timeout=5.0)
        assert not consumer.is_alive(), "watch consumer hung after server death"
        assert errs == [], f"watch leaked an exception: {errs}"
    finally:
        watch.stop()


# --- kubeconfig resolution ---------------------------------------------------

def test_kubeconfig_parsing(tmp_path):
    cfg = tmp_path / "kubeconfig"
    cfg.write_text(
        """
apiVersion: v1
kind: Config
current-context: prod
contexts:
- name: prod
  context: {cluster: c1, user: u1}
clusters:
- name: c1
  cluster:
    server: https://k8s.example:6443
    insecure-skip-tls-verify: true
users:
- name: u1
  user:
    token: sekrit
"""
    )
    rc = k8sutil.get_cluster_config(kubeconfig_path=str(cfg))
    assert rc.host == "https://k8s.example:6443"
    assert rc.bearer_token == "sekrit"
    assert rc.insecure_skip_tls_verify is True


def test_master_url_override_wins(tmp_path, monkeypatch):
    monkeypatch.setenv("KUBECONFIG", "/does/not/exist")
    rc = k8sutil.get_cluster_config(master_url="http://127.0.0.1:8001")
    assert rc.host == "http://127.0.0.1:8001"


def test_no_config_raises(monkeypatch):
    monkeypatch.delenv("KUBECONFIG", raising=False)
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    with pytest.raises(k8sutil.ConfigError):
        k8sutil.get_cluster_config()


# --- leader election ---------------------------------------------------------

def test_leader_election_single_winner(api):
    _srv, cs = api
    a = LeaderElector(cs, "default", identity="a",
                      lease_duration=2.0, renew_deadline=0.2, retry_period=0.1)
    b = LeaderElector(cs, "default", identity="b",
                      lease_duration=2.0, renew_deadline=0.2, retry_period=0.1)
    assert a.try_acquire_or_renew() is True
    assert b.try_acquire_or_renew() is False  # live lease held by a
    assert a.try_acquire_or_renew() is True   # renewal succeeds


def test_leader_election_takeover_after_expiry(api):
    _srv, cs = api
    a = LeaderElector(cs, "default", identity="a", lease_duration=0.3)
    b = LeaderElector(cs, "default", identity="b", lease_duration=0.3)
    assert a.try_acquire_or_renew()
    time.sleep(0.5)  # a's lease expires
    assert b.try_acquire_or_renew() is True
    lease = cs.leases.get("default", "tpu-operator")
    assert lease["spec"]["holderIdentity"] == "b"
    assert lease["spec"]["leaseTransitions"] == 1


def test_leader_election_run_loop(api):
    _srv, cs = api
    elector = LeaderElector(cs, "default", identity="runner",
                            lease_duration=2.0, renew_deadline=0.1,
                            retry_period=0.1)
    led = threading.Event()
    stop = threading.Event()

    def leading(leading_stop):
        led.set()
        leading_stop.wait()

    th = threading.Thread(target=elector.run,
                          kwargs={"on_started_leading": leading,
                                  "stop_event": stop}, daemon=True)
    th.start()
    assert led.wait(5.0)
    assert elector.is_leader.is_set()
    stop.set()
    th.join(timeout=5.0)
    assert not th.is_alive()


def test_leader_election_survives_transient_api_blip(api):
    """One failed renew round must NOT drop leadership while the lease is
    still live (review finding: a single apiserver blip tore down the
    controller)."""
    _srv, cs = api

    class Flaky:
        def __init__(self, inner):
            self._inner = inner
            self.fail_next = 0

        def get(self, ns, name):
            if self.fail_next > 0:
                self.fail_next -= 1
                raise errors.ApiError(500, "InternalError", "blip")
            return self._inner.get(ns, name)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    cs.leases = Flaky(cs.leases)
    elector = LeaderElector(cs, "default", identity="flaky-leader",
                            lease_duration=3.0, renew_deadline=0.1,
                            retry_period=0.05)
    led = threading.Event()
    stop = threading.Event()
    th = threading.Thread(target=elector.run,
                          kwargs={"on_started_leading":
                                  lambda ls: (led.set(), ls.wait()),
                                  "stop_event": stop}, daemon=True)
    th.start()
    assert led.wait(5.0)
    cs.leases.fail_next = 3  # a few consecutive blips, < lease window
    time.sleep(1.0)
    assert elector.is_leader.is_set()  # leadership retained
    stop.set()
    th.join(timeout=5.0)


# --- chaos monkey ------------------------------------------------------------

def test_chaos_kills_only_managed_running_pods(api):
    _srv, cs = api
    cs.pods.create("default", {
        "metadata": {"name": "managed", "labels": {"tpuoperator.dev": ""}},
        "status": {"phase": "Running"}})
    cs.pods.create("default", {
        "metadata": {"name": "done", "labels": {"tpuoperator.dev": ""}},
        "status": {"phase": "Succeeded"}})
    cs.pods.create("default", {
        "metadata": {"name": "unmanaged"}, "status": {"phase": "Running"}})
    monkey = ChaosMonkey(cs, "default", level=5)
    assert monkey.kill_once() == 1
    names = sorted(p["metadata"]["name"] for p in cs.pods.list("default"))
    assert names == ["done", "unmanaged"]


# --- the full binary path ----------------------------------------------------

def test_server_run_end_to_end_over_http():
    """cmd.server.run with --master pointing at the in-process apiserver:
    leader election acquires the Lease, informers watch over real HTTP, a
    TPUJob created through the API becomes pods with injected env."""
    from tpu_operator.cmd.options import build_parser
    from tpu_operator.cmd import server

    with ApiServerHarness() as srv:
        opts = build_parser().parse_args([
            "--master", srv.url, "--namespace", "default",
            "--threadiness", "2", "--resync-period", "0",
            "--gc-interval", "3600", "--status-port", "0",
        ])
        stop = threading.Event()
        th = threading.Thread(target=server.run, args=(opts,),
                              kwargs={"stop_event": stop}, daemon=True)
        th.start()
        cs = Clientset(RestConfig(host=srv.url, timeout=5.0))
        try:
            # leader election ran against the real API
            def lease_held():
                try:
                    lease = cs.leases.get("default", "tpu-operator")
                except Exception:
                    return False
                return bool(lease["spec"]["holderIdentity"])
            assert wait_for(lease_held, timeout=10.0)
            cs.tpujobs.create("default", worker_job_dict("httpjob", replicas=2))
            assert wait_for(lambda: len(cs.pods.list("default")) == 2, timeout=10.0)
            pod = cs.pods.list("default")[0]
            env = {e["name"] for e in pod["spec"]["containers"][0]["env"]}
            assert "JAX_COORDINATOR_ADDRESS" in env
            assert wait_for(
                lambda: cs.tpujobs.get("default", "httpjob")
                .get("status", {}).get("phase") == "Creating", timeout=10.0)
        finally:
            stop.set()
            th.join(timeout=10.0)
        assert not th.is_alive()


# --- bounded retry with jittered backoff (client/rest.py) --------------------

import random as _random

from tpu_operator.client.rest import RestClient
from tpu_operator.controller.chaos import FlakyClientset
from tpu_operator.controller.statusserver import Metrics


def retrying_client(monkeypatch, outcomes, method="GET"):
    """RestClient whose wire layer plays back ``outcomes`` (exception
    instances or return values); returns (client, sleeps, calls)."""
    sleeps, calls = [], []
    client = RestClient(RestConfig(host="http://stub:1", max_retries=3,
                                   retry_base_delay=0.25,
                                   retry_max_delay=2.0),
                        metrics=Metrics(),
                        sleep=sleeps.append,
                        rng=_random.Random(42))
    script = list(outcomes)

    def fake_once(method_, path, body=None):
        calls.append(method_)
        outcome = script.pop(0)
        if isinstance(outcome, BaseException):
            raise outcome
        return outcome

    monkeypatch.setattr(client, "_request_once",
                        lambda m, p, b: fake_once(m, p, b))
    return client, sleeps, calls


def test_rest_retries_transient_500_then_succeeds(monkeypatch):
    client, sleeps, calls = retrying_client(monkeypatch, [
        errors.ApiError(500, message="boom"),
        ConnectionResetError("reset"),
        {"ok": True},
    ])
    assert client.request("GET", "/api/v1/pods") == {"ok": True}
    assert len(calls) == 3
    assert len(sleeps) == 2
    assert all(0 <= s <= 2.0 for s in sleeps)
    assert client.metrics.snapshot()["api_request_retries_total"] == 2


def test_rest_retry_honors_retry_after_on_429(monkeypatch):
    throttled = errors.ApiError(429, message="slow down")
    throttled.retry_after = 1.5
    client, sleeps, _calls = retrying_client(monkeypatch,
                                             [throttled, {"ok": 1}])
    assert client.request("GET", "/x") == {"ok": 1}
    assert sleeps == [1.5]  # server-directed, not jittered


def test_rest_retry_exhausts_budget(monkeypatch):
    client, sleeps, calls = retrying_client(
        monkeypatch, [errors.ApiError(503, message="down")] * 4)
    with pytest.raises(errors.ApiError) as exc:
        client.request("GET", "/x")
    assert exc.value.code == 503
    assert len(calls) == 4  # initial + max_retries
    assert len(sleeps) == 3


def test_rest_never_retries_non_idempotent_verbs(monkeypatch):
    for method in ("POST", "PUT"):
        client, sleeps, calls = retrying_client(
            monkeypatch, [errors.ApiError(500, message="boom")])
        with pytest.raises(errors.ApiError):
            client.request(method, "/x", body={"a": 1})
        assert len(calls) == 1 and sleeps == []


def test_rest_never_retries_permanent_errors(monkeypatch):
    for code in (404, 409, 410, 422):
        client, sleeps, calls = retrying_client(
            monkeypatch, [errors.ApiError(code, message="no")])
        with pytest.raises(errors.ApiError):
            client.request("GET", "/x")
        assert len(calls) == 1 and sleeps == []


def test_rest_retry_against_live_server_connection_refused():
    """The whole-path check: first attempts hit a dead port, the retry
    budget is spent, and the failure surfaces as the transport error."""
    sleeps = []
    client = RestClient(RestConfig(host="http://127.0.0.1:9", timeout=0.2,
                                   max_retries=2),
                        sleep=sleeps.append, rng=_random.Random(1))
    with pytest.raises(OSError):
        client.request("GET", "/api/v1/pods")
    assert len(sleeps) == 2


# --- FlakyClientset (API-level chaos) ----------------------------------------

def test_flaky_clientset_injects_and_passes_through():
    from tpu_operator.client.fake import FakeClientset

    metrics = Metrics()
    flaky = FlakyClientset(FakeClientset(), error_rate=0.5,
                           rng=_random.Random(0), metrics=metrics)
    outcomes = {"ok": 0, "fail": 0}
    codes = set()
    for i in range(200):
        try:
            flaky.pods.create("default", {"metadata": {"name": f"p{i}"}})
            outcomes["ok"] += 1
        except errors.ApiError as e:
            outcomes["fail"] += 1
            codes.add(e.code)
            assert "chaos: injected" in e.message
    # seeded rng: the split is deterministic and near the configured rate
    assert outcomes["fail"] == metrics.snapshot()["chaos_api_errors_total"]
    assert 60 <= outcomes["fail"] <= 140
    assert codes <= {429, 500}
    # successful calls really landed in the backing store
    assert len(flaky.pods.list("default") or []) >= 1 or outcomes["ok"] == 0


def test_flaky_clientset_zero_rate_is_transparent():
    from tpu_operator.client.fake import FakeClientset

    inner = FakeClientset()
    flaky = FlakyClientset(inner, error_rate=0.0)
    flaky.tpujobs.create("default", worker_job_dict("clean"))
    assert flaky.tpujobs.get("default", "clean")["metadata"]["name"] == "clean"
    # watch passes through untouched (same object protocol)
    w = flaky.pods.watch("default")
    w.stop()
    # non-resource attributes defer to the wrapped clientset
    assert flaky.actions is inner.actions
