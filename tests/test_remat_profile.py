"""Remat + profiler-hook tests (8-device CPU mesh).

jax.checkpoint must be semantics-preserving (identical loss with and
without --remat), and the --profile-dir hook must emit a TensorBoard/XProf
trace for the profiled step window.
"""

from __future__ import annotations

import glob
import os

import jax
import numpy as np

from tpu_operator.payload import pipeline, transformer


def _lm_argv(extra=()):
    return ["--batch", "4", "--seq-len", "64", "--dim", "32", "--heads", "2",
            "--layers", "2", "--seq-parallel", "4", *extra]


def test_remat_dots_attn_policy_loss_identical():
    """--remat-policy dots_attn (saves the flash kernel's named residuals)
    must be semantics-preserving vs no remat — same two-step loss to bf16
    wiggle — including GQA, whose kv-sized K/V ride the named residuals."""
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod

    mesh = transformer.make_lm_mesh(8, seq_parallel=4)
    losses = {}
    for label, extra in (("none", []),
                         ("dots_attn", ["--remat", "--remat-policy",
                                        "dots_attn"])):
        args = transformer.parse_args(_lm_argv(extra + ["--kv-heads", "2"]))
        _, _, state, step, batches = transformer.build(args, mesh=mesh)
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", "seq"))
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[label] = float(metrics["loss"])
    assert abs(losses["none"] - losses["dots_attn"]) < 5e-3, losses


def test_remat_dots_attn_gelu_policy_loss_identical():
    """--remat-policy dots_attn_gelu (additionally saves the named MLP
    gelu output) must also be semantics-preserving — a typo'd saved name
    or policy-composition regression would silently recompute or, worse,
    misassociate residuals. Also pins the shared models.remat_policy
    helper the pipeline/MoE builders consume."""
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod, models

    assert models.remat_policy("full") is None
    mesh = transformer.make_lm_mesh(8, seq_parallel=4)
    losses = {}
    for label, extra in (("none", []),
                         ("gelu", ["--remat", "--remat-policy",
                                   "dots_attn_gelu"])):
        args = transformer.parse_args(_lm_argv(extra))
        _, _, state, step, batches = transformer.build(args, mesh=mesh)
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens,
                                           spec=P("data", "seq"))
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[label] = float(metrics["loss"])
    assert abs(losses["none"] - losses["gelu"]) < 5e-3, losses


def test_remat_transformer_loss_identical():
    mesh = transformer.make_lm_mesh(8, seq_parallel=4)
    losses = {}
    for remat in (False, True):
        argv = _lm_argv(["--remat"] if remat else [])
        args = transformer.parse_args(argv)
        _, _, state, step, batches = transformer.build(args, mesh=mesh)

        from jax.sharding import PartitionSpec as P

        from tpu_operator.payload import data as data_mod

        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", "seq"))
        # two steps so the gradient path (where remat differs) feeds back
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[remat] = float(metrics["loss"])
    # bf16 blocks: remat recomputes in a different fusion order, so low
    # bits legitimately wiggle; semantics-equality is to bf16 precision.
    assert abs(losses[False] - losses[True]) < 5e-3, losses


def test_remat_pipeline_loss_identical():
    mesh = pipeline.make_pipe_mesh(8, pipeline=4)
    losses = {}
    for remat in (False, True):
        argv = ["--batch", "8", "--seq-len", "32", "--dim", "32", "--heads",
                "2", "--layers", "4", "--pipeline", "4", "--microbatches",
                "2", "--dtype", "f32"] + (["--remat"] if remat else [])
        args = pipeline.parse_args(argv)
        _, _, state, step, batches = pipeline.build(args, mesh=mesh)

        from tpu_operator.payload import data as data_mod

        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens)
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[remat] = float(metrics["loss"])
    assert abs(losses[False] - losses[True]) < 1e-5, losses


def test_profile_dir_emits_trace(tmp_path):
    from tpu_operator.payload import data as data_mod, linear, train

    args = linear.parse_args(["--steps", "15"])
    mesh = train.make_mesh(4)

    import optax

    from tpu_operator.payload import models

    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    import jax.numpy as jnp

    sample = jnp.zeros((args.batch, args.dim), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)
    batches = data_mod.synthetic_linear(0, args.batch, args.dim)
    prof = str(tmp_path / "prof")
    state, metrics = train.train_loop(mesh, step, state, batches, 15,
                                      profile_dir=prof,
                                      profile_range=(5, 10))
    assert np.isfinite(metrics["loss"])
    assert glob.glob(os.path.join(prof, "plugins", "profile", "*", "*.pb"))
