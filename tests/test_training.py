"""TrainingJob lifecycle tests.

Reference test model: pkg/trainer/training_test.go — exit-code tables
(:31-87), ClusterSpec naming (:89-184), setup/defaulting outcomes (:186-344)
— rebuilt to compile, plus the TPU-native gang/whole-group behaviors the
reference never had.
"""

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.client import errors
from tpu_operator.client.fake import FakeClientset
from tpu_operator.controller.events import EventRecorder
from tpu_operator.trainer import policy
from tpu_operator.trainer.training import TrainingJob
from tests.test_types import make_template


# --- exit-code contract tables (ref: training_test.go:31-87) -----------------

EXIT_CASES = [
    # (terminated_state, retryable, permanent, success)
    (None, False, False, False),
    ({"exitCode": 0}, False, False, True),
    ({"exitCode": 1}, False, True, False),
    ({"exitCode": 127}, False, True, False),
    ({"exitCode": 128}, True, False, False),
    ({"exitCode": 137}, True, False, False),
    ({"exitCode": 255}, True, False, False),
    # OOMKilled is never retryable, even with a "retryable" exit code
    # (ref: training.go:183-192)
    ({"exitCode": 137, "reason": "OOMKilled"}, False, True, False),
    ({"exitCode": 0, "reason": "OOMKilled"}, False, False, False),
]


@pytest.mark.parametrize("term,retryable,permanent,success", EXIT_CASES)
def test_exit_code_contract(term, retryable, permanent, success):
    assert policy.is_retryable_termination_state(term) is retryable
    assert policy.is_permanent_failure(term) is permanent
    assert policy.is_success(term) is success


# --- fixtures ----------------------------------------------------------------

def worker_job(replicas=2, name="train", max_restarts=3, backoff_base=0):
    # backoff_base 0: these lifecycle tests assert the *instant* re-gang
    # semantics; the time-aware backoff path has its own fake-clock tests
    # (test_time_recovery.py).
    return t.TPUJob(
        metadata={"name": name, "namespace": "default", "uid": "uid-9"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=replicas, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER)
            ],
            runtime_id="r1d2",
            max_restarts=max_restarts,
            restart_backoff=t.RestartBackoffSpec(base_seconds=backoff_base),
        ),
    )


def new_training_job(job=None):
    cs = FakeClientset()
    job = job or worker_job()
    cs.tpujobs.create(job.namespace, job.to_dict())
    recorder = EventRecorder(cs)
    return cs, TrainingJob(cs, recorder, job)


def set_container_state(cs, pod, phase, state=None, last_state=None):
    cstatus = {"name": "tpu"}
    if state is not None:
        cstatus["state"] = state
    if last_state is not None:
        cstatus["lastState"] = last_state
    pod["status"] = {"phase": phase, "containerStatuses": [cstatus]}
    cs.pods.update("default", pod)


def all_running(cs):
    for p in cs.pods.list("default"):
        set_container_state(cs, p, "Running", state={"running": {}})


# --- setup (ref: training_test.go:186-344) -----------------------------------

def test_setup_generates_runtime_id_and_phase():
    cs, tj = new_training_job()
    tj.job.spec.runtime_id = ""
    tj.setup()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    assert len(tj.job.spec.runtime_id) == 4
    assert tj.job.spec.termination_policy.chief_replica_name == "WORKER"


def test_setup_skipped_when_phase_set():
    # ref: training.go:220-223 — idempotent across operator restarts
    cs, tj = new_training_job()
    tj.job.status.phase = t.TPUJobPhase.RUNNING
    tj.job.spec.runtime_id = "keep"
    tj.setup()
    assert tj.job.spec.runtime_id == "keep"
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING


def test_setup_invalid_spec_fails_job_with_event():
    job = worker_job()
    job.spec.replica_specs[0].template = make_template(container_name="wrong")
    cs, tj = new_training_job(job)
    tj.setup()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "invalid job spec" in tj.job.status.reason
    events = cs.events.list("default")
    assert any(e["reason"] == "InvalidSpec" for e in events)


# --- cluster spec (ref: training_test.go:89-184) -----------------------------

def test_cluster_spec_names():
    _cs, tj = new_training_job()
    tj.setup()
    assert tj.cluster_spec() == {
        "worker": ["train-worker-r1d2-0:8476", "train-worker-r1d2-1:8476"]
    }


def test_cluster_spec_compat_roles():
    job = t.TPUJob(
        metadata={"name": "ps", "namespace": "default", "uid": "u"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=1, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.SCHEDULER),
                t.TPUReplicaSpec(replicas=2, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.SERVER),
                t.TPUReplicaSpec(replicas=2, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER),
            ],
            runtime_id="q7",
        ),
    )
    _cs, tj = new_training_job(job)
    tj.setup()
    spec = tj.cluster_spec()
    assert spec["scheduler"] == ["ps-scheduler-q7-0:8476"]
    assert spec["server"] == ["ps-server-q7-0:8476", "ps-server-q7-1:8476"]
    assert len(spec["worker"]) == 2


# --- reconcile lifecycle -----------------------------------------------------

def test_reconcile_creates_children_and_transitions():
    cs, tj = new_training_job()
    tj.reconcile()
    # services: 2 per-index + 1 headless; pods: 2 workers
    assert len(cs.services.list("default")) == 3
    assert len(cs.pods.list("default")) == 2
    assert tj.job.status.phase == t.TPUJobPhase.CREATING

    # pods come up → RUNNING
    all_running(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.state == t.State.RUNNING

    # CRD status was written back (ref: training.go:326-343)
    stored = cs.tpujobs.get("default", "train")
    assert stored["status"]["phase"] == t.TPUJobPhase.RUNNING
    assert stored["spec"]["runtimeId"] == "r1d2"


def test_reconcile_headless_service_spec():
    cs, tj = new_training_job()
    tj.reconcile()
    svc = cs.services.get("default", "train-r1d2")
    assert svc["spec"]["clusterIP"] == "None"
    assert svc["spec"]["selector"]["job_name"] == "train"


def test_reconcile_success_path():
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    # chief (worker 0) exits 0; others too
    for p in cs.pods.list("default"):
        set_container_state(cs, p, "Succeeded", state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE
    assert tj.job.status.state == t.State.SUCCEEDED
    # pods retained for kubectl logs (tf_job_design_doc.md:86)
    assert len(cs.pods.list("default")) == 2
    assert any(e["reason"] == "JobSucceeded" for e in cs.events.list("default"))


def test_reconcile_permanent_failure_fails_job():
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    victim = cs.pods.list("default")[0]
    set_container_state(cs, victim, "Failed", state={"terminated": {"exitCode": 1}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert tj.job.status.state == t.State.FAILED
    assert any(e["reason"] == "JobFailed" for e in cs.events.list("default"))


def test_reconcile_oom_never_retried():
    cs, tj = new_training_job()
    tj.reconcile()
    victim = cs.pods.list("default")[0]
    set_container_state(cs, victim, "Failed",
                        state={"terminated": {"exitCode": 137, "reason": "OOMKilled"}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert tj.job.status.attempt == 0  # no group restart burned


# --- whole-group restart (TPU-native) ----------------------------------------

def test_group_restart_on_retryable_death():
    cs, tj = new_training_job()
    tj.reconcile()
    gen0 = {p["metadata"]["name"] for p in cs.pods.list("default")}
    victim = cs.pods.list("default")[0]
    # preemption: SIGKILL → exit 137, no OOM
    set_container_state(cs, victim, "Failed", state={"terminated": {"exitCode": 137}})
    tj.reconcile()
    assert tj.job.status.attempt == 1
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    assert any(e["reason"] == "GroupRestart" for e in cs.events.list("default"))
    # old generation gone
    assert all(p["metadata"]["name"] not in gen0 for p in cs.pods.list("default"))

    # next reconcile creates attempt-1 pods for every index
    tj.reconcile()
    pods = cs.pods.list("default")
    assert len(pods) == 2
    assert all(p["metadata"]["labels"]["attempt"] == "1" for p in pods)
    # env reflects the attempt
    env = {e["name"]: e["value"]
           for e in pods[0]["spec"]["containers"][0]["env"]}
    assert env["TPUJOB_ATTEMPT"] == "1"


def test_group_restart_on_eviction_without_container_status():
    """Kubelet-level eviction (no containerStatuses at all) is routine TPU
    preemption and must burn a group restart, not fail the job."""
    cs, tj = new_training_job()
    tj.reconcile()
    victim = cs.pods.list("default")[0]
    victim["status"] = {"phase": "Failed", "reason": "Evicted",
                        "message": "node is being preempted"}
    cs.pods.update("default", victim)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    assert tj.job.status.attempt == 1


def test_permanent_failure_frees_live_pods():
    """A permanently-failed group must not strand the slice: still-running
    pods are deleted; terminated pods are kept for their logs."""
    cs, tj = new_training_job(worker_job(replicas=3))
    tj.reconcile()
    pods = cs.pods.list("default")
    set_container_state(cs, pods[0], "Failed", state={"terminated": {"exitCode": 1}})
    set_container_state(cs, pods[1], "Running", state={"running": {}})
    set_container_state(cs, pods[2], "Running", state={"running": {}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    remaining = cs.pods.list("default")
    assert len(remaining) == 1  # only the failed pod's logs survive
    assert remaining[0]["status"]["phase"] == "Failed"


def test_group_restart_budget_exhausted():
    # exit 139 (SIGSEGV): application-kind crash, billed to maxRestarts
    # (exit 137/143 are preemption-kind and draw the larger budget —
    # test_time_recovery.py covers that split).
    cs, tj = new_training_job(worker_job(max_restarts=1))
    tj.reconcile()
    for round_ in range(2):
        victim = cs.pods.list("default")[0]
        set_container_state(cs, victim, "Failed",
                            state={"terminated": {"exitCode": 139}})
        tj.reconcile()
        tj.reconcile()  # recreate next generation if restarted
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "retry budget exhausted" in tj.job.status.reason
    # the classification ledger recorded both application-kind failures
    assert [f.kind for f in tj.job.status.failures] == ["application"] * 2


def test_per_pod_mode_no_group_restart():
    # compat spec: retryable failure handled by pod recreation, not teardown
    job = t.TPUJob(
        metadata={"name": "ps", "namespace": "default", "uid": "u"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=1, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.SCHEDULER),
                t.TPUReplicaSpec(replicas=2, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER),
            ],
            runtime_id="q7",
        ),
    )
    cs, tj = new_training_job(job)
    tj.reconcile()
    assert tj.job.spec.restart_policy == t.RestartPolicy.PER_POD
    n_before = len(cs.pods.list("default"))
    victim = next(p for p in cs.pods.list("default")
                  if p["metadata"]["labels"]["job_type"] == "worker")
    victim["status"] = {"phase": "Failed"}
    cs.pods.update("default", victim)
    tj.reconcile()
    assert tj.job.status.attempt == 0
    assert len(cs.pods.list("default")) == n_before + 1  # replacement created


def test_refresh_keeps_in_memory_status_over_stale_cache():
    """Regression: the informer cache lags the operator's own status writes;
    refresh() must not regress the attempt counter or phase (found by
    driving the live control loop — group restart raced back to attempt 0)."""
    cs, tj = new_training_job()
    tj.reconcile()
    victim = cs.pods.list("default")[0]
    set_container_state(cs, victim, "Failed", state={"terminated": {"exitCode": 137}})
    tj.reconcile()
    assert tj.job.status.attempt == 1

    # Stale cached copy: status from before the restart, spec from before setup
    stale = worker_job()
    stale.spec.runtime_id = ""
    stale.status.attempt = 0
    stale.status.phase = t.TPUJobPhase.RUNNING
    tj.refresh(stale)
    assert tj.job.status.attempt == 1          # in-memory status kept
    assert tj.job.spec.runtime_id == "r1d2"    # stale empty runtimeId repaired
    assert tj.job.spec.restart_policy == t.RestartPolicy.WHOLE_GROUP  # defaults re-applied
    tj.reconcile()  # must create attempt-1 generation, not resurrect attempt 0
    pods = cs.pods.list("default", label_selector="job_name=train,attempt=1")
    assert len(pods) == 2


# --- gang creation -----------------------------------------------------------

class QuotaLimitedPods:
    """Wraps the fake pods client to fail after N creates (simulates a full
    TPU slice / quota rejection)."""

    def __init__(self, inner, allow):
        self._inner = inner
        self._allow = allow

    def create(self, namespace, obj):
        if self._allow <= 0:
            raise errors.ApiError(403, "Forbidden", "quota exceeded")
        self._allow -= 1
        return self._inner.create(namespace, obj)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_gang_create_rolls_back_partial_generation():
    cs, tj = new_training_job(worker_job(replicas=4))
    cs.pods = QuotaLimitedPods(cs.pods, allow=2)
    with pytest.raises(errors.ApiError):
        tj.reconcile()
    # nothing stranded: the two created pods were rolled back
    assert cs.pods.list("default") == []
    assert any(e["reason"] == "GangCreateFailed" for e in cs.events.list("default"))


# --- delete (ref: training.go:305-323) ---------------------------------------

def test_delete_removes_children_and_marks_done():
    cs, tj = new_training_job()
    tj.reconcile()
    assert cs.pods.list("default")
    tj.delete()
    assert cs.pods.list("default") == []
    assert cs.services.list("default") == []
    assert tj.job.status.phase == t.TPUJobPhase.DONE


def test_reconcile_cleanup_phase_deletes_then_done():
    cs, tj = new_training_job()
    tj.reconcile()
    tj.job.status.phase = t.TPUJobPhase.CLEANUP
    tj.reconcile()
    assert cs.pods.list("default") == []
    assert tj.job.status.phase == t.TPUJobPhase.DONE


# --- suspend / resume (TPU-native; batch/v1 Job semantics) -------------------

def test_suspend_tears_down_generation_and_parks():
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert len(cs.pods.list("default")) == 2

    tj.job.spec.suspend = True
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.SUSPENDED
    assert tj.job.status.reason == "suspended by spec"
    assert cs.pods.list("default") == []  # slice freed
    assert any(e["reason"] == "JobSuspended" for e in cs.events.list("default"))
    # idempotent while parked: no pods reappear, no repeat events
    n_events = len(cs.events.list("default"))
    tj.reconcile()
    assert cs.pods.list("default") == []
    assert len(cs.events.list("default")) == n_events
    # attempt (the retry budget counter) is untouched
    assert tj.job.status.attempt == 0


def test_resume_regangs_same_attempt_to_completion():
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    tj.job.spec.suspend = True
    tj.reconcile()
    assert cs.pods.list("default") == []

    tj.job.spec.suspend = False
    tj.reconcile()
    pods = cs.pods.list("default")
    assert len(pods) == 2
    # same attempt: no retry budget spent, payload resumes from checkpoint
    assert all(p["metadata"]["labels"]["attempt"] == "0" for p in pods)
    assert any(e["reason"] == "JobResumed" for e in cs.events.list("default"))
    assert tj.job.status.phase in (t.TPUJobPhase.CREATING,
                                   t.TPUJobPhase.RUNNING)

    all_running(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    for p in cs.pods.list("default"):
        set_container_state(cs, p, "Succeeded",
                            state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE


def test_job_created_suspended_never_creates_pods():
    job = worker_job()
    job.spec.suspend = True
    cs, tj = new_training_job(job)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.SUSPENDED
    assert cs.pods.list("default") == []


def test_suspend_does_not_touch_terminal_jobs():
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    for p in cs.pods.list("default"):
        set_container_state(cs, p, "Succeeded",
                            state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE
    tj.job.spec.suspend = True
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE


def test_suspend_roundtrips_through_wire_format():
    job = worker_job()
    job.spec.suspend = True
    wire = job.to_dict()
    assert wire["spec"]["suspend"] is True
    assert t.TPUJob.from_dict(wire).spec.suspend is True
    # default: absent from the wire, parsed false
    job2 = worker_job()
    assert "suspend" not in job2.to_dict()["spec"]
    assert t.TPUJob.from_dict(job2.to_dict()).spec.suspend is False


def test_suspend_retains_terminated_pods_and_their_verdict():
    """Chief already exited 0 but the controller had not rolled it up when
    the user suspended: terminated pods are retained (logs + verdict), and
    resume rolls straight to Done instead of re-running the finished job."""
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    for p in cs.pods.list("default"):
        set_container_state(cs, p, "Succeeded",
                            state={"terminated": {"exitCode": 0}})
    tj.job.spec.suspend = True
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.SUSPENDED
    assert len(cs.pods.list("default")) == 2  # terminated pods kept

    tj.job.spec.suspend = False
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE
    assert len(cs.pods.list("default")) == 2  # nothing re-ran


def test_suspend_survives_operator_restart():
    """Operator dies while a job is parked: the NEW operator's TrainingJob
    (rebuilt from the persisted CRD, reference-style UID-keyed resume) must
    keep the job parked, and a later resume still works."""
    cs, tj = new_training_job()
    tj.reconcile()
    all_running(cs)
    # the user suspends via the apiserver (as the e2e tier does); the
    # in-memory copy follows the same edit, as refresh() would
    wire = cs.tpujobs.get("default", "train")
    wire["spec"]["suspend"] = True
    cs.tpujobs.update("default", wire)
    tj.job.spec.suspend = True
    tj.reconcile()
    assert cs.pods.list("default") == []

    # "restart": a fresh TrainingJob from the apiserver's copy of the job
    wire = cs.tpujobs.get("default", "train")
    revived = TrainingJob(cs, EventRecorder(cs),
                          t.TPUJob.from_dict(wire))
    assert revived.job.spec.suspend is True
    assert revived.job.status.phase == t.TPUJobPhase.SUSPENDED
    revived.reconcile()
    assert cs.pods.list("default") == []  # still parked

    revived.job.spec.suspend = False
    revived.reconcile()
    assert len(cs.pods.list("default")) == 2
    assert all(p["metadata"]["labels"]["attempt"] == "0"
               for p in cs.pods.list("default"))
