"""Flash kernels (interpret mode) under sharded meshes, pinned to the jnp
path.

The dryrun's `(interpret pallas)` configs prove the kernel path compiles
and executes inside the seq ring and the PP x TP schedule; these tests
add the parity half: at identical configs and seeds, the forced-kernel
run must produce the same first-step loss as the jnp fallback, up to the
kernels' documented bf16-P·V rounding. A wrong mask, merge order, or
kernel-vs-shard offset shifts the loss by O(1) and fails loudly here.

TPU_OPERATOR_PALLAS is read at trace time, so each setting builds its own
payload (fresh jit) — flipping the env between steps of one compiled
step function would silently reuse the old path.
"""

import os

import numpy as np

import jax
from jax.sharding import PartitionSpec as P

from tpu_operator.payload import data as data_mod
from tpu_operator.payload import pipeline, transformer


def _first_step_loss(module, argv, mesh_kwargs, spec, pallas: bool) -> float:
    old = os.environ.get("TPU_OPERATOR_PALLAS")
    os.environ["TPU_OPERATOR_PALLAS"] = "force" if pallas else "off"
    try:
        args = module.parse_args(argv)
        if module is transformer:
            mesh = transformer.make_lm_mesh(8, **mesh_kwargs)
        else:
            mesh = pipeline.make_pipe_mesh(8, **mesh_kwargs)
        mesh, _m, state, step, batches = module.build(args, mesh=mesh)
        arrays = data_mod.put_global_batch(mesh, *next(batches), spec=spec)
        _state, metrics = step(state, *arrays)
        return float(jax.device_get(metrics["loss"]))
    finally:
        if old is None:
            del os.environ["TPU_OPERATOR_PALLAS"]
        else:
            os.environ["TPU_OPERATOR_PALLAS"] = old


def test_interpret_pallas_matches_jnp_in_seq_ring():
    """Ring attention over a (data, seq) mesh: merge_kv_block runs as the
    Pallas kernel inside the shard_map ppermute ring."""
    argv = ["--batch", "8", "--seq-len", "128", "--dim", "32",
            "--heads", "2", "--layers", "1", "--seq-parallel", "2"]
    kw = dict(seq_parallel=2)
    ref = _first_step_loss(transformer, argv, kw, P("data", "seq"), False)
    got = _first_step_loss(transformer, argv, kw, P("data", "seq"), True)
    assert np.isfinite(got)
    assert abs(got - ref) < 0.02, (got, ref)


def test_interpret_pallas_matches_jnp_in_pp_tp():
    """PP x TP 1F1B: the fused forward/backward kernels under GSPMD
    `model` partitioning inside the hand-scheduled ticks."""
    argv = ["--batch", "4", "--seq-len", "64", "--dim", "32",
            "--heads", "2", "--layers", "4", "--pipeline", "2",
            "--tensor-parallel", "2", "--microbatches", "2",
            "--schedule", "1f1b"]
    kw = dict(pipeline=2, tensor_parallel=2)
    ref = _first_step_loss(pipeline, argv, kw, None, False)
    got = _first_step_loss(pipeline, argv, kw, None, True)
    assert np.isfinite(got)
    assert abs(got - ref) < 0.02, (got, ref)


def test_interpret_pallas_matches_jnp_gqa_ring():
    """GQA (kv_heads < heads) over the striped seq ring — grouped-KV
    kernel blocks rotating with strided global positions."""
    argv = ["--batch", "8", "--seq-len", "128", "--dim", "32",
            "--heads", "4", "--kv-heads", "2", "--layers", "1",
            "--seq-parallel", "2", "--sp-layout", "striped"]
    kw = dict(seq_parallel=2)
    ref = _first_step_loss(transformer, argv, kw, P("data", "seq"), False)
    got = _first_step_loss(transformer, argv, kw, P("data", "seq"), True)
    assert np.isfinite(got)
    assert abs(got - ref) < 0.02, (got, ref)
