"""Lockdep witness tests: inversion detection with both stacks, reentrant
and self-deadlock handling, Condition.wait release semantics, the
violation ledger, and the disabled-mode zero-overhead contract."""

import threading

import pytest

from tpu_operator.util import lockdep


@pytest.fixture(autouse=True)
def _fresh_graph():
    """Each test gets a clean order graph; the suite-level witness state
    is not meaningful across unrelated scenarios."""
    lockdep.reset()
    yield
    lockdep.reset()


def test_inversion_detected_with_both_stacks():
    a = lockdep.lock("test.A")
    b = lockdep.lock("test.B")

    def forward():
        with a:
            with b:
                pass

    forward()  # witnesses A -> B
    with pytest.raises(lockdep.LockOrderError) as exc:
        with b:
            with a:  # closes the cycle
                pass
    report = str(exc.value)
    # The splat names both locks and carries BOTH acquisition stacks:
    # the inverting one and the prior witness.
    assert "test.A" in report and "test.B" in report
    assert "this acquisition" in report
    assert "prior witness" in report
    # Both stacks point at real source lines in this test.
    assert report.count("test_lockdep.py") >= 2
    assert lockdep.violation_count() == 1


def test_inversion_detected_across_threads():
    a = lockdep.lock("test.A")
    b = lockdep.lock("test.B")
    errors = []

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()

    def t2():
        try:
            with b:
                with a:
                    pass
        except lockdep.LockOrderError as e:
            errors.append(e)

    th = threading.Thread(target=t2)
    th.start()
    th.join()
    assert len(errors) == 1
    assert lockdep.violation_count() == 1


def test_inversion_unwinds_the_inner_lock():
    """acquire() raising from a `with` statement must not leave the lock
    held — __exit__ never runs for a failed __enter__."""
    a = lockdep.lock("test.A")
    b = lockdep.lock("test.B")
    with a:
        with b:
            pass
    with pytest.raises(lockdep.LockOrderError):
        with b:
            with a:
                pass
    # The failed acquisition released `a`: it is immediately takeable.
    assert a.acquire(blocking=False)
    a.release()
    assert lockdep.held_keys() == []


def test_transitive_cycle_through_three_locks():
    a, b, c = (lockdep.lock(f"test.{n}") for n in "ABC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(lockdep.LockOrderError) as exc:
        with c:
            with a:
                pass
    assert "test.B" in str(exc.value)  # the path runs through B


def test_consistent_order_never_flags():
    a = lockdep.lock("test.A")
    b = lockdep.lock("test.B")
    for _ in range(100):
        with a:
            with b:
                pass
    assert lockdep.violation_count() == 0
    assert ("test.A", "test.B") in lockdep.edges()


def test_rlock_reentrancy_is_not_an_edge():
    r = lockdep.rlock("test.R")
    with r:
        with r:
            assert lockdep.held_keys() == ["test.R"]
    assert lockdep.violation_count() == 0
    assert lockdep.edges() == []


def test_plain_lock_self_deadlock_raises_immediately():
    a = lockdep.lock("test.A")
    with a:
        with pytest.raises(lockdep.LockOrderError, match="self-deadlock"):
            a.acquire()
    assert lockdep.violation_count() == 1
    lockdep.reset()  # the guard fixture must not double-count this one


def test_same_key_different_instances_flagged():
    """Two instances of one lock class nested have no defined order —
    two threads nesting them oppositely deadlock, so it reports."""
    a1 = lockdep.lock("test.Same")
    a2 = lockdep.lock("test.Same")
    with pytest.raises(lockdep.LockOrderError):
        with a1:
            with a2:
                pass


def test_condition_wait_releases_for_order_purposes():
    cond = lockdep.condition("test.C")
    entered = threading.Event()
    release = threading.Event()
    held_during_wait = []

    def waiter():
        with cond:
            entered.set()
            cond.wait(timeout=5.0)
            held_during_wait.append(list(lockdep.held_keys()))

    th = threading.Thread(target=waiter)
    th.start()
    assert entered.wait(5.0)
    # While the waiter is parked in wait(), the lock is acquirable —
    # proof the witness (and the real lock) released it.
    acquired = cond.acquire(timeout=5.0)
    assert acquired
    assert lockdep.held_keys() == ["test.C"]
    cond.notify_all()
    cond.release()
    assert lockdep.held_keys() == []
    th.join(timeout=5.0)
    assert not th.is_alive()
    # After re-acquiring out of wait(), the waiter held exactly the cond.
    assert held_during_wait == [["test.C"]]
    assert lockdep.violation_count() == 0
    release.set()


def test_condition_ordering_edges_recorded():
    outer = lockdep.lock("test.Outer")
    cond = lockdep.condition("test.Cond")
    with outer:
        with cond:
            cond.notify_all()
    assert ("test.Outer", "test.Cond") in lockdep.edges()
    with pytest.raises(lockdep.LockOrderError):
        with cond:
            with outer:
                pass


def test_disabled_mode_returns_raw_primitives():
    """The zero-overhead contract: disabled factories hand back the raw
    threading objects — not wrappers with a cheap fast path, NO wrapper
    at all."""
    lockdep.disable_for_test = None  # readability marker only
    lockdep.enable(False)
    try:
        raw = lockdep.lock("test.X")
        assert type(raw) is type(threading.Lock())
        rr = lockdep.rlock("test.Y")
        assert type(rr) is type(threading.RLock())
        rc = lockdep.condition("test.Z")
        assert isinstance(rc, threading.Condition)
        assert type(rc._lock) is type(threading.RLock())
        # And nothing they do is witnessed.
        with raw:
            with rr:
                pass
        assert lockdep.edges() == []
    finally:
        lockdep.enable(True)


def test_violations_accumulate_for_the_conftest_guard():
    a = lockdep.lock("test.A")
    b = lockdep.lock("test.B")
    with a:
        with b:
            pass
    try:
        with b:
            with a:
                pass
    except lockdep.LockOrderError:
        pass
    assert lockdep.violation_count() == 1
    assert "inversion" in lockdep.report()
    lockdep.reset()
    assert lockdep.violation_count() == 0
    assert "no lock-order violations" in lockdep.report()
