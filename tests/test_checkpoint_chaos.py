"""Checkpoint durability chaos: kill -9 mid-save + corrupted latest
checkpoint → the job resumes from the last VERIFIED step — never step 0,
never permanent Failed — and reaches DONE, with the durable step visible in
job status and the restore-fallback counter incremented.

The operator (informers → workqueue → reconcile) runs in-process against
the HTTP test apiserver; the payload is a REAL subprocess
(tests/checkpoint_chaos_worker.py) driven by exactly the env the operator
injected into the pod spec, posting heartbeats through the real status
server. The test plays kubelet:

1. attempt 0's pod goes Running; the worker trains 6 steps with verified
   interval saves, reports ``lastCheckpointStep=6``, kicks off one more
   async save and is SIGKILLed while it is (or was about to be) writing;
2. the chaos (seeded) then makes the on-disk state maximally hostile:
   whatever the killed save left behind is replaced with a *corrupt*
   latest step 8 (copy of step 6 with flipped bytes under an honest
   manifest) plus an orphaned tmp dir from a second phantom killed save;
3. the pod is marked Failed with exit 137 → classified preemption → the
   ledger records the restart with ``resumeStep`` = the durable step 6;
4. attempt 1's worker restores: quarantines the corrupt 8, walks back to
   6, finishes the remaining steps, exits 0 → job DONE.

Runs standalone as a hack/verify.sh gate (marked slow: two subprocess JAX
payloads make it too heavy for the tier-1 sweep).
"""

import os
import random
import re
import shutil
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from tpu_operator.apis.tpujob.v1alpha1.types import ControllerConfig
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.payload import checkpoint as ckpt_mod
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for

pytestmark = pytest.mark.slow  # standalone verify.sh gate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "checkpoint_chaos_worker.py")

KILL_STEP = 6
TOTAL_STEPS = 10


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=60.0, interval=0.05)


def chaos_job(ckdir):
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "ckdur", "namespace": "default"},
        "spec": {
            "replicaSpecs": [{
                "replicas": 1, "tpuReplicaType": "WORKER", "tpuPort": 8476,
                "template": {"spec": {"containers": [{"name": "tpu"}]}},
            }],
            "maxRestarts": 2,
            "checkpointDir": ckdir,
            # Instant re-gang: backoff pacing has its own soak test.
            "restartBackoff": {"baseSeconds": 0},
        },
    }


def pod_env(pod):
    """The operator's injected env contract, straight off the pod spec —
    the worker consumes exactly what a real container would."""
    (container,) = [c for c in pod["spec"]["containers"]
                    if c["name"] == "tpu"]
    return {e["name"]: e["value"] for e in container.get("env", [])}


def launch_worker(pod, mode, sentinel=""):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.update(pod_env(pod))
    env.update({
        "CHAOS_MODE": mode,
        "CHAOS_KILL_STEP": str(KILL_STEP),
        "CHAOS_TOTAL_STEPS": str(TOTAL_STEPS),
        "CHAOS_SENTINEL": sentinel,
        # Fast heartbeat cadence so the in-loop reporter fires too.
        "TPUJOB_HEARTBEAT_INTERVAL": "0.2",
    })
    return subprocess.Popen(
        [sys.executable, WORKER], stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, env=env, cwd=REPO)


def corrupt_latest(ckdir, rng):
    """Seeded post-kill hostility: replace whatever the killed save left
    with a deterministic corrupt latest (step 8 = copy of the verified 6
    with flipped bytes, so its manifest honestly mismatches) plus an
    orphaned tmp dir from a second phantom killed save."""
    for entry in os.listdir(ckdir):
        if entry.split(".")[0] == str(KILL_STEP + 2):
            path = os.path.join(ckdir, entry)
            shutil.rmtree(path, ignore_errors=True)
    good = os.path.join(ckdir, str(KILL_STEP))
    bad = os.path.join(ckdir, str(KILL_STEP + 2))
    shutil.copytree(good, bad)
    victims = sorted(
        os.path.join(root, fn)
        for root, _dirs, files in os.walk(bad) for fn in files
        if fn != ckpt_mod.MANIFEST_NAME and os.path.getsize(
            os.path.join(root, fn)) > 0)
    victim = rng.choice(victims)
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(rng.randrange(size))
        f.write(b"\xde\xad")
    orphan = os.path.join(ckdir, f"{KILL_STEP + 4}.orbax-checkpoint-tmp-7")
    os.makedirs(os.path.join(orphan, "default"))
    with open(os.path.join(orphan, "default", "data"), "wb") as f:
        f.write(b"half-written by a killed save")


def test_kill9_midsave_and_corrupt_latest_resumes_from_verified_step(
        tmp_path):
    rng = random.Random(20260803)
    ckdir = str(tmp_path / "ckpt")
    sentinel = str(tmp_path / "ready0")

    harness = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=harness.url, timeout=5.0))
    server = StatusServer(0)
    server.start()
    config = ControllerConfig(status_url=f"http://127.0.0.1:{server.port}")
    controller = Controller(
        cs, SharedInformerFactory(cs, "default", resync_period=1.0),
        config=config, namespace="default",
        heartbeat_persist_interval=0.0)
    server.metrics = controller.metrics
    server.set_controller(controller)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True, name="ckdur-controller")
    runner.start()

    procs = []

    def get_pod(attempt):
        for p in cs.pods.list("default"):
            if (p["metadata"].get("labels") or {}).get("attempt") \
                    == str(attempt):
                return p
        return None

    def mark_running(pod):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        cs.pods.update_status("default", pod)

    def mark_terminated(pod, exit_code):
        pod["status"] = {
            "phase": "Failed" if exit_code else "Succeeded",
            "containerStatuses": [{
                "name": "tpu",
                "state": {"terminated": {"exitCode": exit_code}}}],
        }
        cs.pods.update_status("default", pod)

    def job_status():
        try:
            return cs.tpujobs.get("default", "ckdur").get("status") or {}
        except Exception:  # noqa: BLE001 — polling
            return {}

    try:
        cs.tpujobs.create("default", chaos_job(ckdir))

        # --- attempt 0: train, verify saves, die by SIGKILL mid-save ------
        assert wait_for(lambda: get_pod(0) is not None), "no attempt-0 pod"
        pod0 = get_pod(0)
        mark_running(pod0)
        proc0 = launch_worker(pod0, "killed", sentinel=sentinel)
        procs.append(proc0)
        assert wait_for(lambda: os.path.exists(sentinel), timeout=120.0), \
            proc0.communicate()[0] if proc0.poll() is not None else \
            "worker 0 never reached the kill point"
        proc0.send_signal(signal.SIGKILL)
        proc0.wait(timeout=30)

        # the durable step was reported before death
        assert wait_for(lambda: (job_status().get("checkpoint") or {})
                        .get("lastCheckpointStep") == KILL_STEP), \
            job_status()

        # --- seeded chaos: corrupt the latest checkpoint ------------------
        corrupt_latest(ckdir, rng)

        mark_terminated(get_pod(0), 137)  # kubelet reports the SIGKILL

        # preemption-classified group restart with the resume step recorded
        assert wait_for(lambda: job_status().get("attempt", 0) >= 1), \
            job_status()
        failures = job_status().get("failures") or []
        assert failures and failures[0]["kind"] == "preemption", failures
        assert failures[0]["resumeStep"] == KILL_STEP, failures

        # --- attempt 1: restore past the corruption, finish ---------------
        assert wait_for(lambda: get_pod(1) is not None), "no attempt-1 pod"
        pod1 = get_pod(1)
        mark_running(pod1)
        proc1 = launch_worker(pod1, "finish")
        procs.append(proc1)
        out1, _ = proc1.communicate(timeout=180)
        assert proc1.returncode == 0, f"exit {proc1.returncode}:\n{out1}"

        # resumed from the last VERIFIED step — never step 0
        m = re.search(r"restored checkpoint step (\d+)", out1)
        assert m, out1
        assert int(m.group(1)) == KILL_STEP, out1
        assert "restarting from step 0" not in out1

        mark_terminated(get_pod(1), 0)
        assert wait_for(lambda: job_status().get("phase") == "Done",
                        timeout=60.0), job_status()

        status = job_status()
        assert status["state"] == "Succeeded"
        assert status["attempt"] == 1

        # durable state visible in job status: final step, fallback counted
        ck = status.get("checkpoint") or {}
        assert ck.get("lastCheckpointStep") == TOTAL_STEPS, status
        assert ck.get("restoreFallbacks", 0) >= 1, status

        # the corrupt latest was quarantined, not deleted; the orphan swept
        entries = os.listdir(ckdir)
        assert any(e.startswith(f"{KILL_STEP + 2}"
                                f"{ckpt_mod.QUARANTINE_SUFFIX}")
                   for e in entries), entries
        assert any(e.endswith(ckpt_mod.ORPHAN_SUFFIX) for e in entries), \
            entries

        # and the operator exports it
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5) as r:
            body = r.read().decode()
        assert re.search(
            r'tpu_operator_job_checkpoint_restore_fallbacks_total'
            r'\{name="ckdur",namespace="default"\} [1-9]', body), body
        assert ('tpu_operator_job_last_checkpoint_step'
                f'{{name="ckdur",namespace="default"}} {TOTAL_STEPS}'
                in body), body
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        stop.set()
        runner.join(timeout=10.0)
        server.stop()
        harness.stop()
