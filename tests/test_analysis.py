"""Static-analysis suite tests: each rule against a fixture tree with a
seeded violation (exact file:line findings asserted), a clean run on the
real tree, the add-a-spec-field drift demo, and regression tests for the
defects the analyzers surfaced in this repo (dead env vars, the silently
swallowed event-aggregation failure, clientset RPCs under the recorder
lock)."""

import logging
import textwrap
import threading
import types as _types
from pathlib import Path

import pytest

from tpu_operator.analysis import concurrency, env_contract, escape, \
    exception_policy, lock_order, payload_image, spec_drift, status_contract
from tpu_operator.analysis.driver import RULES, run_analysis

REPO = Path(__file__).resolve().parent.parent


def write(root: Path, relpath: str, body: str) -> Path:
    path = root / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return path


def keyed(findings):
    return {f.key: f for f in findings}


# --- fixture trees: one seeded violation per rule ----------------------------

def test_spec_drift_fixture(tmp_path):
    write(tmp_path, spec_drift.TYPES, """\
        class TPUJobSpec:
            @classmethod
            def from_dict(cls, d):
                return cls(
                    old_field=d.get("oldField"),
                    new_field=d.get("newField"),
                )
        """)
    write(tmp_path, spec_drift.SCHEMA, """\
        def _obj(properties, required=()):
            return {"type": "object", "properties": properties}


        def spec_schema():
            return _obj({
                "oldField": {"type": "string"},
                "ghostField": {"type": "string"},
            })
        """)
    write(tmp_path, spec_drift.DEFAULTS, "# handles old_field only\n")
    write(tmp_path, spec_drift.VALIDATION, "# checks old_field only\n")
    found = keyed(spec_drift.run(tmp_path))
    # newField: parsed by from_dict, missing from schema AND both handlers
    assert found["schema:newField"].line == 6
    assert found["schema:newField"].path == spec_drift.TYPES
    assert "defaults:newField" in found
    assert "validation:newField" in found
    # ghostField: schema property with no wire key behind it
    assert found["types:ghostField"].path == spec_drift.SCHEMA
    assert found["types:ghostField"].line == 8
    # oldField is fully covered — no findings about it
    assert not any(k.endswith(":oldField") for k in found)


def test_spec_drift_catches_field_added_to_real_types(tmp_path):
    """Acceptance demo: adding a field to the REAL types.py without touching
    schema/defaults/validation reproducibly fails the spec-drift rule."""
    for relpath in (spec_drift.TYPES, spec_drift.SCHEMA,
                    spec_drift.DEFAULTS, spec_drift.VALIDATION):
        dst = tmp_path / relpath
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text((REPO / relpath).read_text())
    # Faithful copy: only the repo's standing (allowlisted) findings.
    before = set(keyed(spec_drift.run(tmp_path)))
    assert not any("shinyNewField" in k for k in before)

    types_path = tmp_path / spec_drift.TYPES
    src = types_path.read_text()
    marker = "            suspend=bool(d.get(\"suspend\", False)),"
    assert marker in src
    types_path.write_text(src.replace(
        marker, marker + "\n            shiny=bool(d.get(\"shinyNewField\", False)),"))
    found = set(keyed(spec_drift.run(tmp_path)))
    assert found - before == {"schema:shinyNewField",
                              "defaults:shinyNewField",
                              "validation:shinyNewField"}


def test_env_contract_fixture(tmp_path):
    write(tmp_path, env_contract.INJECTOR, """\
        def build_replica_env():
            env = {
                "TPUJOB_DEAD": "1",
                "TPUJOB_USED": "1",
            }
            env["TPUJOB_SUBSCRIPTED"] = "x"
            return env
        """)
    write(tmp_path, "tpu_operator/payload/consumer.py", """\
        import os


        def read():
            return (os.environ.get("TPUJOB_USED"),
                    os.environ.get("TPUJOB_SUBSCRIPTED"),
                    os.environ.get("TPUJOB_ORPHAN_READ"))
        """)
    found = keyed(env_contract.run(tmp_path))
    dead = found["injected-unread:TPUJOB_DEAD"]
    assert (dead.path, dead.line) == (env_contract.INJECTOR, 3)
    orphan = found["read-uninjected:TPUJOB_ORPHAN_READ"]
    assert (orphan.path, orphan.line) == \
        ("tpu_operator/payload/consumer.py", 7)
    assert len(found) == 2  # the used/subscripted vars are clean


def test_env_contract_docstring_mention_is_not_a_read(tmp_path):
    write(tmp_path, env_contract.INJECTOR, """\
        def build_replica_env():
            env = {"TPUJOB_ONLY_IN_DOCSTRING": "1"}
            return env
        """)
    write(tmp_path, "tpu_operator/payload/consumer.py", '''\
        """This module documents TPUJOB_ONLY_IN_DOCSTRING but never reads it."""
        ''')
    found = keyed(env_contract.run(tmp_path))
    assert "injected-unread:TPUJOB_ONLY_IN_DOCSTRING" in found


def test_status_contract_fixture(tmp_path):
    write(tmp_path, status_contract.HEARTBEAT, """\
        def report():
            body = {
                "namespace": "x",
                "name": "y",
                "step": 1,
                "mystery": 2,
            }
            return body
        """)
    write(tmp_path, status_contract.STATUSSERVER, """\
        def record_heartbeat(body):
            hb = {"time": "t"}
            hb["step"] = body.get("step")
            hb["ghost"] = 1
            return hb
        """)
    write(tmp_path, status_contract.SCHEMA, """\
        def _obj(properties):
            return {"type": "object", "properties": properties}


        def status_schema():
            return _obj({
                "lastHeartbeat": _obj({
                    "step": {"type": "integer"},
                    "time": {"type": "string"},
                }),
            })
        """)
    found = keyed(status_contract.run(tmp_path))
    mystery = found["posted-unsanitized:mystery"]
    assert (mystery.path, mystery.line) == (status_contract.HEARTBEAT, 6)
    ghost = found["sanitized-unschema:ghost"]
    assert (ghost.path, ghost.line) == (status_contract.STATUSSERVER, 4)
    # namespace/name are the routing envelope, step/time are clean
    assert len(found) == 2


def test_status_contract_metric_hygiene_fixture(tmp_path):
    write(tmp_path, status_contract.STATUSSERVER, """\
        class Metrics:
            def __init__(self):
                self.register("documented_total", "counter", "h")
                self.register("mystery_total", "counter", "h")


        class User:
            def tick(self):
                self.metrics.inc("typo_total")
        """)
    write(tmp_path, "docs/design.md", "only documented_total is here\n")
    write(tmp_path, "tests/test_x.py", "covers documented_total\n")
    found = keyed(status_contract.run(tmp_path))
    assert found["metric-undocumented:mystery_total"].line == 4
    assert "metric-untested:mystery_total" in found
    unreg = found["metric-unregistered:typo_total"]
    assert (unreg.path, unreg.line) == (status_contract.STATUSSERVER, 9)
    assert "metric-undocumented:documented_total" not in found


def test_concurrency_guarded_by_fixture(tmp_path):
    write(tmp_path, "tpu_operator/client/box.py", """\
        import threading


        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = {}  # guarded-by: _lock

            def good(self):
                with self._lock:
                    return len(self._items)

            def good_locked(self):
                return self._items.get("y")

            def bad(self):
                return self._items.get("x")
        """)
    found = keyed(concurrency.run(tmp_path))
    bad = found["guarded-by:tpu_operator/client/box.py:Box._items:bad"]
    assert bad.line == 17
    assert len(found) == 1  # with-block and *_locked accesses are clean


def test_concurrency_thread_and_blocking_fixtures(tmp_path):
    write(tmp_path, "tpu_operator/controller/runner.py", """\
        import threading


        def spawn():
            t = threading.Thread(target=print)
            t.start()
            return t
        """)
    write(tmp_path, "tpu_operator/controller/locky.py", """\
        import threading
        import time

        LOCK = threading.Lock()


        def hold():
            with LOCK:
                time.sleep(1)
        """)
    found = keyed(concurrency.run(tmp_path))
    thread = found["thread:tpu_operator/controller/runner.py:spawn"]
    assert thread.line == 5
    blocking = found[
        "lock-blocking:tpu_operator/controller/locky.py:hold:time.sleep"]
    assert blocking.line == 9
    assert len(found) == 2


def test_concurrency_annotation_on_continuation_line(tmp_path):
    """A guarded-by comment on a wrapped assignment's continuation line
    (the events.py _seen shape) must register — notes are matched against
    the statement's full lineno..end_lineno range."""
    write(tmp_path, "tpu_operator/client/wrapped.py", """\
        import threading


        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._seen = dict(
                    a=1)  # guarded-by: _lock

            def bad(self):
                return self._seen.get("x")
        """)
    found = keyed(concurrency.run(tmp_path))
    assert "guarded-by:tpu_operator/client/wrapped.py:Box._seen:bad" in found


def test_concurrency_join_noise_does_not_mask_unjoined_thread(tmp_path):
    """str.join / os.path.join elsewhere in the file must not satisfy the
    thread-join check — only a .join() on the thread's own binding does."""
    write(tmp_path, "tpu_operator/controller/noisy.py", """\
        import os
        import threading


        def leak():
            path = os.path.join("a", ",".join(["b", "c"]))
            t = threading.Thread(target=print, args=(path,))
            t.start()
            return t
        """)
    found = keyed(concurrency.run(tmp_path))
    assert "thread:tpu_operator/controller/noisy.py:leak" in found


def test_concurrency_daemon_and_joined_threads_are_clean(tmp_path):
    write(tmp_path, "tpu_operator/controller/ok.py", """\
        import threading


        def spawn_daemon():
            threading.Thread(target=print, daemon=True).start()


        def spawn_joined():
            t = threading.Thread(target=print)
            t.start()
            t.join()
        """)
    assert concurrency.run(tmp_path) == []


def test_exception_policy_fixture(tmp_path):
    write(tmp_path, "tpu_operator/controller/recon.py", """\
        import logging

        log = logging.getLogger(__name__)


        def silent():
            try:
                work()
            except ValueError:
                pass


        def broad():
            try:
                work()
            except Exception:
                x = 1
            return x


        def bare():
            try:
                work()
            except:
                log.warning("caught")


        def fine():
            try:
                work()
            except Exception as e:
                log.warning("boom: %s", e)


        def literal_exit():
            raise SystemExit(143)
        """)
    found = keyed(exception_policy.run(tmp_path))
    path = "tpu_operator/controller/recon.py"
    assert found[f"silent-except:{path}:silent"].line == 9
    assert found[f"broad-except:{path}:broad"].line == 16
    assert found[f"bare-except:{path}:bare"].line == 24
    assert found[f"exit-code:{path}:literal_exit"].line == 36
    assert not any(":fine" in k for k in found)
    assert len(found) == 4


def test_payload_image_fixture(tmp_path):
    write(tmp_path, "tpu_operator/payload/mod.py", """\
        import os
        import missingdep
        """)
    write(tmp_path, "build/images/tpu_payload/requirements.txt",
          "numpy==2.0.2\n")
    write(tmp_path, "pyproject.toml", """\
        [project.optional-dependencies]
        payload = [
            "numpy==1.0.0",
        ]
        """)
    found = keyed(payload_image.run(tmp_path))
    imp = found["import:tpu_operator/payload/mod.py:missingdep"]
    assert imp.line == 2
    assert "pin-drift:numpy" in found  # 1.0.0 extra vs 2.0.2 image


# --- the real tree is clean --------------------------------------------------

def test_real_tree_is_clean_under_allowlist():
    active, suppressed, stale = run_analysis(REPO)
    assert active == [], "\n".join(f.render() for f in active)
    assert stale == set(), f"stale allowlist entries: {stale}"
    # the allowlist is genuinely load-bearing, not decorative
    assert suppressed, "expected at least one allowlisted finding"


def test_cli_exit_codes_and_finding_format(tmp_path):
    """hack/analyze.py exits nonzero with file:line findings on a seeded
    violation tree and 0 on an empty-but-valid one."""
    import subprocess
    import sys

    write(tmp_path, "tpu_operator/controller/recon.py", """\
        def reconcile():
            try:
                work()
            except ValueError:
                pass
        """)
    proc = subprocess.run(
        [sys.executable, str(REPO / "hack/analyze.py"),
         "--root", str(tmp_path), "--allowlist", "/dev/null"],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "tpu_operator/controller/recon.py:4: [exceptions]" in proc.stdout

    # the same tree with the violation allowlisted (and the entry in use)
    allow = tmp_path / "allow.txt"
    allow.write_text("exceptions  silent-except:tpu_operator/controller/"
                     "recon.py:reconcile  # test\n")
    proc = subprocess.run(
        [sys.executable, str(REPO / "hack/analyze.py"),
         "--root", str(tmp_path), "--allowlist", str(allow)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout

    # a stale allowlist entry alone fails the gate
    allow.write_text("exceptions  silent-except:nowhere.py:gone  # stale\n"
                     "exceptions  silent-except:tpu_operator/controller/"
                     "recon.py:reconcile  # test\n")
    proc = subprocess.run(
        [sys.executable, str(REPO / "hack/analyze.py"),
         "--root", str(tmp_path), "--allowlist", str(allow)],
        capture_output=True, text=True)
    assert proc.returncode == 1
    assert "stale" in proc.stdout


def test_driver_rejects_unknown_rule():
    with pytest.raises(ValueError):
        run_analysis(REPO, rules=["no-such-rule"])


def test_every_rule_registered():
    assert set(RULES) == {"lifecycle", "spec-drift", "env-contract",
                          "status-contract", "concurrency", "lock-order",
                          "escape", "exceptions", "payload-image"}
    # The lifecycle rule prints first: per-job-state findings are the
    # recurring leak class and the cheapest to act on.
    assert next(iter(RULES)) == "lifecycle"


# --- regression tests for the defects the analyzers surfaced -----------------

def test_env_contract_no_dead_coordinator_port():
    """JAX_COORDINATOR_PORT was injected for five PRs and read by nothing;
    the port rides inside JAX_COORDINATOR_ADDRESS."""
    from tpu_operator.apis.tpujob.v1alpha1.types import TPUJobSpec
    from tpu_operator.trainer.replicas import build_replica_env

    spec = TPUJobSpec.from_dict({"replicaSpecs": [{
        "replicas": 2, "tpuReplicaType": "WORKER", "tpuPort": 8476,
        "template": {"spec": {"containers": [{"name": "tpu"}]}}}]})
    env = build_replica_env("job", "rid", spec, "WORKER", 0)
    assert "JAX_COORDINATOR_PORT" not in env
    assert env["JAX_COORDINATOR_ADDRESS"].endswith(":8476")


def test_process_info_carries_operator_identity():
    """TPUJOB_RUNTIME_ID / TPUJOB_REPLICA_INDEX were injected-but-unread;
    ProcessInfo now surfaces them for log/artifact correlation."""
    from tpu_operator.payload.bootstrap import process_info_from_env

    info = process_info_from_env({
        "JAX_COORDINATOR_ADDRESS": "c:1", "JAX_PROCESS_ID": "1",
        "JAX_NUM_PROCESSES": "2", "TPUJOB_RUNTIME_ID": "ab12",
        "TPUJOB_REPLICA_INDEX": "1",
    })
    assert info.runtime_id == "ab12"
    assert info.replica_index == 1


def test_cache_path_mirror_is_honored(tmp_path):
    """TPUJOB_CACHE_PATH was an injected-but-unread mirror; the bootstrap
    now falls back to it when the ambient JAX var is stripped."""
    import jax

    from tpu_operator.payload import bootstrap, startup as startup_mod

    prev = jax.config.jax_compilation_cache_dir
    try:
        got = bootstrap.enable_compilation_cache(
            {"TPUJOB_CACHE_PATH": str(tmp_path)})
        assert got == str(tmp_path)
        assert jax.config.jax_compilation_cache_dir == str(tmp_path)
        # explicit JAX var still wins over the mirror
        other = tmp_path / "other"
        got = bootstrap.enable_compilation_cache(
            {"JAX_COMPILATION_CACHE_DIR": str(other),
             "TPUJOB_CACHE_PATH": str(tmp_path)})
        assert got == str(other)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
        startup_mod.set_cache_dir("")


class _RecorderClientset:
    """Stub clientset that records calls and asserts the recorder's dedup
    lock is NOT held during any RPC (the lock-blocking fix)."""

    class _Events:
        def __init__(self, outer):
            self.outer = outer
            self.fail_update = False
            self.created = []
            self.updated = []

        def _assert_unlocked(self):
            assert self.outer.lock.acquire(blocking=False), \
                "clientset RPC issued while the recorder lock is held"
            self.outer.lock.release()

        def get(self, namespace, name):
            self._assert_unlocked()
            from tpu_operator.client import errors
            if self.fail_update:
                raise errors.ApiError(409, "Conflict", "conflict")
            return {"metadata": {"name": name, "namespace": namespace},
                    "count": 1}

        def update(self, namespace, ev):
            self._assert_unlocked()
            self.updated.append(ev)
            return ev

        def create(self, namespace, ev):
            self._assert_unlocked()
            self.created.append(ev)
            return ev

    def __init__(self, lock):
        self.lock = lock
        self.events = self._Events(self)


def _job_obj(name="j1"):
    return _types.SimpleNamespace(
        name=name, namespace="default",
        metadata={"uid": "u1", "apiVersion": "tpuoperator.dev/v1alpha1"})


def test_event_recording_rpcs_run_outside_the_dedup_lock():
    from tpu_operator.controller.events import EventRecorder

    recorder = EventRecorder.__new__(EventRecorder)
    lock = threading.Lock()
    cs = _RecorderClientset(lock)
    recorder.__init__(cs)
    recorder._lock = lock  # the stub asserts against this exact lock
    job = _job_obj()
    recorder.event(job, "Normal", "Tick", "msg")       # create path
    recorder.event(job, "Normal", "Tick", "msg")       # aggregation path
    assert len(cs.events.created) == 1
    assert len(cs.events.updated) == 1


def test_event_aggregation_failure_logs_and_falls_back(caplog):
    """The aggregation-update ApiError used to be swallowed with a bare
    ``pass``; it must log and still create a fresh event."""
    from tpu_operator.controller.events import EventRecorder

    lock = threading.Lock()
    cs = _RecorderClientset(lock)
    recorder = EventRecorder.__new__(EventRecorder)
    recorder.__init__(cs)
    recorder._lock = lock
    job = _job_obj()
    recorder.event(job, "Normal", "Tick", "msg")
    cs.events.fail_update = True
    with caplog.at_level(logging.DEBUG,
                         logger="tpu_operator.controller.events"):
        recorder.event(job, "Normal", "Tick", "msg")
    assert len(cs.events.created) == 2, \
        "aggregation failure must fall back to a fresh create"
    assert any("aggregation" in r.message for r in caplog.records)


# --- lock-order rule ----------------------------------------------------------

def test_lock_order_cycle_fixture(tmp_path):
    """Two classes acquiring each other's locks in opposite orders — the
    cross-object cycle no per-function rule can see."""
    write(tmp_path, "tpu_operator/controller/pair.py", """\
        import threading


        class A:
            def __init__(self, b: "B"):
                self._lock = threading.Lock()
                self.b = b

            def forward(self):
                with self._lock:
                    self.b.poke()

            def poke(self):
                with self._lock:
                    pass


        class B:
            def __init__(self, a: A):
                self._lock = threading.Lock()
                self.a = a

            def poke(self):
                with self._lock:
                    pass

            def backward(self):
                with self._lock:
                    self.a.poke()
        """)
    found = keyed(lock_order.run(tmp_path))
    (key,) = [k for k in found if k.startswith("cycle:")]
    assert "A._lock" in key and "B._lock" in key
    # The message carries a concrete witness site per edge.
    assert "pair.py:" in found[key].message


def test_lock_order_consistent_nesting_is_clean(tmp_path):
    write(tmp_path, "tpu_operator/controller/nest.py", """\
        import threading


        class Inner:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    pass


        class Outer:
            def __init__(self):
                self._lock = threading.Lock()
                self.inner = Inner()

            def one(self):
                with self._lock:
                    self.inner.poke()

            def two(self):
                with self._lock:
                    self.inner.poke()
        """)
    assert lock_order.run(tmp_path) == []


def test_lock_order_blocking_one_hop_under_lock(tmp_path):
    """The PR-6 recorder bug shape one call-hop deeper: the blocking call
    is in the callee, where the per-function rule is structurally blind."""
    write(tmp_path, "tpu_operator/controller/hop.py", """\
        import threading
        import time


        class Holder:
            def __init__(self):
                self._lock = threading.Lock()

            def slow_io(self):
                time.sleep(1)

            def bad(self):
                with self._lock:
                    self.slow_io()

            def fine(self):
                self.slow_io()
        """)
    found = keyed(lock_order.run(tmp_path))
    key = "blocking-hop:tpu_operator/controller/hop.py:Holder.bad:self.slow_io"
    assert key in found
    assert "time.sleep" in found[key].message
    assert len([k for k in found if k.startswith("blocking-hop:")]) == 1


def test_lock_order_lockdep_factories_count_as_locks(tmp_path):
    """Locks created through the witness factories participate in the
    graph exactly like raw threading constructors."""
    write(tmp_path, "tpu_operator/controller/dep.py", """\
        from tpu_operator.util import lockdep


        class P:
            def __init__(self, q: "Q"):
                self._lock = lockdep.lock("P._lock")
                self.q = q

            def forward(self):
                with self._lock:
                    self.q.poke()

            def poke(self):
                with self._lock:
                    pass


        class Q:
            def __init__(self):
                self._lock = lockdep.condition("Q._lock")

            def poke(self):
                with self._lock:
                    pass

            def backward(self, p: P):
                with self._lock:
                    p.poke()
        """)
    found = keyed(lock_order.run(tmp_path))
    assert any(k.startswith("cycle:") and "P._lock" in k and "Q._lock" in k
               for k in found)


# --- escape rule --------------------------------------------------------------

def test_escape_thread_shared_attr_fixture(tmp_path):
    write(tmp_path, "tpu_operator/controller/esc.py", """\
        import threading


        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self.results = []
                self.guarded = []  # guarded-by: _lock
                self.count = 0

            def start(self):
                threading.Thread(target=self._run, daemon=True).start()

            def _run(self):
                self.results.append(1)
                with self._lock:
                    self.guarded.append(1)
                    self.count += 1

            def drain(self):
                out, self.results = self.results, []
                return out

            def reset_count(self):
                with self._lock:
                    self.count = 0
        """)
    found = keyed(escape.run(tmp_path))
    key = "attr:tpu_operator/controller/esc.py:Worker.results"
    assert key in found  # mutated in _run (thread) AND drain (main), no lock
    assert "_run" in found[key].message
    # guarded-by annotation and under-lock mutations are exempt
    assert not any("guarded" in k for k in found)
    assert not any("count" in k for k in found)


def test_escape_single_domain_class_is_clean(tmp_path):
    write(tmp_path, "tpu_operator/controller/solo.py", """\
        class Solo:
            def __init__(self):
                self.items = []

            def add(self, x):
                self.items.append(x)
        """)
    assert escape.run(tmp_path) == []


def test_escape_module_global_fixture(tmp_path):
    write(tmp_path, "tpu_operator/controller/glob.py", """\
        import threading

        _count = 0
        _quiet = 0


        def listen(register_event_listener):
            def _cb(event):
                global _count
                _count += 1
            register_event_listener(_cb)


        def read():
            return _count
        """)
    found = keyed(escape.run(tmp_path))
    key = "global:tpu_operator/controller/glob.py:_count"
    assert key in found
    assert not any("_quiet" in k for k in found)  # never mutated


def test_escape_annotated_module_global_is_enforced(tmp_path):
    """A module-level guarded-by annotation is a contract: mutations
    outside `with <lock>:` flag even in an unthreaded module."""
    write(tmp_path, "tpu_operator/util/glob2.py", """\
        import threading

        _lock = threading.Lock()
        _state = {}  # guarded-by: _lock


        def good(k, v):
            with _lock:
                _state[k] = v


        def bad(k):
            _state.pop(k, None)
        """)
    found = keyed(escape.run(tmp_path))
    key = "global:tpu_operator/util/glob2.py:_state"
    assert key in found
    assert "bad" in found[key].message


# --- regression tests for the defects the new rules' first run surfaced ------

def test_informer_dispatch_uses_a_handler_snapshot():
    """Informer._handlers was appended without a lock while the reflector
    thread iterated it (escape-analyzer finding). The fix gives dispatch
    snapshot semantics: a handler registered DURING a dispatch sees the
    next event, not the in-flight one."""
    from tpu_operator.client.informer import Informer

    class _NullClient:
        kind = "Test"

    inf = Informer(_NullClient(), resync_period=0)
    late_calls = []

    def late_handler(obj):
        late_calls.append(obj["n"])

    def registering_handler(obj):
        if obj["n"] == 1:
            inf.add_event_handler(on_add=late_handler)

    inf.add_event_handler(on_add=registering_handler)
    inf._dispatch_add({"n": 1})  # registers late_handler mid-dispatch
    assert late_calls == []      # snapshot: not invoked for event 1
    inf._dispatch_add({"n": 2})
    assert late_calls == [2]     # but sees every later event


def test_startup_cache_hit_counter_is_exact_under_threads():
    """startup._cache_hits was bumped by the JAX monitoring callback —
    which fires on the overlapped prologue's compile worker thread —
    with an unlocked +=, a lost-update race against the heartbeat
    thread's reads (escape-analyzer finding). Locked, N concurrent
    events count exactly N."""
    import threading as _threading

    from jax import monitoring

    from tpu_operator.payload import startup

    assert startup.ensure_cache_listener()
    before = startup.cache_hit_count()
    threads = [
        _threading.Thread(target=lambda: [
            monitoring.record_event("/jax/compilation_cache/cache_hits")
            for _ in range(200)])
        for _ in range(8)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert startup.cache_hit_count() - before == 1600


def test_fake_clientset_version_counter_is_thread_safe():
    """FakeClientset.next_version mutated the counter without taking the
    clientset RLock — safe only because every production caller happened
    to hold it, which nothing enforced (guarded-by finding after the
    fake joined the annotation discipline). Direct concurrent callers
    must now mint unique monotonic versions."""
    from tpu_operator.client.fake import FakeClientset

    cs = FakeClientset()
    minted = []
    lock = __import__("threading").Lock()

    def mint():
        got = [cs.next_version() for _ in range(500)]
        with lock:
            minted.extend(got)

    threads = [__import__("threading").Thread(target=mint)
               for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert len(minted) == 4000
    assert len(set(minted)) == 4000  # no duplicates: no lost updates


def test_escape_local_shadow_and_global_declared_mutators(tmp_path):
    """Mutator-call precision (review finding): a function-local list
    shadowing a module name is NOT a global mutation, while a
    `global`-declared receiver's .append IS one."""
    write(tmp_path, "tpu_operator/controller/shadow.py", """\
        import threading

        items = []


        def spawn():
            threading.Thread(target=print, daemon=True).start()


        def local_only():
            items = []
            items.append(1)
            return items


        def real_mutation():
            global items
            items.append(2)
        """)
    found = keyed(escape.run(tmp_path))
    key = "global:tpu_operator/controller/shadow.py:items"
    assert key in found
    assert "real_mutation" in found[key].message
