"""Subprocess worker for the per-process real-data sharding test
(tests/test_realdata_multiprocess.py).

One process of a 2-process CPU jax.distributed group training the tiny
transformer LM from a shared mmap'd token file. Writes a JSON record of
the window rows THIS process materialized (data.local_batch_rows) and the
first 3 step losses, so the parent can assert the reads are disjoint and
the training trajectory matches a single-process run of the same config.

Usage: realdata_worker.py <port> <pid> <nprocs> <token_path> <out_dir>
"""

import json
import os
import sys


def main() -> None:
    port, pid, nprocs, token_path, out_dir = sys.argv[1:6]
    os.environ.update({
        "JAX_PLATFORMS": "cpu",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_PROCESS_ID": pid,
        "JAX_NUM_PROCESSES": nprocs,
        "TPU_WORKER_ID": pid,
    })
    os.environ.pop("XLA_FLAGS", None)
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from tpu_operator.payload import bootstrap, data as data_mod, transformer

    bootstrap.initialize()
    argv = ["--batch", "4", "--seq-len", "64", "--dim", "32", "--heads", "2",
            "--layers", "1", "--vocab", "128", "--data", token_path,
            "--lr", "1e-2"]
    args = transformer.parse_args(argv)
    mesh, _m, state, step, batches = transformer.build(args)
    spec = transformer.lm_token_spec(mesh)
    rows = data_mod.local_batch_rows(mesh, args.batch, args.seq_len,
                                     spec=spec)
    losses = []
    it = iter(batches)
    for _ in range(3):
        arrays = data_mod.put_global_batch(mesh, *next(it), spec=spec)
        state, metrics = step(state, *arrays)
        losses.append(float(jax.device_get(metrics["loss"])))

    with open(os.path.join(out_dir, f"{pid}.json"), "w") as f:
        json.dump({"rows": list(rows) if rows else None,
                   "losses": losses}, f)


if __name__ == "__main__":
    main()
