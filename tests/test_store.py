"""Remote warm-start store (tpu_operator/store + its payload/operator
wiring).

Layers under test, bottom-up:

- blob backends (localfs atomicity + key safety, fake latency/faults,
  URI resolution with the cloud-scheme gate);
- chunked transfer (multi-chunk roundtrip, torn-upload resume via
  content-addressed chunk keys, per-chunk checksum retry-once, the
  manifest-last commit marker);
- WarmStartStore (checkpoint snapshots, the corrupt index, prefetch
  newest→oldest fallback, local-quarantine parity, cache set-difference
  sync);
- the write-behind uploader (coalescing, non-blocking enqueue, failure
  escalation counters);
- spec.store plumbing (round-trip/defaults/validation/strict schema, env
  injection) and the payload env adapter (process-0 uploader, the
  rendezvous-overlapped prefetch recording the PREFETCH stage);
- Checkpointer integration (verified saves upload, quarantine condemns
  the remote copy, persistent upload failures exit retryable);
- the heartbeat → statusserver → controller chain (storeLastUploadedStep
  / storeUploadFailures → status.store with delta accounting +
  job_store_upload_failures_total / job_store_last_uploaded_step), the
  goodput fold (status.goodput + job_goodput_ratio, prefetch hit/miss →
  store_prefetch_hits_total / store_prefetch_misses_total), and
  ``tpujobctl describe``;
- a slow chaos compose: fake-backend faults + the PR 4 corrupt-latest
  scenario on a fresh node.
"""

import os
import time

import pytest

from tpu_operator.store import blob as blob_mod
from tpu_operator.store import transfer, warmstart, writebehind
from tpu_operator.store.blob import (BlobError, BlobNotFound, FakeBackend,
                                     LocalFSBackend)
from tpu_operator.store.warmstart import WarmStartStore
from tpu_operator.store.writebehind import WriteBehindUploader


@pytest.fixture(autouse=True)
def _reset_prefetch_state():
    from tpu_operator.payload import warmstore

    warmstore.reset_prefetch()
    blob_mod.reset_fake_backends()
    yield
    warmstore.reset_prefetch()
    blob_mod.reset_fake_backends()


def write_tree(root, files):
    for rel, data in files.items():
        path = os.path.join(root, *rel.split("/"))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)


def read_tree(root):
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            p = os.path.join(dirpath, fn)
            out[os.path.relpath(p, root).replace(os.sep, "/")] = \
                open(p, "rb").read()
    return out


SAMPLE = {"a.bin": os.urandom(40_000), "sub/b.txt": b"hello", "empty": b""}


# --- blob backends -----------------------------------------------------------

def test_localfs_roundtrip_and_key_safety(tmp_path):
    be = LocalFSBackend(str(tmp_path / "root"))
    be.put("x/y", b"one")
    assert be.get("x/y") == b"one"
    assert be.exists("x/y") and not be.exists("x/z")
    assert be.list("") == ["x/y"]
    be.delete("x/y")
    be.delete("x/y")  # idempotent
    assert not be.exists("x/y")
    with pytest.raises(BlobNotFound):
        be.get("x/y")
    for bad in ("", "/abs", "a/../b", "a//b", "."):
        with pytest.raises(BlobError):
            be.put(bad, b"nope")


def test_fake_backend_latency_faults_and_counters():
    boom = {"arm": False}

    def fault(op, _key):
        if boom["arm"] and op == "put":
            raise BlobError("injected")

    slept = []
    be = FakeBackend(latency=0.5, fault_hook=fault, sleep=slept.append)
    be.put("k", b"v")
    assert be.get("k") == b"v"
    assert slept == [0.5, 0.5]
    assert be.op_counts["put"] == 1 and be.op_counts["get"] == 1
    boom["arm"] = True
    with pytest.raises(BlobError):
        be.put("k2", b"v2")
    be.corrupt_once("k")
    assert be.get("k") != b"v"   # one poisoned read...
    assert be.get("k") == b"v"   # ...then healthy again


def test_from_uri_schemes(tmp_path):
    assert isinstance(blob_mod.from_uri(str(tmp_path)), LocalFSBackend)
    assert isinstance(blob_mod.from_uri(f"file://{tmp_path}"),
                      LocalFSBackend)
    # fake:// is a process-shared registry: same name = same instance.
    assert blob_mod.from_uri("fake://t1") is blob_mod.from_uri("fake://t1")
    assert blob_mod.from_uri("fake://t1") is not blob_mod.from_uri("fake://t2")
    # Cloud schemes are GATED, not vendored: a clear error naming the
    # registration hook, never an SDK import error at job runtime.
    with pytest.raises(BlobError, match="register_backend"):
        blob_mod.from_uri("gs://bucket/prefix")
    blob_mod.register_backend("gs", lambda uri: FakeBackend())
    assert isinstance(blob_mod.from_uri("gs://bucket/prefix"), FakeBackend)


# --- chunked transfer --------------------------------------------------------

def test_upload_download_roundtrip_multichunk(tmp_path):
    src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
    write_tree(src, SAMPLE)
    be = FakeBackend()
    manifest = transfer.upload_tree(be, src, "snap", chunk_size=4096)
    assert {f["path"] for f in manifest["files"]} == set(SAMPLE)
    big = next(f for f in manifest["files"] if f["path"] == "a.bin")
    assert len(big["chunks"]) == 10  # 40000 / 4096 rounded up
    transfer.download_tree(be, "snap", dst)
    assert read_tree(dst) == SAMPLE
    # Idempotent re-download into the same dir (the gang-shared-fs case).
    gets_before = be.op_counts.get("get", 0)
    transfer.download_tree(be, "snap", dst)
    # Only the manifest is re-read; matching local files skip their chunks.
    assert be.op_counts.get("get", 0) == gets_before + 1


def test_torn_upload_resume_skips_committed_chunks(tmp_path):
    src = str(tmp_path / "src")
    write_tree(src, SAMPLE)
    state = {"puts": 0}

    def fault(op, key):
        if op == "put" and state["puts"] >= 4 and "manifest" not in key:
            raise BlobError("torn: remote went away mid-upload")

    be = FakeBackend(fault_hook=fault)

    def count_put(op, key):
        if op == "put":
            state["puts"] += 1
        fault(op, key)

    be.fault_hook = count_put
    with pytest.raises(BlobError):
        transfer.upload_tree(be, src, "snap", chunk_size=4096,
                             parallelism=1)
    assert not be.exists("snap/" + transfer.MANIFEST_KEY)  # not committed
    landed = len(be.list("snap/"))
    assert landed == 3  # the 4th put died mid-flight
    be.fault_hook = None
    puts_before = be.op_counts.get("put", 0)
    transfer.upload_tree(be, src, "snap", chunk_size=4096, parallelism=1)
    # Resume re-puts only the missing tail + the manifest: chunk keys are
    # content-addressed, so exists == provably-identical bytes.
    total_chunks = sum(
        len(f["chunks"])
        for f in transfer.read_manifest(be, "snap")["files"])
    assert be.op_counts["put"] - puts_before == total_chunks - landed + 1


def test_chunk_corruption_retries_once_then_fails(tmp_path):
    src, dst = str(tmp_path / "src"), str(tmp_path / "dst")
    write_tree(src, SAMPLE)
    be = FakeBackend()
    transfer.upload_tree(be, src, "snap", chunk_size=4096)
    chunk_key = be.list("snap/data/a.bin/")[0]
    # Transient: one poisoned read, the retry sees healthy bytes.
    be.corrupt_once(chunk_key)
    transfer.download_tree(be, "snap", dst)
    assert read_tree(dst) == SAMPLE
    # Permanent: retry also fails → IntegrityError, never silent bad bytes.
    be.corrupt(chunk_key)
    with pytest.raises(transfer.IntegrityError):
        transfer.download_tree(be, "snap", str(tmp_path / "dst2"))


def test_manifest_is_the_commit_marker(tmp_path):
    src = str(tmp_path / "src")
    write_tree(src, SAMPLE)
    be = FakeBackend()
    transfer.upload_tree(be, src, "snap", chunk_size=4096)
    be.delete("snap/" + transfer.MANIFEST_KEY)
    with pytest.raises(BlobNotFound):
        transfer.download_tree(be, "snap", str(tmp_path / "dst"))


# --- WarmStartStore ----------------------------------------------------------

def make_store(chunk=4096, backend=None):
    return WarmStartStore(backend or FakeBackend(), prefix="default/job",
                          chunk_size=chunk)


def test_warmstore_checkpoint_roundtrip(tmp_path):
    step_dir = str(tmp_path / "ck" / "5")
    write_tree(step_dir, SAMPLE)
    ws = make_store()
    ws.upload_checkpoint(step_dir, 5)
    assert ws.checkpoint_steps() == [5]
    assert ws.last_uploaded_step() == 5
    fresh = str(tmp_path / "fresh")
    step, fallbacks = ws.prefetch_checkpoint(fresh)
    assert (step, fallbacks) == (5, 0)
    assert read_tree(os.path.join(fresh, "5")) == SAMPLE


def test_mark_corrupt_hides_step_and_prefetch_falls_back(tmp_path):
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, SAMPLE)
    ws = make_store()
    ws.upload_checkpoint(step_dir, 3)
    ws.upload_checkpoint(step_dir, 7)
    ws.mark_corrupt(7, "local quarantine")
    assert ws.checkpoint_steps() == [3]
    step, _ = ws.prefetch_checkpoint(str(tmp_path / "fresh"))
    assert step == 3
    # Idempotent re-mark is fine.
    ws.mark_corrupt(7)


def test_prefetch_never_prefers_locally_quarantined_step(tmp_path):
    """The bugfix satellite: a step the LOCAL walk condemned
    (``<step>.corrupt-N``) must never be re-materialized from the remote —
    and prefetch pushes the condemnation back to the remote index."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, SAMPLE)
    ws = make_store()
    ws.upload_checkpoint(step_dir, 4)
    ws.upload_checkpoint(step_dir, 8)
    local = str(tmp_path / "local")
    os.makedirs(os.path.join(local, "8.corrupt-0"))
    step, _ = ws.prefetch_checkpoint(local)
    assert step == 4
    assert not os.path.exists(os.path.join(local, "8"))
    # The local verdict propagated: the remote index now condemns 8 too,
    # so even a TRULY fresh node (no quarantine dir) never restores it.
    assert ws.checkpoint_steps() == [4]
    step, _ = ws.prefetch_checkpoint(str(tmp_path / "fresh"))
    assert step == 4


def test_fresh_upload_clears_stale_corrupt_marker(tmp_path):
    """A re-SAVED step must not stay condemned by its predecessor's
    marker: quarantine step N → resume from N-k → replay → a newly
    verified step N uploads — prefetch must prefer it again, or the job
    replays the same k steps after every preemption forever while
    heartbeats advertise N as remotely durable."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, SAMPLE)
    ws = make_store()
    ws.upload_checkpoint(step_dir, 90)
    ws.upload_checkpoint(step_dir, 100)
    ws.mark_corrupt(100, "failed local verification")
    assert ws.checkpoint_steps() == [90]
    # The replayed attempt re-saves a NEW verified step 100 and ships it.
    ws.upload_checkpoint(step_dir, 100)
    assert ws.checkpoint_steps() == [90, 100]
    step, _ = ws.prefetch_checkpoint(str(tmp_path / "fresh"))
    assert step == 100


def test_prefetch_integrity_fallback_next_oldest(tmp_path):
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, SAMPLE)
    be = FakeBackend()
    ws = make_store(backend=be)
    ws.upload_checkpoint(step_dir, 1)
    ws.upload_checkpoint(step_dir, 2)
    be.corrupt(be.list("default/job/checkpoints/2/data/a.bin/")[0])
    fresh = str(tmp_path / "fresh")
    step, fallbacks = ws.prefetch_checkpoint(fresh)
    assert (step, fallbacks) == (1, 1)
    # The torn partial materialization was scrubbed — the local verified
    # walk must never see a manifest-less step dir candidate.
    assert not os.path.exists(os.path.join(fresh, "2"))
    assert ws.checkpoint_steps() == [1]  # condemned remotely


def test_prefetch_never_exposes_partial_step_dir(tmp_path):
    """The restore walk must never observe a half-materialized step: the
    download stages under a non-numeric name and renames the COMPLETE
    dir into place — a torn step dir seen by the PR 4 walk would be
    quarantined locally and condemned remotely, destroying a healthy
    snapshot (the timed-out-prefetch race)."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, SAMPLE)
    be = FakeBackend()
    ws = make_store(backend=be)
    ws.upload_checkpoint(step_dir, 5)
    gets = {"n": 0}

    def die_mid_download(op, _key):
        if op == "get":
            gets["n"] += 1
            if gets["n"] > 2:
                raise BlobError("network blip mid-download")

    be.fault_hook = die_mid_download
    local = str(tmp_path / "local")
    with pytest.raises(BlobError):
        ws.prefetch_checkpoint(local)
    # No numeric step dir AND no staging leftovers: the walk sees nothing.
    assert os.listdir(local) == []
    be.fault_hook = None
    step, _ = ws.prefetch_checkpoint(local)
    assert step == 5
    assert read_tree(os.path.join(local, "5")) == SAMPLE


def test_store_from_env_unusable_localfs_proceeds_storeless(tmp_path):
    """An unmounted/read-only store root raises OSError (not BlobError)
    from LocalFSBackend's makedirs — the env adapter must swallow it and
    run store-less, never crash the attempt into a permanent failure."""
    from tpu_operator.payload import warmstore

    blocker = tmp_path / "not-a-dir"
    blocker.write_text("file, not a mount")
    env = {"TPUJOB_STORE_URI": str(blocker / "warmstore"),
           "TPUJOB_NAME": "jb"}
    assert warmstore.store_from_env(env) is None
    assert warmstore.uploader_from_env(env) is None


def test_cache_sync_is_set_difference(tmp_path):
    cache_a = str(tmp_path / "ca")
    write_tree(cache_a, {"e1-cache": b"x1", "e2-cache": b"x2"})
    be = FakeBackend()
    ws = make_store(backend=be)
    assert ws.upload_cache(cache_a) == 2
    assert ws.upload_cache(cache_a) == 0  # content-named: exists == same
    write_tree(cache_a, {"e3-cache": b"x3"})
    assert ws.upload_cache(cache_a) == 1
    cache_b = str(tmp_path / "cb")
    write_tree(cache_b, {"e1-cache": b"x1"})
    assert ws.prefetch_cache(cache_b) == 2  # only the missing two
    assert read_tree(cache_b) == {"e1-cache": b"x1", "e2-cache": b"x2",
                                  "e3-cache": b"x3"}


# --- write-behind uploader ---------------------------------------------------

def test_writebehind_uploads_and_coalesces(tmp_path):
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, {"f": b"data"})
    be = FakeBackend(latency=0.05)
    up = WriteBehindUploader(WarmStartStore(be, prefix="p"), fail_after=3)
    try:
        for step in (1, 2, 3):
            up.enqueue(step, step_dir)
        assert up.flush(10.0)
        assert up.last_uploaded_step == 3
        ws = WarmStartStore(be, prefix="p")
        # 3 enqueued at save cadence faster than the slow remote: only the
        # newest pending step per drain cycle ships (last-wins).
        assert 3 in ws.checkpoint_steps()
        assert up.stats()["lastUploadedStep"] == 3
        assert up.stats()["uploadFailures"] == 0
    finally:
        up.close()


def test_writebehind_failure_escalation_counters(tmp_path):
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, {"f": b"data"})

    def fault(_op, _key):
        raise BlobError("remote down")

    up = WriteBehindUploader(
        WarmStartStore(FakeBackend(fault_hook=fault), prefix="p"),
        fail_after=2)
    try:
        assert not up.escalated()
        up.enqueue(1, step_dir)
        up.flush(5.0)
        assert up.upload_failures == 1 and not up.escalated()
        up.enqueue(2, step_dir)
        up.flush(5.0)
        assert up.escalated()
        assert up.stats()["uploadFailures"] == 2
    finally:
        up.close()


def test_writebehind_cache_sync_survives_failed_checkpoint_upload(tmp_path):
    """Cache entries compiled this attempt ship even when the checkpoint
    snapshot fails to upload — a broken upload must not ALSO forfeit the
    fresh-node warm compile."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, {"f": b"data"})
    cache_dir = str(tmp_path / "cache")
    write_tree(cache_dir, {"jit_a-cache": b"exe"})

    def fault(op, key):
        if op == "put" and "checkpoints/" in key:
            raise BlobError("snapshot uploads broken")

    be = FakeBackend(fault_hook=fault)
    up = WriteBehindUploader(WarmStartStore(be, prefix="p"),
                             fail_after=1_000,
                             cache_dir_fn=lambda: cache_dir)
    try:
        up.enqueue(1, step_dir)
        assert up.flush(10.0)
        assert up.upload_failures == 1
        assert up.cache_files_uploaded == 1
        assert WarmStartStore(be, prefix="p").prefetch_cache(
            str(tmp_path / "fresh")) == 1
    finally:
        up.close()


def test_upload_cache_once_for_checkpointless_jobs(tmp_path):
    """Jobs with a store but NO checkpointing never build an uploader;
    the bootstrap exit hook still ships their compiled executables."""
    from tpu_operator.payload import warmstore

    cache_dir = str(tmp_path / "cache")
    write_tree(cache_dir, {"jit_z-cache": b"exe"})
    env = {"TPUJOB_STORE_URI": "fake://exitpath", "TPUJOB_NAMESPACE": "ns",
           "TPUJOB_NAME": "jb", "JAX_COMPILATION_CACHE_DIR": cache_dir}
    assert warmstore.upload_cache_once(env) == 1
    assert warmstore.upload_cache_once(env) == 0  # set-difference
    ws = WarmStartStore(blob_mod.fake_backend("exitpath"), prefix="ns/jb")
    assert ws.prefetch_cache(str(tmp_path / "fresh")) == 1
    # Not process 0 / no store: no-op.
    assert warmstore.upload_cache_once(
        {**env, "JAX_PROCESS_ID": "2"}) == 0
    assert warmstore.upload_cache_once(
        {"JAX_COMPILATION_CACHE_DIR": cache_dir}) == 0


def test_upload_cache_once_ignores_ambient_cache_global(tmp_path):
    """An explicit env mapping is the caller's whole contract: the
    module-level cache dir (what bootstrap enabled in THIS process) must
    not leak into it — one test's enable_compilation_cache() polluting a
    later explicit-env upload was an order-dependent tier-1 flake,
    reproduced on the unmodified tree."""
    from tpu_operator.payload import startup as startup_mod, warmstore

    cache_dir = str(tmp_path / "cache")
    write_tree(cache_dir, {"jit_a": b"x"})
    ambient = str(tmp_path / "ambient")
    write_tree(ambient, {"jit_b": b"y", "jit_c": b"z"})
    startup_mod.set_cache_dir(ambient)
    try:
        env = {"TPUJOB_STORE_URI": "fake://ambient-leak",
               "TPUJOB_NAMESPACE": "ns", "TPUJOB_NAME": "jb",
               "JAX_COMPILATION_CACHE_DIR": cache_dir}
        # exactly the env's one entry — never the ambient dir's two
        assert warmstore.upload_cache_once(env) == 1
    finally:
        startup_mod.set_cache_dir("")


def test_writebehind_ships_artifacts(tmp_path):
    """Postmortem step-trace dumps ride the same async worker as
    checkpoints: enqueue_artifact never blocks, the file lands under the
    job's artifacts/ prefix, and an upload failure is logged — never
    counted toward the escalation streak (a postmortem aid must not
    convert a retryable exit into a failed remote)."""
    art = tmp_path / "steptrace-attempt1-p0.json"
    art.write_text('{"kind": "tpujob-steptrace", "steps": []}')
    ws = WarmStartStore(FakeBackend(), prefix="ns/aj")
    up = WriteBehindUploader(ws)
    try:
        up.enqueue_artifact(str(art))
        assert up.flush(timeout=10.0)
        assert ws.list_artifacts() == ["steptrace-attempt1-p0.json"]
        # a missing file fails the upload quietly, without escalation
        up.enqueue_artifact(str(tmp_path / "gone.json"))
        assert up.flush(timeout=10.0)
        assert up.consecutive_failures == 0 and not up.escalated()
    finally:
        up.close()


def test_writebehind_enqueue_never_blocks(tmp_path):
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, {"f": os.urandom(10_000)})
    up = WriteBehindUploader(
        WarmStartStore(FakeBackend(latency=0.3), prefix="p"))
    try:
        t0 = time.perf_counter()
        up.enqueue(1, step_dir)
        up.mark_corrupt(99)
        assert time.perf_counter() - t0 < 0.1  # never touches the backend
        assert up.flush(15.0)
    finally:
        up.close()


# --- spec.store plumbing -----------------------------------------------------

def test_store_spec_roundtrip_defaults_validation():
    from tpu_operator.apis.tpujob import validation
    from tpu_operator.apis.tpujob.v1alpha1 import types as t
    from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults

    def job_spec(store):
        spec = t.TPUJobSpec(
            replica_specs=[t.TPUReplicaSpec(template={"spec": {"containers": [
                {"name": "tpu", "image": "i"}]}})],
            store=store)
        return set_defaults(spec)

    spec = job_spec(t.StoreSpec(uri="/mnt/warmstore"))
    wire = spec.to_dict()["store"]
    assert wire == {"backend": "localfs", "uri": "/mnt/warmstore",
                    "uploadParallelism": 4, "prefetch": True}
    again = t.TPUJobSpec.from_dict(spec.to_dict())
    assert again.store.uri == "/mnt/warmstore"
    validation.validate_tpujob_spec(spec)
    # Backend defaults from the URI scheme — including registered cloud
    # schemes (the register_backend gate must be reachable END TO END
    # from spec.store: validation accepts the slug + matching scheme;
    # resolution is gated at payload runtime).
    spec = job_spec(t.StoreSpec(backend="", uri="fake://tst"))
    assert spec.store.backend == "fake"
    validation.validate_tpujob_spec(spec)
    spec = job_spec(t.StoreSpec(backend="", uri="gs://bucket/warm"))
    assert spec.store.backend == "gs"
    validation.validate_tpujob_spec(spec)
    validation.validate_tpujob_spec(
        job_spec(t.StoreSpec(backend="s3", uri="s3://bucket/warm")))
    # Rejections: malformed backend slug, missing uri, scheme mismatch
    # (in-repo AND registered backends), pool < 1.
    for store, needle in (
            (t.StoreSpec(backend="No_Caps", uri="/x"), "backend"),
            (t.StoreSpec(uri=""), "uri is required"),
            (t.StoreSpec(backend="localfs", uri="fake://x"), "absolute"),
            (t.StoreSpec(backend="fake", uri="/x"), "fake://"),
            (t.StoreSpec(backend="s3", uri="gs://bucket"), "s3://"),
    ):
        with pytest.raises(validation.ValidationError, match=needle):
            validation.validate_tpujob_spec(job_spec(store))
    bad = job_spec(t.StoreSpec(uri="/x"))
    bad.store.upload_parallelism = 0
    with pytest.raises(validation.ValidationError, match="uploadParallelism"):
        validation.validate_tpujob_spec(bad)


def test_schema_strict_store_and_status():
    from tpu_operator.apis.tpujob.v1alpha1 import schema

    body = {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "s"},
        "spec": {"replicaSpecs": [],
                 "store": {"backend": "localfs", "uri": "/w",
                           "uploadParallelism": 2, "prefetch": False}},
        "status": {
            "store": {"lastUploadedStep": 7, "uploadFailures": 1,
                      "attempt": 0, "attemptUploadFailures": 1,
                      "time": "t"},
            "goodput": {"usefulStepSeconds": 10.5, "wallclockSeconds": 20.0,
                        "ratio": 0.525, "attempt": 0, "lastStep": 9},
            "startup": {"prefetchSeconds": 0.4, "prefetchHit": True},
            "lastHeartbeat": {"storeLastUploadedStep": 7,
                              "storeUploadFailures": 1},
        },
    }
    ok, msg = schema.validate_tpujob_strict(body)
    assert ok, msg
    body["spec"]["store"]["bucket"] = "typo"
    ok, msg = schema.validate_tpujob_strict(body)
    assert not ok and "bucket" in msg


def test_env_injection():
    from tpu_operator.apis.tpujob.v1alpha1 import types as t
    from tpu_operator.trainer.replicas import build_replica_env

    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(template={"spec": {"containers": [
            {"name": "tpu"}]}})],
        runtime_id="r1",
        store=t.StoreSpec(backend="localfs", uri="/warm",
                          upload_parallelism=8, prefetch=False))
    env = build_replica_env("j", "r1", spec, "WORKER", 0)
    assert env["TPUJOB_STORE_BACKEND"] == "localfs"
    assert env["TPUJOB_STORE_URI"] == "/warm"
    assert env["TPUJOB_STORE_PARALLELISM"] == "8"
    assert env["TPUJOB_STORE_PREFETCH"] == "0"
    spec.store = None
    env = build_replica_env("j", "r1", spec, "WORKER", 0)
    assert not any(k.startswith("TPUJOB_STORE_") for k in env)


# --- payload env adapter -----------------------------------------------------

def test_store_from_env_and_process_zero_uploader():
    from tpu_operator.payload import warmstore

    assert warmstore.store_from_env({}) is None
    env = {"TPUJOB_STORE_URI": "fake://adapter", "TPUJOB_NAMESPACE": "ns",
           "TPUJOB_NAME": "jb", "TPUJOB_STORE_PARALLELISM": "2"}
    ws = warmstore.store_from_env(env)
    assert ws is not None and ws.prefix == "ns/jb"
    assert ws.upload_parallelism == 2
    # Malformed URI disables the store instead of failing the attempt.
    assert warmstore.store_from_env(
        {"TPUJOB_STORE_URI": "weird://nope"}) is None
    up = warmstore.uploader_from_env(env)
    assert up is not None
    up.close()
    # Only process 0 uploads (single remote writer, like the manifest).
    assert warmstore.uploader_from_env(
        {**env, "JAX_PROCESS_ID": "3"}) is None


def test_prefetch_records_startup_stage(tmp_path):
    from tpu_operator.payload import startup as startup_mod
    from tpu_operator.payload import warmstore

    # Seed the shared fake store with a checkpoint + a cache entry.
    sd = str(tmp_path / "sd")
    write_tree(sd, SAMPLE)
    ws = WarmStartStore(blob_mod.fake_backend("pf"), prefix="ns/jb")
    ws.upload_checkpoint(sd, 6)
    cache_src = str(tmp_path / "cs")
    write_tree(cache_src, {"jit_x-cache": b"exe"})
    ws.upload_cache(cache_src)

    cache_dir = str(tmp_path / "cache")
    ckpt_dir = str(tmp_path / "ckpt")
    env = {"TPUJOB_STORE_URI": "fake://pf", "TPUJOB_NAMESPACE": "ns",
           "TPUJOB_NAME": "jb", "JAX_COMPILATION_CACHE_DIR": cache_dir,
           "TPU_CHECKPOINT_DIR": ckpt_dir}
    assert warmstore.start_prefetch(env)
    result = warmstore.finish_prefetch(timeout=30.0)
    assert result["checkpointStep"] == 6
    assert result["cacheFiles"] == 1
    tracker = startup_mod.new_tracker()
    bd = tracker.breakdown()
    assert bd.get("prefetchHit") is True
    assert "prefetchSeconds" in bd
    assert os.path.isfile(os.path.join(cache_dir, "jit_x-cache"))
    assert os.path.isdir(os.path.join(ckpt_dir, "6"))
    # Disabled prefetch / unwired store: no thread, no stage.
    warmstore.reset_prefetch()
    assert not warmstore.start_prefetch({**env, "TPUJOB_STORE_PREFETCH": "0"})
    assert not warmstore.start_prefetch({})


def test_prefetch_miss_records_false(tmp_path):
    from tpu_operator.payload import startup as startup_mod
    from tpu_operator.payload import warmstore

    env = {"TPUJOB_STORE_URI": "fake://coldpf", "TPUJOB_NAMESPACE": "ns",
           "TPUJOB_NAME": "jb",
           "TPU_CHECKPOINT_DIR": str(tmp_path / "ck")}
    assert warmstore.start_prefetch(env)
    result = warmstore.finish_prefetch(timeout=30.0)
    assert result["checkpointStep"] is None
    assert startup_mod.new_tracker().breakdown()["prefetchHit"] is False


# --- Checkpointer integration ------------------------------------------------

def tiny_state(step=0):
    import jax.numpy as jnp

    return {"step": jnp.int32(step), "w": jnp.arange(64, dtype=jnp.float32)}


def test_checkpointer_uploads_verified_saves(tmp_path):
    from tpu_operator.payload import checkpoint

    be = FakeBackend()
    up = WriteBehindUploader(WarmStartStore(be, prefix="p"), fail_after=3)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1,
                                 uploader=up)
    ck.maybe_save(1, tiny_state(1))
    ck.maybe_save(2, tiny_state(2))
    ck.flush()
    assert up.flush(30.0)
    stats = ck.stats()
    assert stats["lastCheckpointStep"] == 2
    assert stats["lastUploadedStep"] == 2
    assert stats["uploadFailures"] == 0
    assert 2 in WarmStartStore(be, prefix="p").checkpoint_steps()
    ck.close()


def test_checkpointer_upload_escalation_exits_retryable(tmp_path):
    from tpu_operator.payload import checkpoint
    from tpu_operator.payload.bootstrap import EXIT_RETRYABLE

    def fault(_op, _key):
        raise BlobError("remote persistently down")

    up = WriteBehindUploader(
        WarmStartStore(FakeBackend(fault_hook=fault), prefix="p"),
        fail_after=2)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1,
                                 uploader=up)
    # Saves stay locally healthy while the remote fails; once the streak
    # reaches fail_after, the NEXT save boundary escalates retryably —
    # exactly the local save-failure contract.
    step = 1
    with pytest.raises(SystemExit) as exc:
        for step in range(1, 20):
            ck.maybe_save(step, tiny_state(step))
            ck.flush()
            up.flush(10.0)
    assert exc.value.code == EXIT_RETRYABLE
    assert step >= 2  # never on the first transient failure
    ck.close()


def test_quarantine_condemns_remote_copy(tmp_path):
    """Bugfix satellite, end to end at the Checkpointer level: a step
    uploaded remotely and later quarantined by the local restore walk is
    condemned in the remote index — a fresh node's prefetch never
    prefers it."""
    from tests.test_checkpoint_durability import corrupt_a_file
    from tpu_operator.payload import checkpoint

    be = FakeBackend()
    up = WriteBehindUploader(WarmStartStore(be, prefix="p"), fail_after=3)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1,
                                 uploader=up)
    ck.maybe_save(1, tiny_state(1))
    ck.maybe_save(2, tiny_state(2))
    ck.flush()
    assert up.flush(30.0)
    assert WarmStartStore(be, prefix="p").checkpoint_steps() == [1, 2]
    ck.close()

    corrupt_a_file(str(tmp_path / "ck" / "2"), keep_size=True)
    up2 = WriteBehindUploader(WarmStartStore(be, prefix="p"), fail_after=3)
    reader = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1,
                                     uploader=up2)
    _state, start = reader.restore(tiny_state())
    assert start == 1
    assert reader.restore_fallbacks == 1
    assert up2.flush(30.0)
    reader.close()
    assert WarmStartStore(be, prefix="p").checkpoint_steps() == [1]


def test_writebehind_stays_off_the_step_path(tmp_path):
    """The step-loop side of the non-blocking contract at Checkpointer
    granularity: with a 300 ms/op remote, interval saves must not slow
    down measurably vs no store at all (bench.py --store asserts the
    same with real timings; this is the fast unit-level pin)."""
    from tpu_operator.payload import checkpoint

    up = WriteBehindUploader(
        WarmStartStore(FakeBackend(latency=0.3), prefix="p"))
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1,
                                 uploader=up)
    ck.maybe_save(1, tiny_state(1))
    ck.flush()  # local verify done; upload now pending in background
    t0 = time.perf_counter()
    ck.maybe_save(2, tiny_state(2))
    ck.flush()
    assert time.perf_counter() - t0 < 2.0
    ck.close()


# --- heartbeat → statusserver → controller -----------------------------------

def test_heartbeat_body_carries_store_fields():
    from tpu_operator.payload.heartbeat import HeartbeatReporter

    posts = []
    rep = HeartbeatReporter("http://x", "job", poster=lambda _u, b:
                            posts.append(b))
    rep.report(5, {}, checkpoint={"saveFailures": 0, "restoreFallbacks": 0,
                                  "lastCheckpointStep": 4,
                                  "lastUploadedStep": 3,
                                  "uploadFailures": 2})
    assert posts[0]["storeLastUploadedStep"] == 3
    assert posts[0]["storeUploadFailures"] == 2


def test_statusserver_sanitizes_store_fields():
    from tpu_operator.controller.statusserver import Metrics, StatusServer

    server = StatusServer(0, metrics=Metrics())
    server.start()
    try:
        ok, msg = server.record_heartbeat(
            {"name": "x", "storeUploadFailures": -1})
        assert not ok and "negative" in msg
        ok, msg = server.record_heartbeat(
            {"name": "x", "storeLastUploadedStep": "zzz"})
        assert not ok and "non-numeric" in msg
        # Valid fields reach the standby gate (fields themselves accepted).
        ok, msg = server.record_heartbeat(
            {"name": "x", "storeLastUploadedStep": 4,
             "startup": {"prefetchSeconds": 0.5, "prefetchHit": True}})
        assert not ok and msg.startswith("standby")
    finally:
        server.stop()


def make_controller_with_job(name="st"):
    from tpu_operator.apis.tpujob.v1alpha1.types import TPUJob
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.controller.controller import Controller
    from tpu_operator.trainer.training import TrainingJob

    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=3600.0)
    job = TPUJob.from_dict({
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}"},
        "spec": {"replicaSpecs": []},
        "status": {"phase": "Running", "state": "Running", "attempt": 0,
                   "phaseTimeline": {"Creating":
                                     "2026-08-03T00:00:00.000000Z"}},
    })
    tj = TrainingJob(cs, None, job)
    controller.jobs[f"default/{name}"] = tj
    return controller, tj


def test_controller_folds_status_store_with_delta_accounting():
    controller, tj = make_controller_with_job()
    hb = {"time": "2026-08-03T00:01:00.000000Z", "step": 10, "attempt": 0,
          "storeLastUploadedStep": 8, "storeUploadFailures": 2}
    assert controller.record_heartbeat("default", "st", hb)
    st = tj.job.status.store
    assert st["lastUploadedStep"] == 8
    assert st["uploadFailures"] == 2
    assert controller.metrics.counter_value(
        "job_store_upload_failures_total",
        {"namespace": "default", "name": "st"}) == 2
    # New attempt resets the payload counter: lifetime keeps accumulating
    # via the per-attempt baseline, never double-counting.
    tj.job.status.attempt = 1
    hb2 = {"time": "2026-08-03T00:02:00.000000Z", "step": 2, "attempt": 1,
           "storeLastUploadedStep": 9, "storeUploadFailures": 1}
    assert controller.record_heartbeat("default", "st", hb2)
    st = tj.job.status.store
    assert st["uploadFailures"] == 3
    assert st["lastUploadedStep"] == 9
    assert st["attempt"] == 1


def test_controller_goodput_fold_and_prefetch_counters():
    controller, tj = make_controller_with_job("gp")
    metrics = controller.metrics
    # Attempt 0's startup breakdown: firstStep credited as useful work,
    # prefetch MISS ticked once.
    hb0 = {"time": "2026-08-03T00:00:30.000000Z", "step": 1, "attempt": 0,
           "startup": {"firstStepSeconds": 2.0, "prefetchSeconds": 0.0,
                       "prefetchHit": False}}
    assert controller.record_heartbeat("default", "gp", hb0)
    assert metrics.counter_value(
        "store_prefetch_misses_total",
        {"namespace": "default", "name": "gp"}) == 1
    # 60 steps at 0.5 s/step over the next beat.
    hb1 = {"time": "2026-08-03T00:01:00.000000Z", "step": 61, "attempt": 0,
           "stepTimeSeconds": 0.5}
    assert controller.record_heartbeat("default", "gp", hb1)
    gp = tj.job.status.goodput
    assert gp["usefulStepSeconds"] == pytest.approx(2.0 + 60 * 0.5)
    assert gp["wallclockSeconds"] == pytest.approx(60.0)
    assert gp["ratio"] == pytest.approx(32.0 / 60.0)
    assert metrics.counter_value(
        "job_goodput_ratio",
        {"namespace": "default", "name": "gp"}) == pytest.approx(32.0 / 60.0)
    # Attempt 1 after a preemption: prefetch HIT ticked once (retries of
    # the same attempt don't double-tick), useful time keeps accumulating.
    tj.job.status.attempt = 1
    hb2 = {"time": "2026-08-03T00:03:00.000000Z", "step": 55, "attempt": 1,
           "startup": {"firstStepSeconds": 1.0, "prefetchHit": True}}
    assert controller.record_heartbeat("default", "gp", hb2)
    assert controller.record_heartbeat("default", "gp", {
        **hb2, "time": "2026-08-03T00:03:10.000000Z"})
    assert metrics.counter_value(
        "store_prefetch_hits_total",
        {"namespace": "default", "name": "gp"}) == 1
    gp = tj.job.status.goodput
    assert gp["usefulStepSeconds"] == pytest.approx(33.0)
    # The ratio reflects the churn gap: 33 useful of 190 wall.
    assert gp["ratio"] == pytest.approx(33.0 / 190.0, abs=1e-5)
    # job_store_last_uploaded_step rides the statusserver gauge path —
    # referenced here for the status-contract rule; rendering is covered
    # by test_metrics_conformance's live-scrape test.


def test_statusserver_renders_store_gauge():
    from tpu_operator.controller.statusserver import Metrics, StatusServer

    class Store:
        @staticmethod
        def list(_ns=""):
            return [{"metadata": {"namespace": "default", "name": "sg"},
                     "status": {}}]

        @staticmethod
        def get(_ns, _name):
            return {"metadata": {"name": "sg", "namespace": "default"}}

    class Informer:
        store = Store()

    class Factory:
        informers = {}

    class Ctl:
        job_informer = Informer()
        factory = Factory()
        queue = []

        @staticmethod
        def record_heartbeat(_ns, _name, _hb):
            return True

    server = StatusServer(0, metrics=Metrics())
    server.start()
    server.set_controller(Ctl())
    try:
        ok, msg = server.record_heartbeat(
            {"name": "sg", "step": 3, "storeLastUploadedStep": 2})
        assert ok, msg
        text = server.render_metrics()
        assert ('job_store_last_uploaded_step'
                '{name="sg",namespace="default"} 2') in text
    finally:
        server.stop()


def test_ctl_describe_prints_store_and_goodput(capsys):
    import argparse

    from tpu_operator.cmd import ctl

    job = {
        "metadata": {"name": "rs", "namespace": "default"},
        "spec": {"replicaSpecs": [],
                 "store": {"backend": "localfs", "uri": "/warm",
                           "uploadParallelism": 4, "prefetch": True}},
        "status": {"phase": "Running", "state": "Running", "attempt": 1,
                   "store": {"lastUploadedStep": 42, "uploadFailures": 1},
                   "goodput": {"usefulStepSeconds": 80.0,
                               "wallclockSeconds": 100.0, "ratio": 0.8},
                   "startup": {"rendezvousSeconds": 0.2,
                               "prefetchSeconds": 1.5,
                               "compileSeconds": 3.0,
                               "firstStepSeconds": 0.5,
                               "cacheHit": True, "prefetchHit": True,
                               "attempt": 1}},
    }

    class Stub:
        class tpujobs:
            @staticmethod
            def get(_ns, _name):
                return job

        class events:
            @staticmethod
            def list(_ns):
                return []

    opts = argparse.Namespace(namespace="default", name="rs")
    assert ctl.cmd_describe(Stub, opts) == 0
    out = capsys.readouterr().out
    assert "Store:      localfs /warm — last uploaded step 42" in out
    assert "upload failures 1" in out
    assert "prefetch 1.50s" in out
    assert "prefetch hit" in out
    assert "Goodput:    80.0% (useful 80.0s / wallclock 100.0s)" in out


# --- slow: fake-backend faults × PR 4 corrupt-latest chaos -------------------

@pytest.mark.slow
def test_store_chaos_fresh_node_resume(tmp_path):
    """The composed chaos e2e: an attempt uploads through a FLAKY remote
    (transient faults on some puts), its newest LOCAL checkpoint is then
    corrupted (the PR 4 scenario) AND the newest REMOTE snapshot is
    corrupted too — a fresh node must still prefetch + restore the newest
    step that is actually intact, with every bad copy condemned."""
    import random

    from tests.test_checkpoint_durability import corrupt_a_file
    from tpu_operator.payload import checkpoint

    rng = random.Random(42)

    def flaky(op, _key):
        if op == "put" and rng.random() < 0.2:
            raise BlobError("transient remote blip")

    be = FakeBackend()
    be.fault_hook = flaky
    up = WriteBehindUploader(WarmStartStore(be, prefix="p"),
                             fail_after=1_000)
    ck = checkpoint.Checkpointer(str(tmp_path / "nodeA"), save_every=1,
                                 max_to_keep=10, uploader=up)
    for step in range(1, 7):
        ck.maybe_save(step, tiny_state(step))
        ck.flush()
        up.flush(30.0)
    # Flaky puts may have failed whole uploads; retry the tail clean so
    # the remote holds a useful history, as a longer run's later saves
    # would naturally achieve.
    be.fault_hook = None
    for step in (5, 6):
        if step not in WarmStartStore(be, prefix="p").checkpoint_steps():
            up.enqueue(step, os.path.join(str(tmp_path / "nodeA"),
                                          str(step)))
            up.flush(30.0)
    ck.close()
    remote_steps = WarmStartStore(be, prefix="p").checkpoint_steps()
    assert 6 in remote_steps

    # Chaos: the newest REMOTE snapshot's bytes rot.
    victim = be.list("p/checkpoints/6/data/")[0]
    be.corrupt(victim)

    # Fresh node: empty local dir; prefetch falls back past the rotten 6
    # to the newest intact snapshot, then the PR 4 verified walk restores.
    nodeB = str(tmp_path / "nodeB")
    ws = WarmStartStore(be, prefix="p")
    step, fallbacks = ws.prefetch_checkpoint(nodeB)
    assert fallbacks == 1 and step is not None and step < 6
    assert 6 not in ws.checkpoint_steps()
    reader = checkpoint.Checkpointer(nodeB, save_every=1)
    restored, start = reader.restore(tiny_state())
    assert start == step
    assert int(restored["step"]) == step
    reader.close()


# --- retention GC (spec.store.keepSnapshots) ---------------------------------


def test_retain_keeps_newest_n_marker_first(tmp_path):
    """retain(2) removes every verified snapshot but the newest two —
    condemn-then-delete, MARKER-FIRST (the PR-8 ordering): the victim's
    .corrupt marker must land before any of its objects is deleted, and
    the marker itself is removed once the tree is gone (a GC'd step is
    absence, not quarantine — markers must not accumulate)."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, SAMPLE)
    be = FakeBackend()
    ws = WarmStartStore(be, prefix="p", chunk_size=4096)
    for step in (1, 2, 3, 4):
        ws.upload_checkpoint(step_dir, step)
    removed = ws.retain(2)
    assert removed == 2
    assert ws.checkpoint_steps() == [3, 4]
    # No stray markers: a later prefetch sees clean absence.
    assert not [k for k in be.list("p/checkpoints/")
                if k.endswith(".corrupt")]
    # Survivors intact: a fresh node prefetches the newest.
    step, fallbacks = ws.prefetch_checkpoint(str(tmp_path / "fresh"))
    assert (step, fallbacks) == (4, 0)
    # Idempotent: nothing more to remove.
    assert ws.retain(2) == 0
    # keep <= 0 = keep everything (the default, pre-GC behavior).
    assert ws.retain(0) == 0


def test_retain_op_order_marker_before_delete(tmp_path):
    """Op-count/op-order proof on the fake backend: for each victim the
    marker PUT precedes every DELETE of the victim's objects, and the
    final op on the victim is the marker's own delete."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, {"w.bin": b"x" * 100})
    be = FakeBackend()
    ws = WarmStartStore(be, prefix="p", chunk_size=4096)
    ws.upload_checkpoint(step_dir, 1)
    ws.upload_checkpoint(step_dir, 2)
    puts_before = be.op_counts.get("put", 0)
    deletes_before = be.op_counts.get("delete", 0)
    objects_of_1 = [k for k in be.list("p/checkpoints/1/")]
    assert ws.retain(1) == 1
    # Exactly one marker put; deletes = victim's objects + the marker.
    assert be.op_counts.get("put", 0) - puts_before == 1
    assert (be.op_counts.get("delete", 0) - deletes_before
            == len(objects_of_1) + 1)
    assert be.list("p/checkpoints/1/") == []


def test_writebehind_retention_runs_after_commit(tmp_path):
    """The write-behind worker GCs AFTER each successful upload (never
    on failure, never on the step loop): keepSnapshots=2 holds the
    remote tree at the newest two as commits stream."""
    step_dir = str(tmp_path / "sd")
    write_tree(step_dir, {"w.bin": b"y" * 64})
    be = FakeBackend()
    ws = WarmStartStore(be, prefix="p", chunk_size=4096)
    up = WriteBehindUploader(ws, keep_snapshots=2)
    try:
        for step in (10, 20, 30):
            up.enqueue(step, step_dir)
            assert up.flush(10.0)
        assert ws.checkpoint_steps() == [20, 30]
        assert up.gc_removed == 1
    finally:
        up.close()


def test_uploader_from_env_wires_keep(tmp_path):
    from tpu_operator.payload import warmstore

    env = {"TPUJOB_STORE_URI": f"fake://keep-{os.getpid()}",
           "TPUJOB_STORE_BACKEND": "fake",
           "TPUJOB_STORE_KEEP": "3",
           "TPUJOB_NAMESPACE": "default", "TPUJOB_NAME": "kj",
           "JAX_PROCESS_ID": "0"}
    up = warmstore.uploader_from_env(env)
    try:
        assert up is not None and up.keep_snapshots == 3
    finally:
        up.close()
    # Malformed keep degrades to 0 (keep all), never kills the payload.
    up2 = warmstore.uploader_from_env({**env, "TPUJOB_STORE_KEEP": "lots"})
    try:
        assert up2 is not None and up2.keep_snapshots == 0
    finally:
        up2.close()
