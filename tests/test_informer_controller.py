"""Informer + controller loop tests over the fake clientset's watch streams.

This is the tier the reference could never run without a cluster: the full
event-driven loop (informers → workqueue → syncMXJob → reconcile) exercised
in-process (SURVEY.md §4 lesson: add an envtest-style tier).
"""

import threading
import time

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.controller.controller import Controller
from tpu_operator.testing.waiting import make_wait_for
from tests.test_types import make_template


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=5.0, interval=0.02)


def worker_job_dict(name="train", replicas=2, runtime_id="ab12"):
    return t.TPUJob(
        metadata={"name": name, "namespace": "default"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=replicas, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER)
            ],
            runtime_id=runtime_id,
        ),
    ).to_dict()


@pytest.fixture
def harness():
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)  # no resync churn in tests
    controller = Controller(cs, factory)
    stop = threading.Event()
    runner = threading.Thread(
        target=controller.run, args=(2, stop), daemon=True
    )
    runner.start()
    yield cs, controller
    stop.set()
    runner.join(timeout=5.0)


# --- informer-level ----------------------------------------------------------

def test_informer_cache_and_handlers():
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)
    inf = factory.informer_for("tpujobs")
    seen = {"adds": [], "updates": [], "deletes": []}
    inf.add_event_handler(
        on_add=lambda o: seen["adds"].append(o["metadata"]["name"]),
        on_update=lambda old, new: seen["updates"].append(new["metadata"]["name"]),
        on_delete=lambda o: seen["deletes"].append(o["metadata"]["name"]),
    )
    cs.tpujobs.create("default", worker_job_dict("pre-existing"))
    stop = threading.Event()
    factory.start(stop)
    assert factory.wait_for_cache_sync(timeout=5.0)
    try:
        assert wait_for(lambda: "pre-existing" in seen["adds"])
        cs.tpujobs.create("default", worker_job_dict("late"))
        assert wait_for(lambda: "late" in seen["adds"])
        assert inf.store.get("default", "late") is not None

        obj = cs.tpujobs.get("default", "late")
        obj["status"] = {"phase": "Running"}
        cs.tpujobs.update("default", obj)
        assert wait_for(lambda: "late" in seen["updates"])

        cs.tpujobs.delete("default", "late")
        assert wait_for(lambda: "late" in seen["deletes"])
        assert inf.store.get("default", "late") is None
    finally:
        stop.set()


# --- controller end-to-end over fakes ----------------------------------------

def test_controller_reconciles_created_job(harness):
    cs, controller = harness
    cs.tpujobs.create("default", worker_job_dict())
    assert wait_for(lambda: len(cs.pods.list("default")) == 2)
    assert wait_for(lambda: len(cs.services.list("default")) == 3)
    stored = cs.tpujobs.get("default", "train")
    assert stored["status"]["phase"] == t.TPUJobPhase.CREATING

    # Mark pods running → pod informer enqueues owner → phase Running,
    # without any resync tick (the reference needed the 30s resync here).
    for p in cs.pods.list("default"):
        p["status"] = {
            "phase": "Running",
            "containerStatuses": [{"name": "tpu", "state": {"running": {}}}],
        }
        cs.pods.update("default", p)
    assert wait_for(
        lambda: cs.tpujobs.get("default", "train")["status"]["phase"]
        == t.TPUJobPhase.RUNNING
    )


def test_controller_success_flow(harness):
    cs, _controller = harness
    cs.tpujobs.create("default", worker_job_dict())
    assert wait_for(lambda: len(cs.pods.list("default")) == 2)
    for p in cs.pods.list("default"):
        p["status"] = {
            "phase": "Succeeded",
            "containerStatuses": [
                {"name": "tpu", "state": {"terminated": {"exitCode": 0}}}
            ],
        }
        cs.pods.update("default", p)
    assert wait_for(
        lambda: cs.tpujobs.get("default", "train")["status"]["phase"]
        == t.TPUJobPhase.DONE
    )
    stored = cs.tpujobs.get("default", "train")
    assert stored["status"]["state"] == t.State.SUCCEEDED
    # pods retained for logs
    assert len(cs.pods.list("default")) == 2


def test_controller_group_restart_flow(harness):
    cs, _controller = harness
    job = worker_job_dict()
    # instant re-gang: the backoff path is covered by test_time_recovery.py
    job["spec"]["restartBackoff"] = {"baseSeconds": 0, "maxSeconds": 0}
    cs.tpujobs.create("default", job)
    assert wait_for(lambda: len(cs.pods.list("default")) == 2)
    victim = cs.pods.list("default")[0]
    victim["status"] = {
        "phase": "Failed",
        "containerStatuses": [
            {"name": "tpu", "state": {"terminated": {"exitCode": 137}}}
        ],
    }
    cs.pods.update("default", victim)
    # whole group torn down and recreated under attempt=1
    assert wait_for(
        lambda: len(cs.pods.list("default", label_selector="attempt=1")) == 2
    )
    assert cs.tpujobs.get("default", "train")["status"]["attempt"] == 1


def test_controller_forgets_deleted_job(harness):
    cs, controller = harness
    cs.tpujobs.create("default", worker_job_dict())
    assert wait_for(lambda: "default/train" in controller.jobs)
    cs.tpujobs.delete("default", "train")
    assert wait_for(lambda: "default/train" not in controller.jobs)


def test_controller_new_uid_rebuilds_job(harness):
    cs, controller = harness
    cs.tpujobs.create("default", worker_job_dict(runtime_id="one1"))
    assert wait_for(lambda: "default/train" in controller.jobs)
    uid1 = controller.jobs["default/train"].uid
    cs.tpujobs.delete("default", "train")
    assert wait_for(lambda: "default/train" not in controller.jobs)
    cs.tpujobs.create("default", worker_job_dict(runtime_id="two2"))
    assert wait_for(
        lambda: "default/train" in controller.jobs
        and controller.jobs["default/train"].uid != uid1
    )


def test_gc_removes_orphans():
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)
    controller = Controller(cs, factory)
    # Child pod whose owner TPUJob does not exist
    cs.pods.create("default", {
        "metadata": {
            "name": "orphan-pod",
            "labels": {"tpuoperator.dev": "", "job_name": "ghost"},
            "ownerReferences": [
                {"kind": "TPUJob", "name": "ghost", "controller": True}
            ],
        }
    })
    # Child whose owner exists → kept
    cs.tpujobs.create("default", worker_job_dict("alive"))
    cs.pods.create("default", {
        "metadata": {
            "name": "kept-pod",
            "labels": {"tpuoperator.dev": "", "job_name": "alive"},
            "ownerReferences": [
                {"kind": "TPUJob", "name": "alive", "controller": True}
            ],
        }
    })
    deleted = controller.run_gc_once()
    assert deleted == 1
    names = [p["metadata"]["name"] for p in cs.pods.list("default")]
    assert names == ["kept-pod"]


def test_controller_reconciles_100_concurrent_jobs():
    # The reference's design scale: O(100) concurrent jobs per cluster
    # (tf_job_design_doc.md:24). Here with 4 reconcile workers (the
    # reference was only safe at threadiness 1): every job must reach
    # Creating/Running with its pods and headless service materialized,
    # and no job may bleed resources into another's label space.
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)
    controller = Controller(cs, factory)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(4, stop),
                              daemon=True)
    runner.start()
    try:
        n = 100
        for i in range(n):
            cs.tpujobs.create(
                "default", worker_job_dict(name=f"job-{i:03d}", replicas=2,
                                           runtime_id=f"r{i:03d}"))

        def all_reconciled():
            jobs = cs.tpujobs.list("default")
            phases = [j.get("status", {}).get("phase", "") for j in jobs]
            return len(jobs) == n and all(
                p in ("Creating", "Running") for p in phases)

        assert wait_for(all_reconciled, timeout=60.0), [
            (j["metadata"]["name"], j.get("status", {}).get("phase"))
            for j in cs.tpujobs.list("default")
            if j.get("status", {}).get("phase") not in ("Creating", "Running")
        ][:5]
        assert wait_for(lambda: len(cs.pods.list("default")) == 2 * n,
                        timeout=30.0), len(cs.pods.list("default"))
        # headless + one per replica index = 3 services per job
        assert wait_for(lambda: len(cs.services.list("default")) == 3 * n,
                        timeout=30.0), len(cs.services.list("default"))
        # no cross-job bleed: every pod's job label matches its name prefix
        for pod in cs.pods.list("default"):
            labels = pod["metadata"]["labels"]
            assert pod["metadata"]["name"].startswith(labels["job_name"])
    finally:
        stop.set()
        runner.join(timeout=5.0)
