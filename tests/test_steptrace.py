"""Data-plane flight recorder: recorder unit tests, the stepTiming
heartbeat chain (payload → statusserver sanitization → controller fold →
CRD status/metrics), gang straggler detection, the postmortem ring-buffer
dump, and the per-job metric-series cleanup on job deletion.

The e2e section drives the REAL operator over the in-process HTTP
apiserver (strict status-subresource schema admission) with simulated
gang members posting cadence beats — one artificially slowed — and
asserts the straggler surfaces in status.stragglers, the
StragglerDetected event, ``tpujobctl describe``, and ``/metrics``.
"""

import contextlib
import io
import json
import threading

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod
from tpu_operator.apis.tpujob.v1alpha1 import types
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.apis.tpujob.validation import (
    ValidationError,
    validate_tpujob_spec,
)
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import steptrace
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.trainer.training import TrainingJob

wait_for = make_wait_for(timeout=20.0, interval=0.05)


class FakeClock:
    """Deterministic perf_counter: advances by the programmed increments."""

    def __init__(self, *increments):
        self.now = 0.0
        self.steps = list(increments)

    def __call__(self):
        if self.steps:
            self.now += self.steps.pop(0)
        return self.now


def worker_job(name, replicas=1, spec_extra=None):
    spec = {"replicaSpecs": [{
        "replicas": replicas, "tpuReplicaType": "WORKER", "tpuPort": 8476,
        "template": {"spec": {"containers": [{"name": "tpu",
                                              "image": "x"}]}}}]}
    spec.update(spec_extra or {})
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


# --- recorder unit -----------------------------------------------------------

def test_recorder_laps_attribute_time_to_phases():
    clock = FakeClock()
    rec = steptrace.StepRecorder(capacity=16, clock=clock)
    clock.steps = [0.0,   # begin
                   0.010,  # DATA lap
                   0.001,  # DISPATCH lap
                   0.100,  # COMPUTE lap
                   0.002,  # CHECKPOINT lap
                   0.003,  # HOST lap
                   0.0]    # commit total read
    rec.begin(7)
    rec.lap(steptrace.DATA)
    rec.lap(steptrace.DISPATCH)
    rec.lap(steptrace.COMPUTE)
    rec.lap(steptrace.CHECKPOINT)
    rec.lap(steptrace.HOST)
    rec.commit()
    (row,) = rec.snapshot()
    assert row["step"] == 7
    assert row["dataWait"] == pytest.approx(0.010)
    assert row["dispatch"] == pytest.approx(0.001)
    assert row["compute"] == pytest.approx(0.100)
    assert row["checkpoint"] == pytest.approx(0.002)
    assert row["host"] == pytest.approx(0.003)
    assert row["stepSeconds"] == pytest.approx(0.116)


def test_recorder_ring_is_bounded_and_summary_windows_are_disjoint():
    rec = steptrace.StepRecorder(capacity=8)
    for i in range(20):
        rec.begin(i)
        rec.lap(steptrace.COMPUTE)
        rec.commit()
    snap = rec.snapshot()
    assert len(snap) == 8                      # ring bound
    assert [r["step"] for r in snap] == list(range(12, 20))  # newest kept
    assert rec.steps_recorded == 20

    s1 = rec.summary()
    # The digest window is bounded at the ring capacity too: with no
    # heartbeat draining it (standalone payload), accumulation must not
    # grow O(steps) — the digest covers the newest `capacity` steps.
    assert s1["steps"] == 8
    assert rec.summary() is None               # window reset: nothing new
    rec.begin(20)
    rec.lap(steptrace.COMPUTE)
    rec.commit()
    s2 = rec.summary()
    assert s2["steps"] == 1                    # disjoint second window


def test_recorder_digest_percentiles():
    # 100 samples 0.01..1.00: nearest-rank p50 = 0.50, p95 = 0.95.
    values = [i / 100.0 for i in range(1, 101)]
    d = steptrace.digest(values)
    assert d["p50Seconds"] == pytest.approx(0.50)
    assert d["p95Seconds"] == pytest.approx(0.95)
    assert d["maxSeconds"] == pytest.approx(1.00)


def test_recorder_summary_wire_shape():
    rec = steptrace.StepRecorder(capacity=16)
    for i in range(4):
        rec.begin(i)
        rec.lap(steptrace.DATA)
        rec.lap(steptrace.COMPUTE)
        rec.commit()
    s = rec.summary()
    assert set(s) == {"steps", "stepP50Seconds", "stepP95Seconds",
                      "stepMaxSeconds", "stepLocalP95Seconds", "phases"}
    assert set(s["phases"]) == {"dataWait", "compute"}
    for stats in s["phases"].values():
        assert set(stats) == set(steptrace.DIGEST_KEYS)


def test_recorder_abandon_drops_partial_step():
    rec = steptrace.StepRecorder()
    rec.begin(0)
    rec.lap(steptrace.DATA)
    rec.abandon()
    rec.commit()  # no-op: nothing in flight
    assert rec.snapshot() == [] and rec.summary() is None


def test_recorder_dump_and_postmortem(tmp_path):
    ckpt = tmp_path / "data" / "ckpt"
    ckpt.mkdir(parents=True)
    rec = steptrace.StepRecorder(capacity=8)
    for i in range(3):
        rec.begin(i)
        rec.lap(steptrace.COMPUTE)
        rec.commit()
    path = steptrace.postmortem_dump(rec, str(ckpt), env={
        "TPUJOB_NAME": "pm", "TPUJOB_NAMESPACE": "ns",
        "TPUJOB_ATTEMPT": "2", "JAX_PROCESS_ID": "1"})
    # Artifact lands NEXT TO the checkpoint dir, named by attempt+process.
    assert path == str(tmp_path / "data" / "steptrace-attempt2-p1.json")
    body = json.loads(open(path).read())
    assert body["kind"] == "tpujob-steptrace"
    assert body["job"] == "pm" and body["attempt"] == 2
    assert body["processId"] == 1
    assert [r["step"] for r in body["steps"]] == [0, 1, 2]
    # No checkpoint dir → no dump, no raise (best-effort contract).
    assert steptrace.postmortem_dump(rec, "", env={}) is None
    # Unwritable destination (sibling AND in-dir fallback) → logged None,
    # never an exception.
    assert steptrace.postmortem_dump(
        rec, "/proc/definitely-unwritable/ck", env={}) is None
    # checkpointDir that IS a top-level mount point: the sibling slot
    # would be the container rootfs — the artifact goes INSIDE instead.
    assert steptrace.postmortem_path("/ckpt", 1, 2) \
        == "/ckpt/steptrace-attempt1-p2.json"


def test_from_env_gating():
    assert steptrace.from_env({}) is not None                # default ON
    assert steptrace.from_env({"TPUJOB_STEPTRACE_ENABLED": "0"}) is None
    assert steptrace.from_env({"TPUJOB_STEPTRACE_ENABLED": "false"}) is None
    rec = steptrace.from_env({"TPUJOB_STEPTRACE_BUFFER": "64"})
    assert rec.capacity == 64
    # malformed buffer falls back to the default, never kills training
    rec = steptrace.from_env({"TPUJOB_STEPTRACE_BUFFER": "lots"})
    assert rec.capacity == steptrace.DEFAULT_BUFFER_STEPS


# --- spec wiring -------------------------------------------------------------

def test_steptrace_spec_roundtrip_defaults_validation():
    doc = worker_job("t", spec_extra={
        "stepTrace": {"bufferSteps": 128, "stragglerRatio": 1.5}})
    spec = types.TPUJobSpec.from_dict(doc["spec"])
    assert spec.step_trace.enabled is True
    assert spec.step_trace.buffer_steps == 128
    assert spec.step_trace.straggler_ratio == 1.5
    assert spec.to_dict()["stepTrace"] == {
        "enabled": True, "bufferSteps": 128, "stragglerRatio": 1.5}
    validate_tpujob_spec(set_defaults(spec))

    # absent block round-trips absent (None = the defaults)
    bare = types.TPUJobSpec.from_dict(worker_job("t")["spec"])
    assert bare.step_trace is None and "stepTrace" not in bare.to_dict()

    # strict schema admits the block and rejects unknown keys inside it
    ok, _ = schema_mod.validate_tpujob_strict(doc)
    assert ok
    bad = worker_job("t", spec_extra={"stepTrace": {"bufSteps": 1}})
    ok, msg = schema_mod.validate_tpujob_strict(bad)
    assert not ok and "bufSteps" in msg

    # explicit junk reaches validation and fails loudly (never clamped) —
    # even on a DISABLED block: the generated CRD enforces the same
    # minimums unconditionally, so an enabled-only check would diverge
    # the fake apiserver from a real one
    for block in ({"bufferSteps": 4}, {"stragglerRatio": 0.5},
                  {"enabled": False, "bufferSteps": 4}):
        junk = types.TPUJobSpec.from_dict(
            worker_job("t", spec_extra={"stepTrace": block})["spec"])
        with pytest.raises(ValidationError):
            validate_tpujob_spec(set_defaults(junk))


def test_steptrace_env_injection():
    from tpu_operator.trainer.replicas import build_replica_env

    spec = types.TPUJobSpec.from_dict(worker_job("j", spec_extra={
        "stepTrace": {"bufferSteps": 256}})["spec"])
    set_defaults(spec)
    env = build_replica_env("j", "rt1", spec, types.TPUReplicaType.WORKER,
                            0, 0)
    assert env["TPUJOB_STEPTRACE_ENABLED"] == "1"
    assert env["TPUJOB_STEPTRACE_BUFFER"] == "256"

    off = types.TPUJobSpec.from_dict(worker_job("j", spec_extra={
        "stepTrace": {"enabled": False}})["spec"])
    env = build_replica_env("j", "rt1", off, types.TPUReplicaType.WORKER,
                            0, 0)
    assert env["TPUJOB_STEPTRACE_ENABLED"] == "0"

    # no block → no injection (recorder default-on without env)
    bare = types.TPUJobSpec.from_dict(worker_job("j")["spec"])
    env = build_replica_env("j", "rt1", bare, types.TPUReplicaType.WORKER,
                            0, 0)
    assert "TPUJOB_STEPTRACE_ENABLED" not in env


# --- heartbeat reporter ------------------------------------------------------

def _capture_reporter(**kw):
    posts = []
    reporter = heartbeat_mod.HeartbeatReporter(
        "http://x", "j", poster=lambda _url, body: posts.append(body),
        clock=FakeClock(), **kw)
    return reporter, posts


def test_report_carries_steptiming():
    reporter, posts = _capture_reporter()
    digest = {"steps": 5, "stepP95Seconds": 0.2,
              "phases": {"compute": {"p95Seconds": 0.18}}}
    assert reporter.report(5, {"loss": 1.0}, steptiming=digest)
    assert posts[0]["stepTiming"] == digest
    assert posts[0]["loss"] == 1.0
    # None digest (no steps since last beat) → field simply absent
    assert reporter.report(6, {"loss": 0.9}, steptiming=None)
    assert "stepTiming" not in posts[1]


def test_cadence_reporters_not_built_when_steptrace_disabled():
    """spec.stepTrace.enabled: false → the detector no-ops every cadence
    beat, so non-zero processes must not build reporters at all (63
    discarded POSTs per interval on a 64-gang); process 0's stream is
    independent telemetry and keeps flowing."""
    env = {"TPUJOB_STATUS_URL": "http://x", "TPUJOB_NAME": "j",
           "JAX_PROCESS_ID": "1", "TPUJOB_STEPTRACE_ENABLED": "0"}
    assert heartbeat_mod.from_env(env) is None
    r0 = heartbeat_mod.from_env({**env, "JAX_PROCESS_ID": "0"})
    assert r0 is not None and not r0.cadence_only


def test_cadence_only_reporter_posts_minimal_body():
    reporter, posts = _capture_reporter(process_id=3, cadence_only=True,
                                        tokens_per_batch=4096)
    reporter._clock = FakeClock(0.0, 10.0)  # two posts 10 s apart
    digest = {"steps": 3, "stepP95Seconds": 0.5}
    assert reporter.report(10, {"loss": 2.0},
                           checkpoint={"saveFailures": 1},
                           startup={"compileSeconds": 3.0},
                           steptiming=digest)
    assert reporter.report(20, {"loss": 1.5}, steptiming=digest)
    first, second = posts
    # identity + cadence + digest only — no loss/tokens/checkpoint/startup
    assert first["processId"] == 3 and first["stepTiming"] == digest
    for key in ("loss", "tokensPerSec", "startup", "lastCheckpointStep",
                "checkpointSaveFailures"):
        assert key not in first and key not in second
    assert second["stepTimeSeconds"] == pytest.approx(1.0)  # 10 s / 10 steps


# --- statusserver sanitization ----------------------------------------------

class _ControllerStub:
    """Minimal controller: knows one job, captures sanitized heartbeats."""

    class _Store:
        def get(self, _ns, name):
            return {"metadata": {"namespace": "default", "name": name}} \
                if name == "jb" else None

        def list(self):
            return []

    class _Informer:
        def __init__(self):
            self.store = _ControllerStub._Store()

    def __init__(self):
        self.job_informer = self._Informer()
        self.heartbeats = []

    def record_heartbeat(self, _ns, _name, hb):
        self.heartbeats.append(hb)
        return True


@pytest.fixture()
def sanitizing_server():
    server = StatusServer(0)
    server.start()  # stop() blocks unless serve_forever is running
    stub = _ControllerStub()
    server.set_controller(stub)
    try:
        yield server, stub
    finally:
        server.stop()


def test_steptiming_sanitization_rejects_bad_values(sanitizing_server):
    server, _stub = sanitizing_server
    base = {"namespace": "default", "name": "jb", "step": 1}

    ok, msg = server.record_heartbeat({**base, "stepTiming": "fast"})
    assert not ok and "must be an object" in msg
    ok, msg = server.record_heartbeat(
        {**base, "stepTiming": {"stepP95Seconds": -0.1}})
    assert not ok and "stepP95Seconds" in msg
    ok, msg = server.record_heartbeat(
        {**base, "stepTiming": {"stepP50Seconds": float("nan")}})
    assert not ok
    ok, msg = server.record_heartbeat(
        {**base, "stepTiming": {"steps": -1}})
    assert not ok and "negative" in msg
    ok, msg = server.record_heartbeat(
        {**base, "stepTiming": {"phases": {"compute": {
            "p95Seconds": "slow"}}}})
    assert not ok and "non-numeric" in msg
    ok, msg = server.record_heartbeat(
        {**base, "stepTiming": {"phases": {"compute": {
            "maxSeconds": -3}}}})
    assert not ok and "maxSeconds" in msg


def test_steptiming_sanitization_drops_unknown_phases(sanitizing_server):
    server, stub = sanitizing_server
    ok, _ = server.record_heartbeat({
        "namespace": "default", "name": "jb", "step": 1,
        "stepTiming": {"steps": 2,
                       "phases": {"compute": {"p95Seconds": 0.1},
                                  "quantumFlux": {"p95Seconds": 9.9}}}})
    assert ok
    (hb,) = stub.heartbeats
    # known phase kept, unknown phase dropped (forward compatibility),
    # never persisted toward the strict CRD schema
    assert set(hb["stepTiming"]["phases"]) == {"compute"}
    ok, _ = schema_mod.validate_tpujob_strict(worker_job("jb"))
    assert ok


def test_nonzero_process_beat_skips_gauge_stash(sanitizing_server):
    server, _stub = sanitizing_server
    ok, _ = server.record_heartbeat({
        "namespace": "default", "name": "jb", "step": 50, "processId": 2,
        "stepTimeSeconds": 0.5})
    assert ok
    with server._heartbeats_lock:
        assert ("default", "jb") not in server._heartbeats
    ok, _ = server.record_heartbeat({
        "namespace": "default", "name": "jb", "step": 50, "processId": 0})
    assert ok
    with server._heartbeats_lock:
        assert server._heartbeats[("default", "jb")]["step"] == 50


# --- controller fold + straggler detection ----------------------------------

def _controller_with_job(name="sj", spec_extra=None, attempt=0):
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=3600.0)
    job = types.TPUJob.from_dict(worker_job(name, spec_extra=spec_extra))
    job.metadata["uid"] = "u1"
    job.status.attempt = attempt
    controller.jobs[f"default/{name}"] = TrainingJob(
        cs, controller.recorder, job)
    return cs, controller, controller.jobs[f"default/{name}"]


def _beat(pid, local_p95, step=100, attempt=0,
          time="2026-08-04T00:00:00.000000Z"):
    """One cadence beat. The gang-synchronized whole-step p95 is the SAME
    for every member (1.0 s — the collectives equalize it; that is the
    whole point of the local-time signal); ``local_p95`` is the
    per-process local share the detector compares."""
    return {"time": time, "step": step, "attempt": attempt,
            "processId": pid,
            "stepTiming": {"steps": 10, "stepP95Seconds": 1.0,
                           "stepLocalP95Seconds": local_p95,
                           "phases": {"compute": {"p50Seconds": 0.85,
                                                  "p95Seconds": 0.9,
                                                  "maxSeconds": 1.0}}}}


def test_steptiming_folds_into_status_and_histograms():
    _cs, controller, tj = _controller_with_job()
    assert controller.record_heartbeat("default", "sj", _beat(0, 0.25))
    st = tj.job.status.step_timing
    assert st["stepP95Seconds"] == 1.0
    assert st["stepLocalP95Seconds"] == 0.25
    assert st["attempt"] == 0 and st["processId"] == 0
    assert st["phases"]["compute"]["p95Seconds"] == 0.9
    hist = controller.metrics.histogram_snapshot(
        "job_step_phase_seconds", labels={"phase": "compute"})
    assert hist["count"] == 1 and hist["sum"] == pytest.approx(0.9)
    # a second digest observes again (windows are disjoint by contract)
    assert controller.record_heartbeat("default", "sj",
                                       _beat(0, 0.30, step=110))
    hist = controller.metrics.histogram_snapshot(
        "job_step_phase_seconds", labels={"phase": "compute"})
    assert hist["count"] == 2


def test_straggler_flagged_event_gauge_and_clearing():
    cs, controller, tj = _controller_with_job()
    # gang of 4, LOCAL p95s: pids 0-2 healthy at 0.1 s, pid 3 at 0.5 s
    # (5x median) — the whole-step p95 is identical across the gang (the
    # collectives equalize it), which is exactly why the detector keys on
    # the local share.
    for pid in (0, 1, 2):
        assert controller.record_heartbeat("default", "sj", _beat(pid, 0.1))
    assert tj.job.status.stragglers == []      # nobody above 2x yet
    assert controller.record_heartbeat("default", "sj", _beat(3, 0.5))
    (s,) = tj.job.status.stragglers
    assert s["processId"] == 3
    assert s["ratio"] == pytest.approx(5.0)
    assert s["gangMedianSeconds"] == pytest.approx(0.1)
    assert controller.metrics.counter_value(
        "job_straggler_ratio",
        labels={"namespace": "default", "name": "sj"}) == pytest.approx(5.0)
    events = [e for e in cs.events.list("default")
              if e.get("reason") == "StragglerDetected"]
    assert len(events) == 1 and "process 3" in events[0]["message"]
    # the flagged set change forced a persist enqueue
    assert controller.queue.get(timeout=0) == "default/sj"
    controller.queue.done("default/sj")

    # same straggler again: no second event, no forced persist, and the
    # status entry stays the FROZEN flagging snapshot (a per-beat value
    # refresh would make every reconcile see a critical stragglers delta
    # and bypass the writeback limiter); the gauge tracks the drift
    assert controller.record_heartbeat("default", "sj",
                                       _beat(3, 0.6, step=120))
    events = [e for e in cs.events.list("default")
              if e.get("reason") == "StragglerDetected"]
    assert len(events) == 1
    (s2,) = tj.job.status.stragglers
    assert s2["p95Seconds"] == pytest.approx(0.5)   # snapshot, not 0.6
    assert controller.metrics.counter_value(
        "job_straggler_ratio",
        labels={"namespace": "default", "name": "sj"}) == pytest.approx(6.0)

    # recovery: pid 3 back to median → flag clears (and that change
    # persists: an eviction signal must not linger)
    assert controller.record_heartbeat("default", "sj",
                                       _beat(3, 0.1, step=130))
    assert tj.job.status.stragglers == []

    # the status roll-up passes the strict CRD status schema
    assert controller.record_heartbeat("default", "sj",
                                       _beat(3, 0.9, step=140))
    ok, msg = schema_mod.validate_tpujob_strict(tj.job.to_dict())
    assert ok, msg


def test_straggler_respects_spec_ratio_and_enabled():
    # custom ratio 6.0: a 5x member is NOT flagged
    _cs, controller, tj = _controller_with_job(
        spec_extra={"stepTrace": {"stragglerRatio": 6.0}})
    for pid, p95 in ((0, 0.1), (1, 0.1), (2, 0.1), (3, 0.5)):
        assert controller.record_heartbeat("default", "sj", _beat(pid, p95))
    assert tj.job.status.stragglers == []

    # disabled recorder: no detection at all
    _cs, controller, tj = _controller_with_job(
        spec_extra={"stepTrace": {"enabled": False}})
    for pid, p95 in ((0, 0.1), (1, 0.1), (2, 0.1), (3, 5.0)):
        assert controller.record_heartbeat("default", "sj", _beat(pid, p95))
    assert tj.job.status.stragglers == []


def test_straggler_gauge_respects_materiality_floor():
    """µs-level local-time ratios between healthy device-bound hosts are
    noise: the materiality floor suppresses them from the FLAG and from
    the GAUGE alike — the gauge's help text promises above-threshold
    means flagged, so it must never advertise a ratio the detector
    discarded."""
    _cs, controller, tj = _controller_with_job()
    for pid, local in ((0, 1e-6), (1, 1e-6), (2, 1e-6), (3, 20e-6)):
        assert controller.record_heartbeat("default", "sj",
                                           _beat(pid, local))
    assert tj.job.status.stragglers == []     # 20x ratio, but µs vs a 1 s step
    assert controller.metrics.counter_value(
        "job_straggler_ratio",
        labels={"namespace": "default", "name": "sj"}) == pytest.approx(1.0)


def test_straggler_falls_back_to_step_time_without_digest():
    """Digest-less payloads (recorder off, old payload) still get
    detection from the plain stepTimeSeconds cadence."""
    _cs, controller, tj = _controller_with_job()
    for pid, sec in ((0, 0.1), (1, 0.1), (2, 0.1)):
        hb = {"time": "2026-08-04T00:00:00.000000Z", "step": 100,
              "attempt": 0, "processId": pid, "stepTimeSeconds": sec}
        assert controller.record_heartbeat("default", "sj", hb)
    hb = {"time": "2026-08-04T00:00:00.000000Z", "step": 100,
          "attempt": 0, "processId": 3, "stepTimeSeconds": 0.4}
    assert controller.record_heartbeat("default", "sj", hb)
    (s,) = tj.job.status.stragglers
    assert s["processId"] == 3 and s["ratio"] == pytest.approx(4.0)


def test_stale_generation_cadence_dropped_and_attempt_resets():
    _cs, controller, tj = _controller_with_job(attempt=2)
    # stale-generation beat carrying stepTiming: dropped whole (PR-2 rule)
    assert controller.record_heartbeat(
        "default", "sj", _beat(3, 9.9, attempt=1)) is None
    assert tj.job.status.stragglers == []
    assert tj.job.status.step_timing is None

    # attempt 2 cadence accumulates...
    for pid, p95 in ((0, 0.1), (1, 0.1), (2, 0.1), (3, 0.5)):
        assert controller.record_heartbeat("default", "sj",
                                           _beat(pid, p95, attempt=2))
    assert tj.job.status.stragglers
    # ...and an attempt bump resets the gang map: the new generation is
    # judged only on its own beats
    tj.job.status.attempt = 3
    assert controller.record_heartbeat("default", "sj",
                                       _beat(0, 0.1, attempt=3))
    assert controller._gang_cadence["default/sj"]["procs"].keys() == {0}


def test_cadence_entries_expire_and_ghosts_do_not_skew_median():
    """A member that stopped posting (dead pod, replaced replica) must
    not pin the gang median at its frozen last value forever, and the
    per-job map stays bounded — the HEARTBEAT_CAP slow-leak class."""
    from tpu_operator.controller import controller as controller_mod

    clock = {"now": 1_000.0}
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=3600.0,
                            wall_clock=lambda: clock["now"])
    job = types.TPUJob.from_dict(worker_job("sj"))
    job.metadata["uid"] = "u1"
    controller.jobs["default/sj"] = TrainingJob(cs, controller.recorder, job)
    tj = controller.jobs["default/sj"]
    for pid, local in ((0, 0.1), (1, 0.1), (2, 0.1), (3, 0.5)):
        assert controller.record_heartbeat("default", "sj",
                                           _beat(pid, local))
    assert [s["processId"] for s in tj.job.status.stragglers] == [3]

    # everyone but pid 0 goes silent past the expiry: the ghosts drop,
    # the gang shrinks below 2, and the stale flag clears
    clock["now"] += controller_mod.CADENCE_EXPIRY_SECONDS + 1
    assert controller.record_heartbeat("default", "sj",
                                       _beat(0, 0.1, step=200))
    procs = controller._gang_cadence["default/sj"]["procs"]
    assert set(procs) == {0}
    assert tj.job.status.stragglers == []


def test_two_member_gang_uses_even_median():
    """len-2 gang: median is the mean of both members, so the flagging
    ratio tops out below 2.0 — the default threshold deliberately cannot
    fire on a pair (one member being 'half the gang' is not a straggler
    signal); a lower spec ratio can opt in."""
    _cs, controller, tj = _controller_with_job(
        spec_extra={"stepTrace": {"stragglerRatio": 1.5}})
    assert controller.record_heartbeat("default", "sj", _beat(0, 0.1))
    assert controller.record_heartbeat("default", "sj", _beat(1, 0.9))
    (s,) = tj.job.status.stragglers
    assert s["processId"] == 1
    assert s["gangMedianSeconds"] == pytest.approx(0.5)
    assert s["ratio"] == pytest.approx(1.8)


def test_per_job_series_removed_on_job_deletion():
    """Satellite: ALL registry-resident per-job labeled series — the
    PR 8 goodput gauge, the new straggler gauge, and the per-job
    counters — are dropped when the job is deleted, so a long-lived
    operator never accumulates dead series (the PR-1 event-cache
    slow-leak class)."""
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0))
    labels = {"namespace": "default", "name": "gone"}
    controller.metrics.set_gauge("job_goodput_ratio", 0.5, labels=labels)
    controller.metrics.set_gauge("job_straggler_ratio", 3.0, labels=labels)
    for counter in ("job_checkpoint_save_failures_total",
                    "job_checkpoint_restore_fallbacks_total",
                    "job_store_upload_failures_total",
                    "compilation_cache_hits_total",
                    "store_prefetch_hits_total",
                    "store_prefetch_misses_total"):
        controller.metrics.inc(counter, labels=labels)
    rendered = "\n".join(controller.metrics.render_lines())
    assert 'name="gone"' in rendered

    # job absent from the informer cache → the deletion branch runs
    assert controller.sync_tpujob("default/gone") is True
    rendered = "\n".join(controller.metrics.render_lines())
    assert 'name="gone"' not in rendered
    assert "default/gone" not in controller._gang_cadence


# --- e2e: slowed replica over the real operator + apiserver ------------------

@pytest.fixture()
def harness():
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop), daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


def _get(port, path):
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_e2e_straggler_detection_status_metrics_describe(harness):
    api, cs, _controller, server = harness
    cs.tpujobs.create("default", worker_job("gang", replicas=4))
    assert wait_for(lambda: len(api.clientset.pods.list("default")) == 4)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: cs.tpujobs.get("default", "gang")
                    .get("status", {}).get("phase") == "Running")

    # four gang members post through the REAL reporters built from the
    # operator's env contract; process 2 is artificially slowed (5x)
    env = {"TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
           "TPUJOB_NAME": "gang", "TPUJOB_NAMESPACE": "default",
           "TPUJOB_ATTEMPT": "0"}
    for pid in range(4):
        reporter = heartbeat_mod.from_env({**env,
                                           "JAX_PROCESS_ID": str(pid)})
        assert reporter.cadence_only == (pid != 0)
        # local share differs per process; the whole-step p95 is gang-
        # synchronized (identical) — the realistic SPMD shape
        p95 = 0.5 if pid == 2 else 0.1
        digest = {"steps": 20, "stepP50Seconds": 0.9,
                  "stepP95Seconds": 1.0, "stepMaxSeconds": 1.2,
                  "stepLocalP95Seconds": p95,
                  "phases": {"dataWait": {"p50Seconds": 0.001,
                                          "p95Seconds": 0.002,
                                          "maxSeconds": 0.003},
                             "compute": {"p50Seconds": p95 * 0.9,
                                         "p95Seconds": p95,
                                         "maxSeconds": p95 * 1.2}}}
        assert reporter.report(100, {"loss": 2.5}, steptiming=digest)

    # → status.stragglers flags process 2 through the strict status schema
    def stragglers():
        return (cs.tpujobs.get("default", "gang").get("status", {})
                .get("stragglers") or [])
    assert wait_for(lambda: [s.get("processId") for s in stragglers()]
                    == [2],
                    describe=lambda: cs.tpujobs.get("default",
                                                    "gang").get("status"))
    (s,) = stragglers()
    assert s["ratio"] == pytest.approx(5.0)

    # → status.stepTiming carries process 0's phase breakdown
    status = cs.tpujobs.get("default", "gang")["status"]
    assert status["stepTiming"]["phases"]["compute"]["p95Seconds"] == 0.1

    # → StragglerDetected event on the job
    events = [e for e in cs.events.list("default")
              if e.get("reason") == "StragglerDetected"]
    assert events and "process 2" in events[0]["message"]

    # → /metrics: the straggler gauge and the phase histogram
    body = _get(server.port, "/metrics")
    assert ('tpu_operator_job_straggler_ratio'
            '{name="gang",namespace="default"} 5' in body)
    assert 'tpu_operator_job_step_phase_seconds_bucket{le="0.5",' \
           'phase="compute"}' in body

    # → tpujobctl describe prints the phase table and the straggler
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main(["--master", api.url, "describe", "gang"])
    assert rc == 0
    text = out.getvalue()
    assert "Step:" in text and "compute" in text and "dataWait" in text
    assert "Straggler:  process 2" in text and "5.0x" in text


# --- train_loop integration --------------------------------------------------

def _tiny_build(steps=6):
    from tpu_operator.payload.cifar import build, parse_args

    args = parse_args(["--steps", str(steps), "--batch", "16",
                       "--blocks", "1", "--widths", "8", "8", "8",
                       "--log-every", "0"])
    return build(args)


@pytest.mark.slow
def test_train_loop_records_phases_and_posts_digest():
    from tpu_operator.payload import train

    mesh, _m, state, step, batches = _tiny_build()
    rec = steptrace.StepRecorder(capacity=32)
    posts = []
    reporter = heartbeat_mod.HeartbeatReporter(
        "http://x", "lj", poster=lambda _u, b: posts.append(b),
        interval=0.0)  # every step is due
    train.train_loop(mesh, step, state, batches, steps=4,
                     heartbeat=reporter, steptrace=rec, overlap=False)
    assert rec.steps_recorded == 4
    rows = rec.snapshot()
    for row in rows:
        # every phase boundary in the loop landed in the record (no
        # checkpointer → no checkpoint lap: an absent phase is honest,
        # a zero-duration one would just pad every digest)
        assert {"dataWait", "compute", "host"} <= set(row), row
        assert "checkpoint" not in row, row
    # Self-measurement guard: with a beat due EVERY step, the report's
    # device_get reads the already-fenced previous metrics — the HOST lap
    # must not swallow a whole step's compute (the old same-step fence
    # made host ≈ the full step time and falsely flagged process 0 as
    # the gang straggler). On the synchronous CPU backend the device
    # work lands in DISPATCH, so compare host against the step total;
    # majority vote, not per-row, to shrug off CI noise.
    later = rows[1:]
    assert sum(r["host"] < 0.5 * r["stepSeconds"] for r in later) \
        > len(later) / 2, rows
    timed = [p["stepTiming"] for p in posts if "stepTiming" in p]
    # Each beat drains the window BEFORE the current step commits (the
    # post itself is timed as HOST work of the step it rides), so the
    # final step's window has no later beat to ride — it stays in the
    # ring for the postmortem. 4 steps → 3 posted window-steps.
    assert timed and sum(t["steps"] for t in timed) == 3
    assert "compute" in timed[0]["phases"]


@pytest.mark.slow
def test_train_loop_dumps_postmortem_on_retryable_exit(tmp_path, monkeypatch):
    from tpu_operator.payload import bootstrap, checkpoint, train

    monkeypatch.setenv("TPUJOB_ATTEMPT", "0")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    mesh, _m, state, step, batches = _tiny_build()
    ckpt_dir = tmp_path / "work" / "ckpt"

    shipped = []

    class _UploaderStub:
        """The write-behind surface the checkpointer + dump path touch."""

        def escalated(self):
            return False

        def enqueue(self, _step, _step_dir):
            pass

        def mark_corrupt(self, _step):
            pass

        def stats(self):
            return {}

        def enqueue_artifact(self, path, name=""):
            shipped.append(path)

        def close(self, flush=False, timeout=0.0):
            pass

    ck = checkpoint.Checkpointer(str(ckpt_dir), save_every=100,
                                 uploader=_UploaderStub())
    rec = steptrace.StepRecorder(capacity=32)

    def trip_drain(step_no, _metrics):
        if step_no >= 2:
            bootstrap.request_drain()

    bootstrap.reset_drain()
    try:
        with pytest.raises(SystemExit) as ei:
            train.train_loop(mesh, step, state, batches, steps=6,
                             log_every=1, log_fn=trip_drain,
                             checkpointer=ck, heartbeat=None,
                             steptrace=rec, overlap=False)
        assert ei.value.code == bootstrap.EXIT_RETRYABLE
    finally:
        bootstrap.reset_drain()
        ck.close()

    artifact = tmp_path / "work" / "steptrace-attempt0-p0.json"
    assert artifact.exists()
    body = json.loads(artifact.read_text())
    assert body["kind"] == "tpujob-steptrace"
    assert len(body["steps"]) >= 2
    assert all("compute" in row for row in body["steps"])
    # the artifact rode the existing write-behind worker toward the store
    assert shipped == [str(artifact)]


@pytest.mark.slow
def test_train_loop_passes_through_non_retryable_systemexit():
    """SystemExit.code may be any object (sys.exit("message") is legal):
    the retryable-exit dump hook must compare, never int()-coerce — a
    string code raised a ValueError inside the except handler and
    replaced the intended exit with an unrelated traceback."""
    from tpu_operator.payload import train

    mesh, _m, state, step, batches = _tiny_build()

    def explode(step_no, _metrics):
        raise SystemExit("operator asked politely")

    with pytest.raises(SystemExit) as ei:
        train.train_loop(mesh, step, state, batches, steps=3,
                         log_every=1, log_fn=explode, heartbeat=None,
                         overlap=False)
    assert ei.value.code == "operator asked politely"
