"""Subprocess payload for the checkpoint durability chaos test
(tests/test_checkpoint_chaos.py).

Runs ONE single-process CPU training attempt of the tiny linear-regression
payload through the operator's real env contract — the parent passes this
worker exactly the env the operator injected into the pod spec
(TPU_CHECKPOINT_DIR, TPUJOB_NAME/NAMESPACE/ATTEMPT, TPUJOB_STATUS_URL) —
so checkpoint restore, interval saves, and checkpoint-carrying heartbeats
all exercise their production paths.

Two modes, selected by CHAOS_MODE:

- ``killed`` (attempt 0): train to CHAOS_KILL_STEP with verified interval
  saves, post a final heartbeat carrying the durable step, kick off one
  more *async* save (the one the kill lands in the middle of), write the
  sentinel file, and spin until the parent SIGKILLs us — the canonical
  preempted-mid-save death.
- ``finish`` (attempt >= 1): restore (the parent corrupted the latest
  checkpoint, so this walks back to the last verified step), train to
  CHAOS_TOTAL_STEPS, post the final durability stats, exit 0.

The restore/resume step is asserted by the parent from this process's log
("restored checkpoint step N").
"""

import os
import sys
import time


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("XLA_FLAGS", None)
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    import logging

    logging.basicConfig(level=logging.INFO, stream=sys.stdout,
                        format="%(asctime)s %(levelname)s %(message)s")
    logging.getLogger("absl").setLevel(logging.WARNING)

    import jax.numpy as jnp
    import optax

    from tpu_operator.payload import (bootstrap, checkpoint, data as data_mod,
                                      heartbeat as heartbeat_mod, models,
                                      train)

    mode = os.environ["CHAOS_MODE"]
    kill_step = int(os.environ.get("CHAOS_KILL_STEP", "6"))
    total_steps = int(os.environ.get("CHAOS_TOTAL_STEPS", "10"))
    sentinel = os.environ.get("CHAOS_SENTINEL", "")

    def run(info: bootstrap.ProcessInfo) -> None:
        mesh = train.make_mesh(1)
        model = models.LinearRegressor()
        tx = optax.sgd(0.1)
        sample = jnp.zeros((8, 8), jnp.float32)
        state = train.create_train_state(model, jax.random.key(0), sample, tx)
        state = train.place_state(mesh, state)
        step = train.make_regression_train_step(model, tx, mesh, state)
        batches = data_mod.synthetic_linear(0, 8, 8)

        ckpt = checkpoint.from_env_or_args(save_every=2)
        assert ckpt is not None, "operator did not inject TPU_CHECKPOINT_DIR"

        steps = kill_step if mode == "killed" else total_steps
        state, _metrics = train.train_loop(mesh, step, state, batches,
                                           steps=steps, checkpointer=ckpt)

        # Final heartbeat with the attempt's durability stats — the chaos
        # loop is too fast for the in-loop interval reporter to be the one
        # carrying the final word, so post it explicitly the same way.
        reporter = heartbeat_mod.from_env()
        if reporter is not None:
            reporter.report(steps, None, checkpoint=ckpt.stats())

        if mode == "killed":
            # One more async save for the kill to land inside (its litter —
            # a torn tmp dir or an unverified commit — is then replaced by
            # the parent's *seeded* corrupt-latest so the outcome stays
            # deterministic), then hand control to the parent.
            try:
                ckpt.manager.save(
                    kill_step + 2,
                    args=ckpt._ocp.args.StandardSave(state), force=True)
            except Exception:  # noqa: BLE001 — racing our own SIGKILL
                pass
            if sentinel:
                with open(sentinel, "w") as f:
                    f.write(str(kill_step))
            while True:  # parent SIGKILLs us here, "mid-save"
                time.sleep(0.1)
        ckpt.close()

    sys.exit(bootstrap.run_payload(run))


if __name__ == "__main__":
    main()
