"""Defaulting + validation table tests.

Reference test model: pkg/apis/mxnet/validation/validation_test.go:26-113
(valid spec passes; missing chief / bad type / missing container fail) and
the defaulting assertions inside training_test.go:186-344 — the reference's
copies do not even compile (SURVEY.md §4); these do.
"""

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.apis.tpujob.validation import (
    ValidationError,
    validate_tpujob_spec,
    validate_tpu_resources,
)
from tests.test_types import make_spec, make_template


# --- defaults ---------------------------------------------------------------

def test_defaults_fill_replicas_port_type():
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=0, template=make_template(), tpu_port=None,
                                        tpu_replica_type="")]
    )
    set_defaults(spec)
    rs = spec.replica_specs[0]
    assert rs.replicas == 1
    assert rs.tpu_port == t.DEFAULT_TPU_PORT
    assert rs.tpu_replica_type == t.TPUReplicaType.WORKER


def test_defaults_chief_worker_when_schedulerless():
    # TPU-native mode: no SCHEDULER → chief is WORKER[0]
    spec = make_spec()
    set_defaults(spec)
    assert spec.termination_policy.chief_replica_name == t.TPUReplicaType.WORKER
    assert spec.termination_policy.chief_replica_index == 0
    assert spec.restart_policy == t.RestartPolicy.WHOLE_GROUP


def test_defaults_chief_scheduler_in_compat_mode():
    # ref: training.go:252-257 — chief defaults to SCHEDULER[0]
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(replicas=1, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.SCHEDULER),
            t.TPUReplicaSpec(replicas=2, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.SERVER),
            t.TPUReplicaSpec(replicas=2, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.WORKER),
        ]
    )
    set_defaults(spec)
    assert spec.termination_policy.chief_replica_name == t.TPUReplicaType.SCHEDULER
    assert spec.restart_policy == t.RestartPolicy.PER_POD


def test_defaults_idempotent():
    spec = make_spec()
    set_defaults(spec)
    once = spec.to_dict()
    set_defaults(spec)
    assert spec.to_dict() == once


def test_defaults_lowercase_role_normalized():
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=1, template=make_template(),
                                        tpu_replica_type="worker")]
    )
    set_defaults(spec)
    assert spec.replica_specs[0].tpu_replica_type == t.TPUReplicaType.WORKER


# --- validation -------------------------------------------------------------

def _valid_spec():
    spec = make_spec()
    return set_defaults(spec)


def test_validate_ok():
    validate_tpujob_spec(_valid_spec())


def test_validate_missing_termination_policy():
    spec = make_spec()
    spec.termination_policy = None
    with pytest.raises(ValidationError, match="termination policy"):
        validate_tpujob_spec(spec)


def test_validate_missing_template():
    spec = _valid_spec()
    spec.replica_specs[0].template = None
    with pytest.raises(ValidationError, match="template"):
        validate_tpujob_spec(spec)


def test_validate_missing_port():
    spec = _valid_spec()
    spec.replica_specs[0].tpu_port = None
    with pytest.raises(ValidationError, match="tpuPort"):
        validate_tpujob_spec(spec)


def test_validate_bad_replica_type():
    spec = _valid_spec()
    spec.replica_specs[0].tpu_replica_type = "CHIEFTAIN"
    with pytest.raises(ValidationError, match="CHIEFTAIN"):
        validate_tpujob_spec(spec)


def test_validate_chief_matches_no_replica():
    # ref: validation.go:79-81
    spec = _valid_spec()
    spec.termination_policy = t.TerminationPolicySpec(
        chief_replica_name=t.TPUReplicaType.SCHEDULER
    )
    with pytest.raises(ValidationError, match="matches no replicaSpec"):
        validate_tpujob_spec(spec)


def test_validate_container_name_required():
    # ref: validation.go:68-76 (container named "mxnet" → here "tpu")
    spec = _valid_spec()
    spec.replica_specs[0].template = make_template(container_name="main")
    with pytest.raises(ValidationError, match="container named 'tpu'"):
        validate_tpujob_spec(spec)


def test_validate_scheduler_must_be_single():
    # ref: replicas.go:87-93, hoisted to validation
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(replicas=2, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.SCHEDULER),
            t.TPUReplicaSpec(replicas=1, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.WORKER),
        ]
    )
    set_defaults(spec)
    with pytest.raises(ValidationError, match="SCHEDULER"):
        validate_tpujob_spec(spec)


def test_validate_duplicate_role():
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(replicas=1, template=make_template()),
            t.TPUReplicaSpec(replicas=2, template=make_template()),
        ]
    )
    set_defaults(spec)
    with pytest.raises(ValidationError, match="duplicate"):
        validate_tpujob_spec(spec)


def test_validate_empty_spec():
    spec = t.TPUJobSpec()
    set_defaults(spec)
    with pytest.raises(ValidationError, match="at least one"):
        validate_tpujob_spec(spec)


# --- TPU resource validation ------------------------------------------------

def test_multislice_requires_divisible_workers():
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(replicas=3, template=make_template(tpu_chips=4)),
        ],
        num_slices=2,
    )
    set_defaults(spec)
    with pytest.raises(ValidationError, match="divisible"):
        validate_tpu_resources(spec)


def test_multislice_requires_chips():
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=4, template=make_template())],
        num_slices=2,
    )
    set_defaults(spec)
    with pytest.raises(ValidationError, match="no TPU chips"):
        validate_tpu_resources(spec)


def test_multislice_ok():
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=4, template=make_template(tpu_chips=4))],
        num_slices=2,
    )
    set_defaults(spec)
    validate_tpu_resources(spec)
