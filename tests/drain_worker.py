"""Subprocess worker for the coordinated multi-process drain test
(tests/test_multiprocess_drain.py).

Runs one process of a 2-process CPU jax.distributed group training the tiny
transformer LM through the full bootstrap + train_loop path, with interval
saves effectively disabled — the only checkpoint that can appear is the
coordinated drain save, so the parent test can assert exactly which step
every process agreed on.

Usage: drain_worker.py <coordinator_port> <process_id> <num_processes>
       <checkpoint_dir> <sentinel_dir>
"""

import faulthandler
import os
import signal
import sys


def main() -> None:
    faulthandler.register(signal.SIGUSR1)  # debug: dump stacks when hung
    port, pid, nprocs, ckpt_dir, sentinel_dir = sys.argv[1:6]
    # Must be set before the first jax import: one local CPU device per
    # process, and the bootstrap env contract this worker consumes.
    os.environ.update({
        "JAX_PLATFORMS": "cpu",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_PROCESS_ID": pid,
        "JAX_NUM_PROCESSES": nprocs,
        "TPU_WORKER_ID": pid,
    })
    os.environ.pop("XLA_FLAGS", None)
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    # A sitecustomize hook may have registered a real-accelerator PJRT
    # plugin (and imported jax) at interpreter boot — before this main()
    # ran. Backend *clients* are lazy, so overriding the platform config
    # here still wins (same trick as tests/conftest.py).
    import jax

    jax.config.update("jax_platforms", "cpu")

    import logging

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")

    from tpu_operator.payload import (bootstrap, checkpoint, train,
                                      transformer)

    def run(info: bootstrap.ProcessInfo) -> None:
        args = transformer.parse_args([
            "--batch", "4", "--seq-len", "32", "--dim", "16", "--heads",
            "2", "--layers", "1", "--vocab", "64",
        ])
        mesh, _model, state, step, batches = transformer.build(args)
        ckpt = checkpoint.Checkpointer(ckpt_dir, save_every=10 ** 9)
        sentinel = os.path.join(sentinel_dir, f"stepping_{info.process_id}")

        def log_fn(i, _metrics):
            # First log interval: tell the parent we are in steady-state
            # stepping (safe to deliver SIGTERM).
            if not os.path.exists(sentinel):
                with open(sentinel, "w") as f:
                    f.write(str(i))

        try:
            # steps is effectively unbounded: this run only ends by drain.
            train.train_loop(mesh, step, state, batches, steps=200_000,
                             log_every=5, log_fn=log_fn,
                             checkpointer=ckpt,
                             spec=transformer.lm_token_spec(mesh))
        finally:
            ckpt.close()

    sys.exit(bootstrap.run_payload(run))


if __name__ == "__main__":
    main()
