"""Pipeline-parallelism tests (8-device CPU mesh).

The GPipe-style scheduler in payload/pipeline.py must be a semantics-
preserving transform: pipelined application over the (data, pipe) mesh
equals sequential stage application — forward and gradients — and the full
LM train step learns the synthetic recurrence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_operator.payload import pipeline


def _args(**over):
    base = dict(batch=8, seq_len=32, dim=32, heads=2, layers=4,
                pipeline=4, microbatches=2, dtype="f32", lr=1e-2)
    base.update(over)
    argv = []
    for k, v in base.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    return pipeline.parse_args(argv)


@pytest.fixture(scope="module")
def mesh():
    return pipeline.make_pipe_mesh(8, pipeline=4)  # (data=2, pipe=4)


@pytest.fixture(scope="module")
def stage_and_params(mesh):
    args = _args()
    stage = pipeline._stage_module(args)
    sample = jnp.zeros((1, args.seq_len, args.dim), jnp.float32)
    stacked = pipeline.init_stacked_params(
        stage, jax.random.key(0), mesh.shape["pipe"], sample)
    return args, stage, stacked


def _sequential_apply(stage, stacked, x):
    num_stages = jax.tree_util.tree_leaves(stacked)[0].shape[0]
    for s in range(num_stages):
        params = jax.tree_util.tree_map(lambda p: p[s], stacked)
        x = stage.apply({"params": params}, x)
    return x


def test_pipeline_apply_matches_sequential(mesh, stage_and_params):
    args, stage, stacked = stage_and_params
    x = jax.random.normal(jax.random.key(1), (8, args.seq_len, args.dim),
                          jnp.float32)
    want = _sequential_apply(stage, stacked, x)
    got = pipeline.pipeline_apply(
        mesh, lambda p, h: stage.apply({"params": p}, h), stacked, x,
        microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_apply_grad_matches_sequential(mesh, stage_and_params):
    args, stage, stacked = stage_and_params
    x = jax.random.normal(jax.random.key(2), (8, args.seq_len, args.dim),
                          jnp.float32)

    def loss_pipe(params, x):
        out = pipeline.pipeline_apply(
            mesh, lambda p, h: stage.apply({"params": p}, h), params, x,
            microbatches=4)
        return jnp.mean(out.astype(jnp.float32) ** 2)

    def loss_seq(params, x):
        return jnp.mean(_sequential_apply(stage, params, x) ** 2)

    gp, gx_p = jax.grad(loss_pipe, argnums=(0, 1))(stacked, x)
    gs, gx_s = jax.grad(loss_seq, argnums=(0, 1))(stacked, x)
    np.testing.assert_allclose(np.asarray(gx_p), np.asarray(gx_s),
                               atol=1e-5, rtol=1e-5)
    for got, want in zip(jax.tree_util.tree_leaves(gp),
                         jax.tree_util.tree_leaves(gs)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-4)


def test_single_stage_degenerates_to_plain_stack():
    # pipe=1 mesh: the scheduler must collapse to sequential with no hops.
    args = _args(layers=4, pipeline=1)
    mesh1 = pipeline.make_pipe_mesh(2, pipeline=1)
    stage = pipeline._stage_module(args)
    sample = jnp.zeros((1, args.seq_len, args.dim), jnp.float32)
    stacked = pipeline.init_stacked_params(stage, jax.random.key(3), 1, sample)
    x = jax.random.normal(jax.random.key(4), (4, args.seq_len, args.dim),
                          jnp.float32)
    want = _sequential_apply(stage, stacked, x)
    got = pipeline.pipeline_apply(
        mesh1, lambda p, h: stage.apply({"params": p}, h), stacked, x,
        microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_state_shardings_put_stages_on_pipe_axis(mesh):
    args = _args()
    _mesh, _stage, state, _step, _batches = pipeline.build(args, mesh=mesh)
    shardings = pipeline.state_shardings(mesh, state)
    stage_spec = jax.tree_util.tree_leaves(shardings.params["stages"])[0].spec
    assert stage_spec[0] == "pipe"
    assert shardings.params["head"].spec == ()
    # adam moments over stage params shard identically
    opt_leaves = [
        s for path, s in jax.tree_util.tree_flatten_with_path(
            shardings.opt_state)[0]
        if any(getattr(p, "key", None) == "stages" for p in path)
    ]
    assert opt_leaves and all(s.spec[0] == "pipe" for s in opt_leaves)


def test_pipeline_lm_loss_descends(mesh):
    args = _args(batch=16, layers=4, microbatches=4, steps=30,
                 log_every=0)
    _mesh, _stage, state, step, batches = pipeline.build(args, mesh=mesh)

    from tpu_operator.payload import data as data_mod

    losses = []
    for _ in range(30):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


# --- 1F1B schedule ------------------------------------------------------------


def test_onef1b_schedule_table_is_well_formed():
    s_, m_ = 4, 8
    table = pipeline.onef1b_schedule(s_, m_)
    assert len(table) == 2 * (m_ + s_ - 1)
    # every (stage, op, microbatch) happens exactly once
    seen = set()
    for t, row in enumerate(table):
        assert len(row) == s_
        for s, op in enumerate(row):
            if op is not None:
                assert op not in [o for (ss, o) in seen if ss == s]
                seen.add((s, op))
    for s in range(s_):
        for m in range(m_):
            assert (s, ("F", m)) in seen and (s, ("B", m)) in seen
    # dataflow: F(m) at stage s+1 is exactly one tick after stage s;
    # B(m) at stage s is one tick after stage s+1.
    when = {(s, op): t for t, row in enumerate(table)
            for s, op in enumerate(row) if op}
    for m in range(m_):
        for s in range(s_ - 1):
            assert when[(s + 1, ("F", m))] == when[(s, ("F", m))] + 1
            assert when[(s, ("B", m))] == when[(s + 1, ("B", m))] + 1


@pytest.mark.parametrize("m_", [4, 16])
def test_onef1b_memory_is_o_stages_not_microbatches(m_):
    # Peak in-flight microbatches per stage (F done, B pending) is S - s —
    # independent of M. GPipe's forward scan holds all M.
    s_ = 4
    table = pipeline.onef1b_schedule(s_, m_)
    for s in range(s_):
        live, peak = 0, 0
        for row in table:
            op = row[s]
            if op and op[0] == "F":
                live += 1
            elif op and op[0] == "B":
                live -= 1
            peak = max(peak, live)
        assert peak == s_ - s, (s, peak)


def test_onef1b_bubble_fraction_shrinks_with_microbatches():
    # Measured bubble at S=4: idle slots / total slots per stage. The
    # flush bubble is (S-1)/(M+S-1) per direction; more microbatches
    # amortize it — and unlike GPipe, 1F1B pays no memory for that.
    s_ = 4

    def bubble(m_):
        table = pipeline.onef1b_schedule(s_, m_)
        idle = sum(1 for row in table for op in row if op is None)
        return idle / (len(table) * s_)

    b4, b16 = bubble(4), bubble(16)
    assert abs(b4 - (s_ - 1) / (4 + s_ - 1)) < 0.04
    assert abs(b16 - (s_ - 1) / (16 + s_ - 1)) < 0.02
    assert b16 < b4 / 2


def test_1f1b_matches_gpipe_loss_and_update(mesh):
    # Same seed, same batch: the hand-differentiated 1F1B step must produce
    # the same loss AND the same updated parameters as jax.grad of the
    # GPipe scan — manual vjp bookkeeping against program-level autodiff.
    from tpu_operator.payload import data as data_mod

    a_g = _args(batch=16, microbatches=4, schedule="gpipe")
    a_f = _args(batch=16, microbatches=4, schedule="1f1b")
    _, _, st_g, step_g, batches = pipeline.build(a_g, mesh=mesh)
    _, _, st_f, step_f, _ = pipeline.build(a_f, mesh=mesh)
    (tok,) = next(batches)
    (dev,) = data_mod.put_global_batch(mesh, tok)
    new_g, m_g = step_g(st_g, dev)
    new_f, m_f = step_f(st_f, dev)
    assert abs(float(m_g["loss"]) - float(m_f["loss"])) < 1e-5
    flat_g = jax.tree_util.tree_leaves(new_g.params)
    flat_f = jax.tree_util.tree_leaves(new_f.params)
    for g_leaf, f_leaf in zip(flat_g, flat_f):
        np.testing.assert_allclose(np.asarray(g_leaf), np.asarray(f_leaf),
                                   atol=2e-5, rtol=2e-5)


def test_1f1b_stash_residuals_matches_input_stash(mesh):
    """--stash residuals (store the stage vjp's residual leaves, no
    recompute) must produce the same loss and updated params as the
    recompute-from-input path — it is the same math with the forward run
    once instead of twice. f32 so the comparison is tight."""
    from tpu_operator.payload import data as data_mod

    a_in = _args(batch=16, microbatches=4, schedule="1f1b")
    a_res = _args(batch=16, microbatches=4, schedule="1f1b",
                  stash="residuals")
    _, _, st_i, step_i, batches = pipeline.build(a_in, mesh=mesh)
    _, _, st_r, step_r, _ = pipeline.build(a_res, mesh=mesh)
    (tok,) = next(batches)
    (dev,) = data_mod.put_global_batch(mesh, tok)
    new_i, m_i = step_i(st_i, dev)
    new_r, m_r = step_r(st_r, dev)
    assert abs(float(m_i["loss"]) - float(m_r["loss"])) < 1e-6
    for li, lr in zip(jax.tree_util.tree_leaves(new_i.params),
                      jax.tree_util.tree_leaves(new_r.params)):
        np.testing.assert_allclose(np.asarray(li), np.asarray(lr),
                                   atol=1e-5, rtol=1e-5)


def test_1f1b_lm_loss_descends(mesh):
    from tpu_operator.payload import data as data_mod

    args = _args(batch=16, microbatches=4, schedule="1f1b")
    _mesh, _stage, state, step, batches = pipeline.build(args, mesh=mesh)
    losses = []
    for _ in range(30):
        (tok,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tok)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_1f1b_rejects_grad_accum(mesh):
    with pytest.raises(ValueError, match="grad-accum"):
        pipeline.build(_args(batch=32, microbatches=4, schedule="1f1b",
                             grad_accum=2), mesh=mesh)


def test_build_validates_divisibility():
    with pytest.raises(ValueError):
        pipeline.build(_args(batch=6, microbatches=4),
                       mesh=pipeline.make_pipe_mesh(8, pipeline=4))
    with pytest.raises(ValueError):
        pipeline._stage_module(_args(layers=5, pipeline=4))


@pytest.fixture(scope="module")
def mesh3():
    # PP × TP: (data=2, pipe=2, model=2)
    return pipeline.make_pipe_mesh(8, pipeline=2, tensor_parallel=2)


def test_pp_tp_state_shardings(mesh3):
    args = _args(pipeline=2, tensor_parallel=2, layers=4)
    _, _, state, _step, _batches = pipeline.build(args, mesh=mesh3)
    sh = pipeline.state_shardings(mesh3, state)
    blk = sh.params["stages"]["block0"]
    assert blk["q"]["kernel"].spec == ("pipe", None, "model")
    assert blk["mlp_up"]["kernel"].spec == ("pipe", None, "model")
    assert blk["mlp_up"]["bias"].spec == ("pipe", "model")
    assert blk["attn_out"]["kernel"].spec == ("pipe", "model", None)
    assert blk["mlp_down"]["kernel"].spec == ("pipe", "model", None)
    assert blk["ln_attn"]["scale"].spec == ("pipe", None)
    assert sh.params["tok_embed"].spec == ()


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_pp_tp_matches_2axis_pipeline(mesh, mesh3, schedule):
    """PP × TP on the 3-axis mesh = the plain (data, pipe) pipeline: same
    seed, same batch → same loss and same updated params (the TP sharding
    is a placement concern only; GSPMD's psums must not change the math
    beyond f32 summation order)."""
    from tpu_operator.payload import data as data_mod

    base = dict(batch=16, microbatches=2, pipeline=2, layers=4, heads=2,
                schedule=schedule, split_qkv="on")
    a_tp = _args(tensor_parallel=2, **base)
    a_2x = _args(**base)
    mesh2 = pipeline.make_pipe_mesh(4, pipeline=2)
    _, _, st_tp, step_tp, batches = pipeline.build(a_tp, mesh=mesh3)
    _, _, st_2x, step_2x, _ = pipeline.build(a_2x, mesh=mesh2)
    # Two full steps: losses must agree tightly each step (semantic
    # parity *through* an optimizer update). Raw params only loosely —
    # adam's first steps are epsilon-dominated, so the f32
    # reduction-order difference between the GSPMD-sharded and unsharded
    # compiles legitimately perturbs updates at the ~1e-3 relative level.
    for _ in range(2):
        (tok,) = next(batches)
        (dev3,) = data_mod.put_global_batch(mesh3, tok)
        (dev2,) = data_mod.put_global_batch(mesh2, tok)
        st_tp, m_tp = step_tp(st_tp, dev3)
        st_2x, m_2x = step_2x(st_2x, dev2)
        assert abs(float(m_tp["loss"]) - float(m_2x["loss"])) < 2e-5
    flat_tp = jax.tree_util.tree_leaves(st_tp.params)
    flat_2x = jax.tree_util.tree_leaves(st_2x.params)
    for a, b in zip(flat_tp, flat_2x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-3)


def test_pp_tp_1f1b_loss_descends(mesh3):
    from tpu_operator.payload import data as data_mod

    args = _args(batch=16, microbatches=2, pipeline=2, layers=4, heads=2,
                 tensor_parallel=2, schedule="1f1b")
    _mesh, _stage, state, step, batches = pipeline.build(args, mesh=mesh3)
    losses = []
    for _ in range(25):
        (tok,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh3, tok)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_pp_tp_validates_divisibility(mesh3):
    with pytest.raises(ValueError, match="tensor-parallel"):
        pipeline._stage_module(_args(heads=3, pipeline=2,
                                     tensor_parallel=2), tp=2)
    with pytest.raises(ValueError, match="tensor-parallel"):
        pipeline._stage_module(_args(heads=4, kv_heads=1, pipeline=2,
                                     tensor_parallel=2), tp=2)


def test_zero1_shards_opt_state_only(mesh):
    """--zero1: adam moments shard over data on their first divisible dim;
    params stay replicated across data (pipe/model sharding unchanged);
    one train step matches the non-zero1 step exactly."""
    from tpu_operator.payload import data as data_mod

    # _args serializes every value, so build the store_true flag directly
    args_z = pipeline.parse_args(
        ["--batch", "16", "--seq-len", "32", "--dim", "32", "--heads", "2",
         "--layers", "4", "--pipeline", "4", "--microbatches", "4",
         "--dtype", "f32", "--lr", "1e-2", "--schedule", "1f1b", "--zero1"])
    args_p = pipeline.parse_args(
        ["--batch", "16", "--seq-len", "32", "--dim", "32", "--heads", "2",
         "--layers", "4", "--pipeline", "4", "--microbatches", "4",
         "--dtype", "f32", "--lr", "1e-2", "--schedule", "1f1b"])
    _, _, st_z, step_z, batches = pipeline.build(args_z, mesh=mesh)
    _, _, st_p, step_p, _ = pipeline.build(args_p, mesh=mesh)

    sh = pipeline.state_shardings(mesh, st_z, zero1=True)
    mu = sh.opt_state[0].mu
    # stage moment [S=4, 32, 128]: dim 1 divisible by data=2
    assert mu["stages"]["block0"]["mlp_up"]["kernel"].spec == \
        ("pipe", "data", None)
    # replicated-param moment [256, 32]: dim 0 shards over data
    assert mu["tok_embed"].spec == ("data", None)
    # params themselves stay replicated over data
    assert sh.params["tok_embed"].spec == ()

    (tok,) = next(batches)
    (dev,) = data_mod.put_global_batch(mesh, tok)
    new_z, m_z = step_z(st_z, dev)
    new_p, m_p = step_p(st_p, dev)
    assert abs(float(m_z["loss"]) - float(m_p["loss"])) < 1e-6
    for a, b in zip(jax.tree_util.tree_leaves(new_z.params),
                    jax.tree_util.tree_leaves(new_p.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)


def test_pipeline_gqa_descends(mesh):
    from tpu_operator.payload import data as data_mod

    args = _args(batch=16, microbatches=4, heads=4, kv_heads=2,
                 schedule="1f1b")
    _mesh, _stage, state, step, batches = pipeline.build(args, mesh=mesh)
    blk = state.params["stages"]
    # stacked stage params: [S, in, out]; K/V project to kv_heads*head_dim
    assert blk["block0"]["k"]["kernel"].shape == (4, 32, 16)
    losses = []
    for _ in range(25):
        (tok,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tok)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_interleaved_schedule_invariants():
    """The simulated schedule must satisfy every dependency (the generator
    asserts them internally), fire each unit exactly once (also internal),
    degenerate to plain 1F1B's makespan at V=1, and shrink the relative
    bubble ~V x at fixed S, M."""
    t_v1 = pipeline.onef1b_interleaved_schedule(4, 1, 8)["act"].shape[0]
    assert t_v1 == 2 * (8 + 4 - 1)  # plain 1F1B flush makespan
    t_v2 = pipeline.onef1b_interleaved_schedule(4, 2, 8)["act"].shape[0]
    rb1 = (t_v1 - 2 * 8) / (2 * 8)
    rb2 = (t_v2 - 2 * 2 * 8) / (2 * 2 * 8)
    assert rb2 < rb1 / 1.5, (rb1, rb2)
    # generator-internal audits across a grid (raises on violation)
    for s, v, m in [(2, 2, 4), (2, 3, 6), (4, 4, 8), (8, 2, 8)]:
        tbl = pipeline.onef1b_interleaved_schedule(s, v, m)
        assert ((tbl["act"] == 1).sum() == (tbl["act"] == 2).sum()
                == v * m * s)
    with pytest.raises(ValueError, match="divisible"):
        pipeline.onef1b_interleaved_schedule(4, 2, 6)


def test_interleaved_matches_plain_1f1b(mesh):
    """S=2 devices x V=2 chunks must equal the plain S=4 pipeline: the
    chunk stacks initialize identically (same rng, V*S chunks), only their
    device placement differs — loss trajectory tight, params loose (adam's
    first steps amplify f32 summation-order differences)."""
    from tpu_operator.payload import data as data_mod

    a_int = _args(batch=16, microbatches=4, layers=4, pipeline=2,
                  schedule="1f1b-interleaved", virtual_stages=2)
    a_pln = _args(batch=16, microbatches=4, layers=4, pipeline=4,
                  schedule="1f1b")
    mesh2 = pipeline.make_pipe_mesh(4, pipeline=2)
    _, _, st_i, step_i, batches = pipeline.build(a_int, mesh=mesh2)
    _, _, st_p, step_p, _ = pipeline.build(a_pln, mesh=mesh)
    # identical underlying chunk params, different layout
    vs = jax.tree_util.tree_leaves(st_i.params["stages"])[0]
    ps = jax.tree_util.tree_leaves(st_p.params["stages"])[0]
    np.testing.assert_array_equal(
        np.asarray(vs).reshape(ps.shape), np.asarray(ps))
    for _ in range(2):
        (tok,) = next(batches)
        (dev_i,) = data_mod.put_global_batch(mesh2, tok)
        (dev_p,) = data_mod.put_global_batch(mesh, tok)
        st_i, m_i = step_i(st_i, dev_i)
        st_p, m_p = step_p(st_p, dev_p)
        assert abs(float(m_i["loss"]) - float(m_p["loss"])) < 2e-5, \
            (float(m_i["loss"]), float(m_p["loss"]))
    for a, b in zip(jax.tree_util.tree_leaves(st_i.params),
                    jax.tree_util.tree_leaves(st_p.params)):
        np.testing.assert_allclose(
            np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b),
            atol=5e-3, rtol=5e-3)


def test_interleaved_1f1b_loss_descends():
    from tpu_operator.payload import data as data_mod

    args = _args(batch=16, microbatches=4, layers=4, pipeline=2,
                 schedule="1f1b-interleaved", virtual_stages=2)
    mesh2 = pipeline.make_pipe_mesh(4, pipeline=2)
    _m, _s, state, step, batches = pipeline.build(args, mesh=mesh2)
    losses = []
    for _ in range(25):
        (tok,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh2, tok)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_interleaved_validates_divisibility():
    mesh2 = pipeline.make_pipe_mesh(4, pipeline=2)
    with pytest.raises(ValueError, match="divisible"):
        # microbatches (1) not divisible by pipeline (2)
        pipeline.build(_args(batch=16, microbatches=1, layers=4,
                             pipeline=2, schedule="1f1b-interleaved",
                             virtual_stages=2), mesh=mesh2)
    with pytest.raises(ValueError, match="layers"):
        pipeline.build(_args(batch=16, microbatches=4, layers=6,
                             pipeline=2, schedule="1f1b-interleaved",
                             virtual_stages=2), mesh=mesh2)


def test_interleaved_v1_default_works():
    """--schedule 1f1b-interleaved with the flag's default
    --virtual-stages 1 must run (the [V, S] layout applies at V=1 too) and
    match plain 1f1b's loss on the same config."""
    from tpu_operator.payload import data as data_mod

    mesh2 = pipeline.make_pipe_mesh(4, pipeline=2)
    a_int = _args(batch=16, microbatches=4, layers=4, pipeline=2,
                  schedule="1f1b-interleaved")
    a_pln = _args(batch=16, microbatches=4, layers=4, pipeline=2,
                  schedule="1f1b")
    _, _, st_i, step_i, batches = pipeline.build(a_int, mesh=mesh2)
    _, _, st_p, step_p, _ = pipeline.build(a_pln, mesh=mesh2)
    (tok,) = next(batches)
    (dev,) = data_mod.put_global_batch(mesh2, tok)
    _, m_i = step_i(st_i, dev)
    _, m_p = step_p(st_p, dev)
    assert abs(float(m_i["loss"]) - float(m_p["loss"])) < 2e-5


def test_crossover_tool_calibration_reproduces_measurements(monkeypatch):
    """hack/pipeline_crossover.py: the (rho, m0) calibration must exactly
    reproduce both measured S=1 rows by construction, rho must land in
    (0, 1) (a recompute fraction), and the projection must respect the
    two structural facts the schedules guarantee: at matched (S, M),
    interleaved V=2 strictly shrinks the bubble term but adds ticks, and
    plain 1F1B wall time is monotone non-increasing in M (bigger M =
    smaller bubble fraction at fixed machinery-per-activation)."""
    import pathlib

    monkeypatch.syspath_prepend(
        str(pathlib.Path(__file__).resolve().parent.parent / "hack"))
    import pipeline_crossover as pc

    dense, plain, inter, m0_batch = 327.4, 393.8, 418.0, 4
    rho, m0 = pc.calibrate(dense, plain, inter, m0_batch)
    assert 0.0 < rho < 1.0
    assert m0 > 0.0
    got_p = pc.simulate("plain", 1, 1, m0_batch, dense, rho, m0, m0_batch)
    got_i = pc.simulate("interleaved", 1, 2, m0_batch, dense, rho, m0,
                        m0_batch)
    assert abs(got_p - plain) < 0.1, (got_p, plain)
    assert abs(got_i - inter) < 0.1, (got_i, inter)
    # bubble-dominated corner (M == S): interleaving projected to win
    assert pc.simulate("interleaved", 4, 2, 4, dense, rho, m0, m0_batch) \
        < pc.simulate("plain", 4, 1, 4, dense, rho, m0, m0_batch)
    # machinery-dominated corner (M >> S): plain projected to win
    assert pc.simulate("plain", 4, 1, 32, dense, rho, m0, m0_batch) \
        < pc.simulate("interleaved", 4, 2, 32, dense, rho, m0, m0_batch)
    walls = [pc.simulate("plain", 4, 1, m, dense, rho, m0, m0_batch)
             for m in (4, 8, 16, 32)]
    assert all(a >= b for a, b in zip(walls, walls[1:])), walls
