"""Megatron-style LM tensor parallelism tests (8-device CPU mesh).

lm_tp_shardings is layout, not math: TP=4 must match TP=1 losses, shard
the paired kernels column/row over the model axis, and train end-to-end.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from tpu_operator.payload import data as data_mod, transformer


def _argv(extra=()):
    return ["--batch", "8", "--seq-len", "64", "--dim", "64", "--heads", "4",
            "--layers", "2", *extra]


@pytest.fixture(scope="module")
def mesh():
    return transformer.make_lm_mesh(8, tensor_parallel=4)  # (data=2, model=4)


def test_tp_kernels_sharded_col_and_row(mesh):
    args = transformer.parse_args(_argv(["--tensor-parallel", "4"]))
    _, _, state, _step, _batches = transformer.build(args, mesh=mesh)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    specs = {jax.tree_util.keystr(p): l.sharding.spec for p, l in flat}

    def spec_for(fragment):
        return next(s for k, s in specs.items()
                    if fragment in k and k.endswith("kernel']"))

    assert spec_for("['q']") == (None, "model")
    assert spec_for("['k']") == (None, "model")
    assert spec_for("['v']") == (None, "model")
    assert spec_for("mlp_up") == (None, "model")
    assert spec_for("lm_head") == (None, "model")
    assert spec_for("attn_out") == ("model", None)
    assert spec_for("mlp_down") == ("model", None)
    # LayerNorms and embeddings replicate
    assert all(s == () for k, s in specs.items() if "ln_" in k)
    assert all(s == () for k, s in specs.items() if "embed" in k)


def test_tp_matches_single_device_loss(mesh):
    losses = {}
    for tp in (1, 4):
        # --split-qkv on for both, so the param trees (and the seeded
        # init draws) are identical; only the sharding differs.
        args = transformer.parse_args(
            _argv(["--tensor-parallel", str(tp), "--split-qkv", "on"]))
        m = mesh if tp == 4 else transformer.make_lm_mesh(1)
        _, _, state, step, batches = transformer.build(args, mesh=m)
        (tokens,) = next(batches)
        from jax.sharding import PartitionSpec as P

        spec = P("data", None) if tp == 4 else P()
        (dev,) = data_mod.put_global_batch(m, tokens, spec=spec)
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[tp] = float(metrics["loss"])
    assert abs(losses[1] - losses[4]) < 5e-3, losses


def test_tp_loss_descends(mesh):
    args = transformer.parse_args(
        _argv(["--tensor-parallel", "4", "--lr", "1e-2"]))
    _, _, state, step, batches = transformer.build(args, mesh=mesh)
    from jax.sharding import PartitionSpec as P

    losses = []
    for _ in range(30):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", None))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_tp_and_sp_compose_into_3axis_mesh():
    mesh3 = transformer.make_lm_mesh(8, seq_parallel=2, tensor_parallel=4)
    assert dict(mesh3.shape) == {"data": 1, "seq": 2, "model": 4}
    with pytest.raises(ValueError, match="divisible"):
        transformer.make_lm_mesh(8, seq_parallel=3, tensor_parallel=4)


def test_tp_rejects_fsdp(mesh):
    args = transformer.parse_args(
        _argv(["--tensor-parallel", "4", "--fsdp"]))
    with pytest.raises(ValueError, match="exclusive"):
        transformer.build(args, mesh=mesh)


def test_tp_fused_qkv_compat_shards_packed_kernel(mesh):
    # --split-qkv off under TP: the fused [d, 3d] kernel (checkpoint-compat
    # layout) still column-shards over the model axis.
    args = transformer.parse_args(
        _argv(["--tensor-parallel", "4", "--split-qkv", "off"]))
    _, _, state, _step, _batches = transformer.build(args, mesh=mesh)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    specs = {jax.tree_util.keystr(p): l.sharding.spec for p, l in flat}
    qkv = next(s for k, s in specs.items()
               if "['qkv']" in k and k.endswith("kernel']"))
    assert qkv == (None, "model")


def test_tp_composes_with_seq_parallel_3axis():
    # (data=2, seq=2, model=2): ring attention over TP-sharded heads in one
    # jit; loss must match the unsharded single-device run.
    mesh3 = transformer.make_lm_mesh(8, seq_parallel=2, tensor_parallel=2)
    assert dict(mesh3.shape) == {"data": 2, "seq": 2, "model": 2}
    argv = ["--batch", "4", "--seq-len", "64", "--dim", "64", "--heads", "4",
            "--layers", "2", "--seq-parallel", "2", "--tensor-parallel", "2"]
    args = transformer.parse_args(argv)
    _, _, state, step, batches = transformer.build(args, mesh=mesh3)

    args1 = transformer.parse_args(
        ["--batch", "4", "--seq-len", "64", "--dim", "64", "--heads", "4",
         "--layers", "2", "--split-qkv", "on"])
    mesh1 = transformer.make_lm_mesh(1)
    _, _, s1, step1, _ = transformer.build(args1, mesh=mesh1)

    from jax.sharding import PartitionSpec as P

    (tokens,) = next(batches)
    (d3,) = data_mod.put_global_batch(mesh3, tokens, spec=P("data", "seq"))
    (d1,) = data_mod.put_global_batch(mesh1, tokens, spec=P())
    _, m3 = step(state, d3)
    _, m1 = step1(s1, d1)
    assert abs(float(m3["loss"]) - float(m1["loss"])) < 2e-2, (
        float(m3["loss"]), float(m1["loss"]))


def test_3axis_loss_descends():
    mesh3 = transformer.make_lm_mesh(8, seq_parallel=2, tensor_parallel=2)
    args = transformer.parse_args(
        ["--batch", "8", "--seq-len", "64", "--dim", "64", "--heads", "4",
         "--layers", "2", "--seq-parallel", "2", "--tensor-parallel", "2",
         "--lr", "1e-2"])
    _, _, state, step, batches = transformer.build(args, mesh=mesh3)

    from jax.sharding import PartitionSpec as P

    losses = []
    for _ in range(25):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh3, tokens, spec=P("data", "seq"))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_ulysses_rejects_tensor_parallel():
    mesh3 = transformer.make_lm_mesh(8, seq_parallel=2, tensor_parallel=2)
    args = transformer.parse_args(
        ["--batch", "4", "--seq-len", "32", "--dim", "32", "--heads", "4",
         "--layers", "1", "--seq-parallel", "2", "--tensor-parallel", "2",
         "--sp-mode", "ulysses"])
    with pytest.raises(ValueError, match="ulysses"):
        transformer.build(args, mesh=mesh3)


# --- grouped-query attention (GQA) -------------------------------------------


def test_gqa_shrinks_kv_projections_and_descends():
    from tpu_operator.payload import data as data_mod, transformer

    args = transformer.parse_args([
        "--batch", "8", "--seq-len", "64", "--dim", "64", "--heads", "4",
        "--kv-heads", "1", "--layers", "2", "--lr", "1e-2"])
    mesh = transformer.make_lm_mesh(2)
    mesh, _m, state, step, batches = transformer.build(args, mesh=mesh)
    blk = state.params["block0"]
    assert blk["q"]["kernel"].shape == (64, 64)
    assert blk["k"]["kernel"].shape == (64, 16)  # 1 kv head x head_dim 16
    assert blk["v"]["kernel"].shape == (64, 16)

    losses = []
    for _ in range(30):
        (tok,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tok,
                                           spec=transformer.lm_token_spec(mesh))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_gqa_with_full_heads_equals_split_mha():
    from tpu_operator.payload import data as data_mod, transformer

    base = ["--batch", "4", "--seq-len", "32", "--dim", "32", "--heads",
            "2", "--layers", "2", "--split-qkv", "on"]
    mesh = transformer.make_lm_mesh(1)
    _, _, s_mha, step_mha, batches = transformer.build(
        transformer.parse_args(base), mesh=mesh)
    _, _, s_gqa, step_gqa, _ = transformer.build(
        transformer.parse_args(base + ["--kv-heads", "2"]), mesh=mesh)
    (tok,) = next(batches)
    (dev,) = data_mod.put_global_batch(mesh, tok, spec=None)
    _, m_mha = step_mha(s_mha, dev)
    _, m_gqa = step_gqa(s_gqa, dev)
    # kv_heads == heads is exactly MHA: same param tree, same loss.
    assert abs(float(m_mha["loss"]) - float(m_gqa["loss"])) < 1e-6


def test_gqa_composes_with_tensor_parallel():
    from tpu_operator.payload import data as data_mod, transformer

    args = transformer.parse_args([
        "--batch", "8", "--seq-len", "32", "--dim", "32", "--heads", "4",
        "--kv-heads", "2", "--layers", "2", "--tensor-parallel", "2"])
    mesh = transformer.make_lm_mesh(4, tensor_parallel=2)
    mesh, _m, state, step, batches = transformer.build(args, mesh=mesh)
    shardings = transformer.lm_tp_shardings(mesh, state)
    k_spec = shardings.params["block0"]["k"]["kernel"].spec
    assert k_spec == (None, "model")  # kv heads shard over model

    args1 = transformer.parse_args([
        "--batch", "8", "--seq-len", "32", "--dim", "32", "--heads", "4",
        "--kv-heads", "2", "--layers", "2", "--split-qkv", "on"])
    mesh1 = transformer.make_lm_mesh(1)
    _, _, s1, step1, _ = transformer.build(args1, mesh=mesh1)
    (tok,) = next(batches)
    (dev_tp,) = data_mod.put_global_batch(mesh, tok,
                                          spec=transformer.lm_token_spec(mesh))
    (dev_1,) = data_mod.put_global_batch(mesh1, tok, spec=None)
    _, m_tp = step(state, dev_tp)
    _, m_1 = step1(s1, dev_1)
    # bf16 matmuls: the TP psum reorders partial-product accumulation
    assert abs(float(m_tp["loss"]) - float(m_1["loss"])) < 1e-3


def test_gqa_validates_divisibility():
    import pytest

    from tpu_operator.payload import transformer

    with pytest.raises(ValueError, match="kv-heads"):
        transformer.build(transformer.parse_args(
            ["--heads", "4", "--kv-heads", "3"]),
            mesh=transformer.make_lm_mesh(1))
    with pytest.raises(ValueError, match="kv-heads"):
        # 4 % -1 == 0 in Python: the sign needs its own check
        transformer.build(transformer.parse_args(
            ["--heads", "4", "--kv-heads", "-1"]),
            mesh=transformer.make_lm_mesh(1))
    with pytest.raises(ValueError, match="kv-heads"):
        transformer.build(transformer.parse_args(
            ["--heads", "4", "--kv-heads", "1", "--tensor-parallel", "2",
             "--dim", "32"]),
            mesh=transformer.make_lm_mesh(4, tensor_parallel=2))


def test_split_qkv_off_under_tp_warns(caplog):
    """--split-qkv off with a model axis > 1 shards a fused [d,3d]
    kernel's columns across the q/k/v thirds — supported (checkpoint
    layout compat, test_tp_fused_qkv_compat_shards_packed_kernel) but
    heads stop being shard-local, so both LM payloads must say so."""
    import logging

    from tpu_operator.payload import moe, transformer

    with caplog.at_level(logging.WARNING):
        transformer.build(transformer.parse_args(
            ["--batch", "8", "--heads", "4", "--dim", "32", "--seq-len",
             "32", "--tensor-parallel", "2", "--split-qkv", "off"]),
            mesh=transformer.make_lm_mesh(4, tensor_parallel=2))
    assert any("split-qkv off" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.WARNING):
        moe.build(moe.parse_args(
            ["--batch", "8", "--heads", "4", "--dim", "32", "--seq-len",
             "32", "--experts", "4", "--tensor-parallel", "2",
             "--split-qkv", "off"]),
            mesh=moe.make_moe_mesh(8, expert_parallel=2, tensor_parallel=2))
    assert any("split-qkv off" in r.message for r in caplog.records)
