"""Satellite observability: the event-dedup cache is bounded, and chaos
kills are attributable (Event + counter) instead of silent."""

import random

from tpu_operator.client.fake import FakeClientset
from tpu_operator.controller.chaos import ChaosMonkey
from tpu_operator.controller.events import EventRecorder
from tpu_operator.controller.statusserver import Metrics


class Obj:
    def __init__(self, name, namespace="default"):
        self.name = name
        self.namespace = namespace
        self.metadata = {"name": name, "namespace": namespace, "uid": f"u-{name}"}


def test_event_seen_cache_lru_bounded():
    cs, metrics = FakeClientset(), Metrics()
    rec = EventRecorder(cs, seen_cap=2, metrics=metrics)
    for i in range(4):
        rec.event(Obj(f"job{i}"), "Normal", "Reason", "msg")
    assert len(rec._seen) == 2
    snap = metrics.snapshot()
    assert snap["events_emitted_total"] == 4
    assert snap["events_pruned_total"] == 2
    # evicted entry re-records as a fresh Event instead of crashing
    rec.event(Obj("job0"), "Normal", "Reason", "msg")
    assert len(rec._seen) == 2


def test_event_aggregation_counts_and_forget_object():
    cs, metrics = FakeClientset(), Metrics()
    rec = EventRecorder(cs, metrics=metrics)
    job = Obj("agg")
    rec.event(job, "Normal", "Reason", "same msg")
    rec.event(job, "Normal", "Reason", "same msg")
    (ev,) = cs.events.list("default")
    assert ev["count"] == 2
    snap = metrics.snapshot()
    assert snap["events_emitted_total"] == 2
    assert snap["events_aggregated_total"] == 1
    # object deleted → its dedup entries prune, counted
    assert rec.forget_object("default", "agg") == 1
    assert metrics.snapshot()["events_pruned_total"] == 1
    assert not rec._seen


def test_chaos_kill_records_event_and_counter():
    cs, metrics = FakeClientset(), Metrics()
    rec = EventRecorder(cs, metrics=metrics)
    cs.pods.create("default", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": "victim", "namespace": "default",
            "labels": {"tpuoperator.dev": ""},
            "ownerReferences": [{"kind": "TPUJob", "name": "myjob",
                                 "uid": "u-1", "controller": True}],
        },
        "status": {"phase": "Running"},
    })
    monkey = ChaosMonkey(cs, "default", level=0, rng=random.Random(0),
                         recorder=rec, metrics=metrics)
    assert monkey.kill_once() == 1
    assert metrics.snapshot()["chaos_kills_total"] == 1
    events = cs.events.list("default")
    kill_events = [e for e in events if e["reason"] == "ChaosPodKill"]
    assert kill_events, events
    ev = kill_events[0]
    assert ev["involvedObject"]["name"] == "myjob"
    assert ev["involvedObject"]["kind"] == "TPUJob"
    assert "victim" in ev["message"]


def test_chaos_without_recorder_still_counts():
    cs, metrics = FakeClientset(), Metrics()
    cs.pods.create("default", {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "p", "namespace": "default",
                     "labels": {"tpuoperator.dev": ""}},
        "status": {"phase": "Running"},
    })
    monkey = ChaosMonkey(cs, "default", level=0, rng=random.Random(0),
                         metrics=metrics)
    assert monkey.kill_once() == 1
    assert metrics.snapshot()["chaos_kills_total"] == 1
