"""Chaos soak: the full control loop survives compound, seeded misery.

The operator (informers → workqueue → reconcile, in-process threads) runs
against the real HTTP apiserver harness through a :class:`FlakyClientset`
injecting 429/500s into 10% of its own API calls, while a chaos monkey at
level 1 deletes managed pods and a simulated kubelet preempts the first two
generations outright. The checkpointed job must still reach DONE:

- the preemptions draw from the enlarged preemption budget (``maxRestarts``
  is 1 — the seed-era shared budget would have failed the job on the second
  preemption);
- restarts are spaced through the BACKOFF phase (observed in the phase
  timeline), released by the deadline manager's exact-time wakeup;
- afterwards no pods from stale generations survive.

Every random source is seeded; timing is thread-scheduling dependent but
the outcome (restart count, final phase, pod set) is not.
"""

import random
import threading
import time


from tpu_operator.client.errors import ApiError
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.client.workqueue import RateLimitingQueue
from tpu_operator.controller.chaos import ChaosMonkey, FlakyClientset
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import Metrics
from tpu_operator.testing.apiserver import ApiServerHarness
from tests.test_informer_controller import wait_for


def soak_job_dict():
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "soak", "namespace": "default"},
        "spec": {
            "replicaSpecs": [{
                "replicas": 2, "tpuReplicaType": "WORKER", "tpuPort": 8476,
                "template": {"spec": {"containers": [{"name": "tpu"}]}},
            }],
            # ONE application restart — two preemptions under the old
            # shared budget would have failed this job.
            "maxRestarts": 1,
            "checkpointDir": "/ckpt/soak",
            "restartBackoff": {"baseSeconds": 1, "maxSeconds": 4},
        },
    }


class KubeletSim(threading.Thread):
    """Walks pods Pending → Running; preempts every Running pod of
    generations 0 and 1 (Failed with reason Preempted — kubelet-level, no
    container record) until those generations are gone, then lets later
    generations run briefly and succeed. Re-preempting replacements is
    deliberate: a chaos kill can delete a Failed pod before the operator
    observes it, and a real preempted slice keeps killing whatever lands on
    it. The ledger's one-record-per-attempt invariant keeps the budget
    math at exactly one preemption per generation regardless."""

    PREEMPTED_ATTEMPTS = ("0", "1")

    def __init__(self, cs, stop):
        super().__init__(daemon=True, name="kubelet-sim")
        self.cs = cs
        self.stop_event = stop
        self.running_since = {}

    def run(self):
        while not self.stop_event.is_set():
            try:
                self.tick()
            except ApiError:
                pass  # racing the operator's teardown is expected
            time.sleep(0.05)

    def tick(self):
        now = time.monotonic()
        for pod in self.cs.pods.list("default"):
            md = pod["metadata"]
            name = md["name"]
            attempt = (md.get("labels") or {}).get("attempt", "")
            phase = (pod.get("status") or {}).get("phase", "")
            if phase in ("", "Pending"):
                pod["status"] = {
                    "phase": "Running",
                    "containerStatuses": [
                        {"name": "tpu", "state": {"running": {}}}],
                }
                self.running_since.setdefault(name, now)
                self.cs.pods.update_status("default", pod)
            elif phase == "Running":
                ran = now - self.running_since.get(name, now)
                if attempt in self.PREEMPTED_ATTEMPTS and ran >= 0.2:
                    # slice preemption: pod Failed at the kubelet level
                    pod["status"] = {"phase": "Failed",
                                     "reason": "Preempted",
                                     "message": "node preempted"}
                    self.cs.pods.update_status("default", pod)
                elif attempt not in self.PREEMPTED_ATTEMPTS and ran >= 0.8:
                    # checkpointed payload finishes its remaining steps
                    pod["status"] = {
                        "phase": "Succeeded",
                        "containerStatuses": [
                            {"name": "tpu",
                             "state": {"terminated": {"exitCode": 0}}}],
                    }
                    self.cs.pods.update_status("default", pod)


def test_chaos_soak_checkpointed_job_reaches_done():
    harness = ApiServerHarness().start()
    raw = Clientset(RestConfig(host=harness.url, timeout=5.0))
    # The operator's own view of the world is flaky: 10% of CRUD calls
    # throw 429/500 (seeded), exercising requeue + gang rollback paths.
    metrics = Metrics()
    flaky = FlakyClientset(
        Clientset(RestConfig(host=harness.url, timeout=5.0)),
        error_rate=0.10, rng=random.Random(7), metrics=metrics)

    factory = SharedInformerFactory(flaky, "default", resync_period=1.0)
    controller = Controller(
        flaky, factory, namespace="default", metrics=metrics,
        queue=RateLimitingQueue(base_delay=0.2, max_delay=1.0),
    )
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True, name="soak-controller")
    runner.start()

    kubelet = KubeletSim(raw, stop)
    kubelet.start()

    # Level-1 chaos monkey against the raw client, seeded; stopped once the
    # final generation appears so the run has a deterministic end state.
    chaos_stop = threading.Event()
    monkey = ChaosMonkey(raw, "default", level=1, interval=0.3,
                         rng=random.Random(3), metrics=metrics)
    chaos = threading.Thread(target=monkey.run, args=(chaos_stop,),
                             daemon=True, name="soak-chaos")
    chaos.start()

    try:
        raw.tpujobs.create("default", soak_job_dict())

        def job_status():
            try:
                return raw.tpujobs.get("default", "soak").get("status") or {}
            except ApiError:
                return {}

        # both preemption rounds must pass through the backoff phase
        assert wait_for(lambda: job_status().get("attempt", 0) >= 2,
                        timeout=60.0), job_status()
        chaos_stop.set()

        assert wait_for(lambda: job_status().get("phase") == "Done",
                        timeout=60.0), job_status()

        status = job_status()
        assert status["state"] == "Succeeded"
        assert status["attempt"] == 2
        # backoff was observed between generations
        assert "Backoff" in (status.get("phaseTimeline") or {}), status
        # the ledger classified both restarts as preemption — the
        # application budget (1) was never touched
        kinds = [f["kind"] for f in status.get("failures") or []]
        assert kinds == ["preemption", "preemption"], status.get("failures")

        # no pods leak: only the final generation's pods remain, terminal
        def only_final_generation():
            pods = raw.pods.list("default")
            return (len(pods) == 2
                    and all(p["metadata"]["labels"]["attempt"] == "2"
                            for p in pods)
                    and all((p.get("status") or {}).get("phase")
                            == "Succeeded" for p in pods))
        assert wait_for(only_final_generation, timeout=30.0), [
            (p["metadata"]["name"],
             p["metadata"]["labels"].get("attempt"),
             (p.get("status") or {}).get("phase"))
            for p in raw.pods.list("default")]

        # the soak actually exercised the chaos paths it claims to
        snap = metrics.snapshot()
        assert snap["chaos_api_errors_total"] > 0
    finally:
        chaos_stop.set()
        stop.set()
        runner.join(timeout=10.0)
        harness.stop()
