"""Paged KV-cache decode engine tests (payload/kvcache.py, ISSUE 20).

The oracle is the full re-forward: at a fixed seed, greedy decode through
the paged incremental engine must reproduce the greedy sequence of
re-running the whole growing context through ``model.apply`` every token
— the cache is an optimization, never a semantic change. Below that, the
functional decode mirrors (``models.lm_decode_apply``) must be BIT-equal
to the flax module forward, and the allocator's page accounting must
hold under admission/release churn.
"""

import argparse

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tpu_operator.payload import flash_attention as fa
from tpu_operator.payload import kvcache
from tpu_operator.payload import models
from tpu_operator.payload import ring_attention as ring
from tpu_operator.payload import train
from tpu_operator.payload import transformer

WINDOW = 16
NEW = 4
VOCAB = 32
DIM = 16


# --- fixtures -----------------------------------------------------------------


def build_model(kv_heads=1, layers=2, seed=0):
    """(model, params) on the serve payload's exact build path — seq_len
    spans prompt + decode so the position table covers grown contexts."""
    shim = argparse.Namespace(
        vocab=VOCAB, dim=DIM, heads=2, kv_heads=kv_heads, layers=layers,
        seq_len=WINDOW + NEW, seq_parallel=1, tensor_parallel=1,
        split_qkv="auto", sp_mode="ring", sp_layout="contiguous",
        remat=False)
    mesh = train.make_mesh(axis_names=("data", "model"))
    model = transformer._build_model(shim, mesh)
    sample = jnp.zeros((2, WINDOW), jnp.int32)
    state = train.create_train_state(model, jax.random.key(seed), sample,
                                     optax.adam(1e-3))
    return model, state.params


def make_engine(kv_heads=1, layers=2, slots=2, page_size=4, num_pages=0):
    spec = kvcache.ModelSpec(vocab=VOCAB, dim=DIM, heads=2, layers=layers,
                             max_seq=WINDOW + NEW, kv_heads=kv_heads)
    return kvcache.DecodeEngine(spec, slots=slots, prompt_pad=WINDOW,
                                max_new=NEW, page_size=page_size,
                                num_pages=num_pages)


def greedy_reforward(model, params, prompt, n):
    """The dense oracle: re-forward the whole growing context per token."""
    ctx = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray(np.array(ctx, np.int32)[None, :]))
        nxt = int(jnp.argmax(logits[0, -1].astype(jnp.float32)))
        out.append(nxt)
        ctx.append(nxt)
    return out


def prompt_of(seed, length=WINDOW):
    return (np.arange(length) * 3 + seed + 1).astype(np.int32) % VOCAB


# --- page allocator invariants ------------------------------------------------


def test_allocator_alloc_free_reuse():
    a = kvcache.PageAllocator(4)
    assert a.free_pages == 4 and a.held_pages == 0
    first = a.alloc(3)
    assert sorted(first) == [0, 1, 2]
    assert a.utilization() == pytest.approx(0.75)
    # All-or-nothing: 2 > 1 free page → None, nothing leaked.
    assert a.alloc(2) is None
    assert a.free_pages == 1
    a.free(first)
    assert a.free_pages == 4 and a.held_pages == 0
    # Freed pages are immediately reusable.
    assert sorted(a.alloc(4)) == [0, 1, 2, 3]


def test_allocator_double_and_foreign_free_raise():
    a = kvcache.PageAllocator(2)
    pages = a.alloc(1)
    a.free(pages)
    with pytest.raises(ValueError):
        a.free(pages)  # double free
    with pytest.raises(ValueError):
        a.free([7])  # never allocated from this pool
    with pytest.raises(ValueError):
        a.alloc(0)
    with pytest.raises(ValueError):
        kvcache.PageAllocator(0)


# --- the functional decode mirrors --------------------------------------------


@pytest.mark.parametrize("kv_heads", [0, 1])
def test_lm_decode_apply_bit_equal_to_module(kv_heads):
    """models.lm_decode_apply (the standalone-apply mirror the engine
    jits) must be BIT-equal to the flax TransformerLM forward — same
    params, same submodule math, only the attention callable injected."""
    model, params = build_model(kv_heads=kv_heads)
    tokens = jnp.asarray(prompt_of(0)[None, :])
    want = model.apply({"params": params}, tokens)

    def attend_for_layer(_i):
        return lambda q, k, v: ring.reference_attention(q, k, v,
                                                        causal=True)

    positions = jnp.arange(WINDOW, dtype=jnp.int32)[None, :]
    got = models.lm_decode_apply(
        params, tokens, positions, attend_for_layer, vocab=VOCAB, dim=DIM,
        heads=2, kv_heads=kv_heads, layers=2, max_seq=WINDOW + NEW)
    assert np.array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("use_pallas", [False, True])
def test_flash_decode_matches_reference(use_pallas):
    """The cached-decode kernel path (Pallas in interpret mode on CPU)
    against the jnp reference: length-masked single-token GQA attention
    over a padded cache span."""
    rng = np.random.default_rng(0)
    b, s, h, kvh, d = 3, 32, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, kvh, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, kvh, d)), jnp.float32)
    lengths = jnp.asarray([1, 17, 32], jnp.int32)
    got = fa.flash_decode(q, k, v, lengths, use_pallas=use_pallas)
    want = fa._decode_ref(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_decode_ignores_garbage_past_length():
    """The masking discipline: positions >= length contribute EXACTLY
    nothing — poisoning them (NaN would propagate through any nonzero
    weight) must not change the output at all."""
    rng = np.random.default_rng(1)
    b, s, h, d = 2, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    k = np.asarray(rng.normal(size=(b, s, h, d)), np.float32)
    v = np.asarray(rng.normal(size=(b, s, h, d)), np.float32)
    lengths = jnp.asarray([5, 12], jnp.int32)
    clean = fa._decode_ref(q, jnp.asarray(k), jnp.asarray(v), lengths)
    k[0, 5:], v[0, 5:] = 1e30, -1e30
    k[1, 12:], v[1, 12:] = 1e30, -1e30
    dirty = fa._decode_ref(q, jnp.asarray(k), jnp.asarray(v), lengths)
    assert np.array_equal(np.asarray(clean), np.asarray(dirty))


# --- paged incremental decode vs the dense re-forward -------------------------


@pytest.mark.parametrize("kv_heads", [0, 1])
def test_incremental_decode_matches_reforward(kv_heads):
    """The tentpole equivalence: greedy decode through the paged cache ==
    greedy re-forward of the whole growing context, token for token, at
    MHA (kv_heads=0) and GQA shapes."""
    model, params = build_model(kv_heads=kv_heads)
    eng = make_engine(kv_heads=kv_heads)
    prompt = prompt_of(0)
    toks = [eng.admit(0, prompt, NEW, params)]
    for _ in range(NEW - 1):
        out = eng.step(params, np.array([True, False]))
        toks.append(int(out[0]))
    assert toks == greedy_reforward(model, params, prompt, NEW)


def test_short_prompt_and_concurrent_slots_match_reforward():
    """Short (padded) prompts and two slots decoding concurrently: each
    slot's sequence must equal its own dense reference — neither the
    padded prompt tail nor the neighbour's pages may leak in."""
    model, params = build_model()
    eng = make_engine()
    p0, p1 = prompt_of(0, length=5), prompt_of(9, length=11)
    toks0 = [eng.admit(0, p0, NEW, params)]
    toks1 = [eng.admit(1, p1, NEW, params)]
    for _ in range(NEW - 1):
        out = eng.step(params, np.array([True, True]))
        toks0.append(int(out[0]))
        toks1.append(int(out[1]))
    assert toks0 == greedy_reforward(model, params, p0, NEW)
    assert toks1 == greedy_reforward(model, params, p1, NEW)


def test_page_table_indirection_is_transparent():
    """Page-table correctness: after churn scrambles which physical pages
    a slot owns, decode through the scrambled table still equals the
    dense reference — the table, not page adjacency, defines the span."""
    model, params = build_model()
    eng = make_engine(slots=2)
    # Burn pages so the next admission gets a non-contiguous, non-zero
    # page set: admit+release on slot 0, then hold slot 1, re-admit 0.
    eng.admit(0, prompt_of(3), NEW, params)
    eng.admit(1, prompt_of(4), NEW, params)
    eng.release(0)
    prompt = prompt_of(7)
    toks = [eng.admit(0, prompt, NEW, params)]
    assert eng.slot_pages(0)[0] != 0  # genuinely scrambled physical pages
    for _ in range(NEW - 1):
        out = eng.step(params, np.array([True, False]))
        toks.append(int(out[0]))
    assert toks == greedy_reforward(model, params, prompt, NEW)


# --- slot admission / eviction churn ------------------------------------------


def test_admission_churn_invariants_and_page_reuse():
    """Admit/release churn across slots: the allocator's accounting stays
    exact (held + free == pool, no page owned twice), a released slot's
    pages immediately serve the next admission, and a full pool refuses
    (returns None) instead of corrupting."""
    _model, params = build_model()
    eng = make_engine(slots=2)  # pool auto-sized: 2 slots × 5 pages
    assert eng.num_pages == 2 * eng.pages_per_slot
    first = eng.admit(0, prompt_of(0), NEW, params)
    assert first is not None
    held0 = eng.slot_pages(0)
    eng.admit(1, prompt_of(1), NEW, params)
    held1 = eng.slot_pages(1)
    assert not set(held0) & set(held1)  # no page owned twice
    assert eng.allocator.held_pages + eng.allocator.free_pages \
        == eng.num_pages
    assert eng.utilization() == pytest.approx(1.0)
    # Pool exhausted: a third admission is refused, not partially built.
    assert not eng.can_admit(WINDOW, NEW)
    # Double-admit into an occupied slot is a caller bug, not a refusal.
    with pytest.raises(ValueError):
        eng.admit(0, prompt_of(2), NEW, params)
    # Release slot 0 mid-flight: its pages are the next admission's.
    eng.release(0)
    assert eng.utilization() == pytest.approx(0.5)
    eng.admit(0, prompt_of(3), NEW, params)
    assert set(eng.slot_pages(0)) == set(held0)
    eng.release(1)
    with pytest.raises(ValueError):
        eng.release(1)  # second release must raise
    # Stepping an unoccupied-but-active slot is caught host-side.
    with pytest.raises(ValueError):
        eng.step(params, np.array([True, True]))
    # Decode past a slot's admitted budget is caught host-side.
    eng.admit(1, prompt_of(4), 1, params)
    with pytest.raises(ValueError):
        eng.step(params, np.array([False, True]))


def test_oversubscribed_pool_backpressures():
    """num_pages below slots × pages-per-slot: the second admission waits
    (None) until the first request's release frees its pages — exactly
    the continuous-batching admission backpressure serve.py rides."""
    _model, params = build_model()
    eng = make_engine(slots=2, num_pages=5)  # one request's worth
    assert eng.admit(0, prompt_of(0), NEW, params) is not None
    assert eng.admit(1, prompt_of(1), NEW, params) is None  # queued
    assert eng.slot_pages(1) is None
    eng.release(0)
    assert eng.admit(1, prompt_of(1), NEW, params) is not None


# --- hot reload under load ----------------------------------------------------


def test_hot_reload_swaps_params_without_invalidating_pages():
    """The serve hot-reload contract: params are an argument, so swapping
    weights mid-request touches NO cache state — the page tables and
    owned pages are untouched, the prefix decoded under the old weights
    stands, and continued decode (a) actually uses the new weights and
    (b) still matches an identically-swapped reference engine."""
    model_a, params_a = build_model(seed=0)
    _model_b, params_b = build_model(seed=1)
    eng = make_engine()
    prompt = prompt_of(0)
    toks = [eng.admit(0, prompt, NEW, params_a)]
    out = eng.step(params_a, np.array([True, False]))
    toks.append(int(out[0]))
    tables_before = eng._tables.copy()
    pages_before = eng.slot_pages(0)
    # The swap: same engine, new params, live pages.
    for _ in range(NEW - 2):
        out = eng.step(params_b, np.array([True, False]))
        toks.append(int(out[0]))
    assert np.array_equal(eng._tables, tables_before)
    assert eng.slot_pages(0) == pages_before
    assert eng.slot_length(0) == WINDOW + NEW - 1
    # Reference: a second engine making the identical swap reproduces
    # the sequence (cached-prefix semantics are deterministic)...
    ref = make_engine()
    ref_toks = [ref.admit(0, prompt, NEW, params_a)]
    ref_toks.append(int(ref.step(params_a, np.array([True, False]))[0]))
    for _ in range(NEW - 2):
        ref_toks.append(int(ref.step(params_b, np.array([True, False]))[0]))
    assert toks == ref_toks
    # ...and the prefix decoded under the old weights stands: it matches
    # the all-A dense reference exactly.
    all_a = greedy_reforward(model_a, params_a, prompt, NEW)
    assert toks[:2] == all_a[:2]


# --- serve-loop integration (continuous batching) -----------------------------


def serve_args(**kw):
    from tpu_operator.payload import serve as serve_mod

    argv = []
    defaults = {"load": "50:1", "batch": 2, "decode_tokens": NEW,
                "window": WINDOW, "vocab": VOCAB, "dim": DIM, "heads": 2,
                "kv_heads": 1, "layers": 2, "reload_poll": 0.1,
                "reload_stagger": 0.0}
    defaults.update(kw)
    for key, value in defaults.items():
        argv.extend([f"--{key.replace('_', '-')}", str(value)])
    return serve_mod.parse_args(argv)


def test_mid_iteration_completion_frees_slot_and_pages():
    """Satellite: a request finishing mid-iteration frees its slot AND
    its pages immediately — the next queued request admits on the very
    next iteration, before the longer neighbour finishes (the old loop
    recycled slots only at whole-batch boundaries)."""
    from tpu_operator.payload import bootstrap
    from tpu_operator.payload import serve as serve_mod

    args = serve_args(load="0:0")
    info = bootstrap.ProcessInfo(
        coordinator_address="", process_id=0, num_processes=1,
        worker_id=0, worker_hostnames=(), job_name="sv")
    loop = serve_mod.ServeLoop(args, info, heartbeat=None, store=None,
                               recorder=None)
    # Short request (1 token: done at admission prefill) + long request.
    short = loop.submit(prompt_of(0), 1)
    long1 = loop.submit(prompt_of(1), NEW)
    waiting = loop.submit(prompt_of(2), NEW)
    loop._admit_from_queue()
    # The short request completed DURING admission (its only token came
    # from the prefill) — its pages freed, the waiting request admitted
    # into the same iteration's free slot.
    assert short.done.is_set() and len(short.tokens) == 1
    assert not long1.done.is_set()
    loop._admit_from_queue()
    assert loop.queue_depth() == 0  # waiting admitted, not parked
    for _ in range(NEW):
        loop._decode_step()
    assert long1.done.is_set() and len(long1.tokens) == NEW
    assert waiting.done.is_set() and len(waiting.tokens) == NEW
    assert loop.completed == 3
    assert loop.engine.utilization() == 0.0
