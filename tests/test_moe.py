"""Expert-parallel MoE tests (8-device CPU mesh).

The GShard-style dispatch in payload/moe.py must be exact algebra: top-2
routing invariants, identical-experts degeneration to a dense FFN, capacity
drops that stay finite, expert-axis shardings, and end-to-end loss descent
on the (data=2, expert=4) mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_operator.payload import moe


def _args(**over):
    base = dict(batch=8, seq_len=32, dim=32, heads=2, layers=2,
                experts=4, expert_parallel=4, capacity_factor=2.0,
                dtype="f32", lr=1e-2)
    base.update(over)
    argv = []
    for k, v in base.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    return moe.parse_args(argv)


@pytest.fixture(scope="module")
def mesh():
    return moe.make_moe_mesh(8, expert_parallel=4)  # (data=2, expert=4)


def test_top2_dispatch_invariants():
    logits = jax.random.normal(jax.random.key(0), (2, 16, 4))
    dispatch, combine, aux, drop = moe.top2_dispatch(logits, capacity=16)
    # ample capacity: every token lands in exactly its two experts…
    np.testing.assert_allclose(np.asarray(dispatch.sum(axis=(2, 3))), 2.0)
    # …each slot holds at most one token…
    assert float(dispatch.sum(axis=(1,)).max()) <= 1.0 + 1e-6
    # …and renormalized gates sum to 1 per token.
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0,
                               atol=1e-6)
    # aux loss is ≥ 1 at exact balance (Switch scaling), finite here.
    assert np.isfinite(float(aux)) and float(aux) >= 1.0
    # nothing dropped at ample capacity
    assert abs(float(drop)) < 1e-6


def test_top2_capacity_drops_tokens_not_correctness():
    logits = jnp.zeros((1, 16, 2))  # all tokens tie → argmax routes all to e0
    dispatch, combine, _aux, drop = moe.top2_dispatch(logits, capacity=4)
    # expert 0 first choices fill 4 slots; the rest of its traffic drops
    assert float(dispatch[0, :, 0].sum()) <= 4.0 + 1e-6
    assert np.isfinite(np.asarray(combine)).all()
    # 32 routed assignments (2 × 16 tokens), 8 capacity slots ⇒ 75% dropped
    np.testing.assert_allclose(float(drop), 0.75, atol=1e-6)


def test_drop_frac_reported_in_training_metrics(mesh):
    """The dropped-token fraction must surface per step: ~0 at an ample
    capacity factor, decidedly nonzero when capacity is starved."""
    from tpu_operator.payload import data as data_mod

    ample = _args(capacity_factor=4.0)
    starved = _args(capacity_factor=0.25)
    _, _, st_a, step_a, batches = moe.build(ample, mesh=mesh)
    _, _, st_s, step_s, _ = moe.build(starved, mesh=mesh)
    (tok,) = next(batches)
    from jax.sharding import PartitionSpec as P

    (dev,) = data_mod.put_global_batch(mesh, tok, spec=P("data", None))
    _, m_a = step_a(st_a, dev)
    _, m_s = step_s(st_s, dev)
    assert float(m_a["drop_frac"]) < 0.05, m_a
    assert float(m_s["drop_frac"]) > 0.2, m_s
    assert 0.0 <= float(m_s["drop_frac"]) <= 1.0


def test_gather_dispatch_matches_einsum_oracle(mesh):
    """The scatter/gather dispatch must agree exactly with the GShard
    one-hot einsum path — same routing (shared top2_routing), same expert
    math, f32 so the comparison is tight. Covers kept, dropped
    (capacity-starved), and gate-renormalized tokens."""
    cls = moe._moe_mlp_class(mesh, jnp.float32)
    x = jax.random.normal(jax.random.key(3), (4, 32, 32))
    for cf in (4.0, 0.5):
        lg = cls(dim=32, experts=4, capacity_factor=cf,
                 dispatch_mode="gather")
        le = cls(dim=32, experts=4, capacity_factor=cf,
                 dispatch_mode="einsum")
        params = le.init(jax.random.key(4), x)["params"]
        got = lg.apply({"params": params}, x)
        want = le.apply({"params": params}, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

        def loss(p, layer):
            return jnp.sum(layer.apply({"params": p}, x) ** 2)

        gg = jax.grad(lambda p: loss(p, lg))(params)
        ge = jax.grad(lambda p: loss(p, le))(params)
        for kg, ke in zip(jax.tree_util.tree_leaves(gg),
                          jax.tree_util.tree_leaves(ge)):
            np.testing.assert_allclose(np.asarray(kg), np.asarray(ke),
                                       rtol=1e-4, atol=1e-4)


def test_router_z_loss_reported_and_declines_logits():
    """z-loss must appear in metrics and actually regularize: training
    with a large z coefficient must shrink router logit magnitudes vs
    z-coef 0."""
    from tpu_operator.payload import data as data_mod
    from jax.sharding import PartitionSpec as P

    mesh2 = moe.make_moe_mesh(2, expert_parallel=2)

    def run(z_coef, steps=25):
        args = _args(expert_parallel=2, router_z_coef=z_coef, lr=3e-3)
        _, _, st, step, batches = moe.build(args, mesh=mesh2)
        it = iter(batches)
        m = None
        for _ in range(steps):
            (dev,) = data_mod.put_global_batch(mesh2, next(it)[0],
                                               spec=P("data", None))
            st, m = step(st, dev)
        # router kernels live under blockN/moe/router
        total = 0.0
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                st.params)[0]:
            keys = tuple(getattr(p, "key", str(p)) for p in path)
            if "router" in keys:
                total += float(jnp.sum(leaf.astype(jnp.float32) ** 2))
        return m, total

    m_z, norm_z = run(1.0)
    m_0, norm_0 = run(0.0)
    assert np.isfinite(float(m_z["router_z"]))
    assert norm_z < norm_0, (norm_z, norm_0)


def test_aux_loss_trains_drop_frac_down(mesh):
    """The property the drop_frac metric exists to protect: from a
    near-init router at a tight capacity factor, K training steps with
    the Switch aux loss must reduce the dropped-assignment fraction.
    (Round-3 measured drop_frac 0.64 at an untrained router and had no
    evidence balancing ever engages — this pins it.)"""
    from tpu_operator.payload import data as data_mod
    from jax.sharding import PartitionSpec as P

    args = _args(capacity_factor=1.0, lr=3e-3, aux_coef=5e-2, seq_len=64)
    _, _, st, step, batches = moe.build(args, mesh=mesh)
    it = iter(batches)

    def one(st):
        (dev,) = data_mod.put_global_batch(mesh, next(it)[0],
                                           spec=P("data", None))
        return step(st, dev)

    st, m0 = one(st)
    early = float(m0["drop_frac"])
    drops = []
    for _ in range(60):
        st, m = one(st)
        drops.append(float(m["drop_frac"]))
    late = float(np.mean(drops[-10:]))
    assert late < early - 0.05, (early, late, drops[-5:])


def test_identical_experts_degenerate_to_dense_ffn(mesh):
    # When every expert holds the same weights and capacity is ample, the
    # MoE layer must compute exactly gelu(x·w1)·w2 (gates sum to 1).
    args = _args()
    cls = moe._moe_mlp_class(mesh, jnp.float32)
    layer = cls(dim=args.dim, experts=4, capacity_factor=4.0)
    x = jax.random.normal(jax.random.key(1), (4, 16, args.dim))
    params = layer.init(jax.random.key(2), x)["params"]
    w1_0 = params["w1"][0]
    w2_0 = params["w2"][0]
    params = dict(params)
    params["w1"] = jnp.broadcast_to(w1_0, params["w1"].shape)
    params["w2"] = jnp.broadcast_to(w2_0, params["w2"].shape)
    got = layer.apply({"params": params}, x)
    import flax.linen as nn

    want = nn.gelu(x @ w1_0) @ w2_0
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_expert1_matches_expert4_loss_when_balanced(mesh):
    # expert_parallel=1 vs =4 on the same spec + seed: the math is identical
    # (sharding is layout, not semantics) — losses must agree.
    args = _args()
    mesh1 = moe.make_moe_mesh(2, expert_parallel=1)
    _, _, s1, step1, batches = moe.build(_args(expert_parallel=1), mesh=mesh1)
    _, _, s4, step4, _ = moe.build(args, mesh=mesh)

    from tpu_operator.payload import data as data_mod

    (tokens,) = next(batches)
    (d1,) = data_mod.put_global_batch(mesh1, tokens)
    (d4,) = data_mod.put_global_batch(mesh, tokens)
    _, m1 = step1(s1, d1)
    _, m4 = step4(s4, d4)
    assert abs(float(m1["loss"]) - float(m4["loss"])) < 1e-4
    assert abs(float(m1["aux_loss"]) - float(m4["aux_loss"])) < 1e-4


def test_state_shardings_put_experts_on_expert_axis(mesh):
    args = _args()
    _mesh, _model, state, _step, _batches = moe.build(args, mesh=mesh)
    shardings = moe.state_shardings(mesh, state)
    flat = jax.tree_util.tree_flatten_with_path(shardings.params)[0]
    moe_specs = [s.spec for path, s in flat
                 if any(getattr(p, "key", None) in ("w1", "w2") for p in path)]
    assert moe_specs and all(s[0] == "expert" for s in moe_specs)
    router_specs = [s.spec for path, s in flat
                    if any(getattr(p, "key", None) == "router" for p in path)]
    assert router_specs and all(s == () for s in router_specs)


def test_moe_lm_loss_descends(mesh):
    args = _args(batch=16, steps=30, log_every=0)
    _mesh, _model, state, step, batches = moe.build(args, mesh=mesh)

    from tpu_operator.payload import data as data_mod

    losses = []
    for _ in range(30):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(float(metrics["aux_loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_build_validates_expert_divisibility():
    with pytest.raises(ValueError):
        moe.build(_args(experts=3, expert_parallel=4),
                  mesh=moe.make_moe_mesh(8, expert_parallel=4))


@pytest.fixture(scope="module")
def mesh_ep_tp():
    # (data=2, expert=2, model=2): the composed EP × TP mesh.
    return moe.make_moe_mesh(8, expert_parallel=2, tensor_parallel=2)


def test_ep_tp_mesh_axes(mesh_ep_tp):
    assert dict(zip(mesh_ep_tp.axis_names,
                    mesh_ep_tp.devices.shape)) == {
        "data": 2, "expert": 2, "model": 2}


def test_ep_tp_loss_matches_unsharded(mesh_ep_tp):
    # Same spec + seed on (data=2, expert=2, model=2) vs a single-device
    # mesh: sharding is layout, not semantics.
    args = _args(expert_parallel=2, tensor_parallel=2)
    mesh1 = moe.make_moe_mesh(1, expert_parallel=1)
    # split_qkv=on pins the same param tree (and init draws) on both
    # sides; the TP build splits automatically, the unsharded one would
    # default to the fused kernel.
    _, _, s1, step1, batches = moe.build(
        _args(expert_parallel=1, split_qkv="on"), mesh=mesh1)
    _, _, s8, step8, _ = moe.build(args, mesh=mesh_ep_tp)

    from tpu_operator.payload import data as data_mod

    (tokens,) = next(batches)
    (d1,) = data_mod.put_global_batch(mesh1, tokens)
    (d8,) = data_mod.put_global_batch(mesh_ep_tp, tokens)
    _, m1 = step1(s1, d1)
    _, m8 = step8(s8, d8)
    assert abs(float(m1["loss"]) - float(m8["loss"])) < 1e-4
    assert abs(float(m1["aux_loss"]) - float(m8["aux_loss"])) < 1e-4


def test_ep_tp_state_shardings(mesh_ep_tp):
    # Expert FFNs shard (expert, ·, model)/(expert, model, ·); dense
    # q/k/v column-parallel; routers replicate.
    args = _args(expert_parallel=2, tensor_parallel=2)
    _m, _model, state, _step, _b = moe.build(args, mesh=mesh_ep_tp)
    shardings = moe.state_shardings(mesh_ep_tp, state)
    flat = jax.tree_util.tree_flatten_with_path(shardings.params)[0]

    def specs_for(key):
        return [s.spec for path, s in flat
                if any(getattr(p, "key", None) == key for p in path)]

    assert all(s == ("expert", None, "model") for s in specs_for("w1"))
    assert all(s == ("expert", "model", None) for s in specs_for("w2"))
    assert all(s == (None, "model")
               for s in specs_for("q")), specs_for("q")
    assert all(s == ("model", None) for s in specs_for("attn_out"))
    assert all(s == () for s in specs_for("router"))


def test_ep_tp_loss_descends(mesh_ep_tp):
    args = _args(batch=16, expert_parallel=2, tensor_parallel=2,
                 log_every=0)
    _mesh, _model, state, step, batches = moe.build(args, mesh=mesh_ep_tp)

    from tpu_operator.payload import data as data_mod

    losses = []
    for _ in range(30):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh_ep_tp, tokens)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_ep_tp_validates_head_divisibility():
    with pytest.raises(ValueError, match="heads"):
        moe.build(_args(heads=3, expert_parallel=2, tensor_parallel=2),
                  mesh=moe.make_moe_mesh(8, expert_parallel=2,
                                         tensor_parallel=2))


def test_moe_gqa_with_ep_tp_descends(mesh_ep_tp):
    from tpu_operator.payload import data as data_mod

    args = _args(batch=16, expert_parallel=2, tensor_parallel=2,
                 heads=4, kv_heads=2)
    _m, _model, state, step, batches = moe.build(args, mesh=mesh_ep_tp)
    assert state.params["block0"]["k"]["kernel"].shape == (32, 16)
    losses = []
    for _ in range(25):
        (tok,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh_ep_tp, tok)
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_moe_gqa_validates_divisibility(mesh_ep_tp):
    with pytest.raises(ValueError, match="kv-heads"):
        moe.build(_args(heads=4, kv_heads=3),
                  mesh=moe.make_moe_mesh(2, expert_parallel=1))
    with pytest.raises(ValueError, match="kv-heads"):
        moe.build(_args(heads=4, kv_heads=-2),
                  mesh=moe.make_moe_mesh(2, expert_parallel=1))
    with pytest.raises(ValueError, match="kv-heads"):
        # MQA (1 K/V head) cannot shard over a TP degree of 2
        moe.build(_args(heads=4, kv_heads=1, expert_parallel=2,
                        tensor_parallel=2), mesh=mesh_ep_tp)
