"""tpujobctl CLI tests over the in-process HTTP apiserver."""

import io
import contextlib

import pytest

from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.testing.apiserver import ApiServerHarness


@pytest.fixture
def api():
    with ApiServerHarness() as srv:
        yield srv, Clientset(RestConfig(host=srv.url, timeout=5.0))


def run_ctl(srv, *args):
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main(["--master", srv.url, *args])
    return rc, out.getvalue()


def write_manifest(tmp_path, name="cjob", replicas=2):
    path = tmp_path / "job.yml"
    path.write_text(f"""
apiVersion: tpuoperator.dev/v1alpha1
kind: TPUJob
metadata:
  name: {name}
spec:
  checkpointDir: /ckpt/{name}
  maxRestarts: 2
  replicaSpecs:
    - replicas: {replicas}
      tpuReplicaType: WORKER
      tpuPort: 8476
      template:
        spec:
          containers:
            - name: tpu
              image: x
""")
    return str(path)


def test_submit_list_get_describe_delete(api, tmp_path):
    srv, cs = api
    rc, out = run_ctl(srv, "submit", "-f", write_manifest(tmp_path))
    assert rc == 0 and "default/cjob created" in out
    assert cs.tpujobs.get("default", "cjob")["metadata"]["name"] == "cjob"

    rc, out = run_ctl(srv, "list")
    assert rc == 0
    assert "NAME" in out and "cjob" in out and "WORKER×2" in out

    rc, out = run_ctl(srv, "get", "cjob", "-o", "json")
    assert rc == 0
    import json

    job = json.loads(out)
    assert job["spec"]["checkpointDir"] == "/ckpt/cjob"

    rc, out = run_ctl(srv, "get", "cjob")  # yaml default
    assert rc == 0 and "checkpointDir: /ckpt/cjob" in out

    # Status + an event, as the operator would write them.
    job = cs.tpujobs.get("default", "cjob")
    job["status"] = {"phase": "Running", "state": "Running", "attempt": 1,
                     "replicaStatuses": [{"tpuReplicaType": "WORKER",
                                          "state": "Running",
                                          "replicasStates": {"Running": 2}}]}
    cs.tpujobs.update_status("default", job)
    cs.events.create("default", {
        "metadata": {"name": "cjob.ev1"},
        "involvedObject": {"kind": "TPUJob", "name": "cjob"},
        "type": "Normal", "reason": "SuccessfulCreate",
        "message": "created pod cjob-worker-x-0", "count": 1,
    })

    rc, out = run_ctl(srv, "describe", "cjob")
    assert rc == 0
    assert "Phase:      Running" in out
    assert "Attempt:    1 / maxRestarts 2" in out
    assert "Checkpoint: /ckpt/cjob" in out
    assert "WORKER: 2" in out
    assert "SuccessfulCreate" in out

    rc, out = run_ctl(srv, "delete", "cjob")
    assert rc == 0 and "deleted" in out
    assert cs.tpujobs.list("default") == []


def test_submit_multi_doc_and_skip_foreign_kinds(api, tmp_path):
    srv, cs = api
    path = tmp_path / "multi.yml"
    path.write_text("""
apiVersion: v1
kind: ConfigMap
metadata: {name: not-a-job}
---
apiVersion: tpuoperator.dev/v1alpha1
kind: TPUJob
metadata: {name: a}
spec: {replicaSpecs: []}
---
apiVersion: tpuoperator.dev/v1alpha1
kind: TPUJob
metadata: {name: b, namespace: other}
spec: {replicaSpecs: []}
""")
    rc, out = run_ctl(srv, "submit", "-f", str(path))
    assert rc == 0
    assert "default/a created" in out
    assert "other/b created" in out  # manifest namespace wins
    assert cs.tpujobs.list("other")[0]["metadata"]["name"] == "b"


def test_errors_are_clean(api, tmp_path):
    srv, _cs = api
    rc, _ = run_ctl(srv, "get", "missing")
    assert rc == 1
    rc, _ = run_ctl(srv, "delete", "missing")
    assert rc == 1
    rc, _ = run_ctl(srv, "submit", "-f", str(tmp_path / "nope.yml"))
    assert rc == 1


def test_no_command_prints_help():
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main([])
    assert rc == 2
    assert "submit" in out.getvalue()
