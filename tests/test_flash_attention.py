"""Flash-attention kernel tests (Pallas, interpret mode on the CPU mesh).

The oracle is ring_attention.reference_attention; every path — single call,
streamed multi-block merge, gradients through the custom VJP, and the full
ring integration — must match it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_operator.payload import flash_attention as fa
from tpu_operator.payload import ring_attention as ring


def qkv(b=1, t=256, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    shape = (b, t, h, d)
    mk = lambda: jnp.asarray(rng.normal(size=shape), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = qkv()
    got = fa.flash_attention(q, k, v, causal=causal)
    want = ring.reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_streamed_blocks_match_reference():
    """Two sequential merge_kv_block calls over a split K/V equal one full
    attention — the exact pattern of a ring step."""
    q, k, v = qkv(t=256)
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, h, t, d = qt.shape
    half = t // 2

    carry = fa.init_carry(b, h, t, d)
    # Visit the *second* half first: order must not matter.
    carry = fa.merge_kv_block(qt, kt[:, :, half:], vt[:, :, half:], carry,
                              jnp.array([0.0, half]), causal=True)
    carry = fa.merge_kv_block(qt, kt[:, :, :half], vt[:, :, :half], carry,
                              jnp.array([0.0, 0.0]), causal=True)
    got = jnp.einsum("bhqd->bqhd", fa.finalize(carry, q.dtype))
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_pallas", [True, False])
def test_grad_matches_reference(use_pallas):
    """use_pallas=True exercises the custom VJP (_merge_fwd/_merge_bwd,
    pallas forward in interpret mode); False the plain jnp autodiff path."""
    q, k, v = qkv(t=128)

    def loss_flash(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, causal=True,
                               use_pallas=use_pallas) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ring.reference_attention(q, k, v, causal=True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_with_pallas_kernel_matches_reference():
    from tpu_operator.payload.transformer import make_lm_mesh

    mesh = make_lm_mesh(4, seq_parallel=2)
    q, k, v = qkv(b=2, t=256, h=2, d=64)
    got = ring.ring_attention(q, k, v, mesh, causal=True, use_pallas=True)
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_pallas", [True, False])
def test_ring_backward_matches_reference(use_pallas):
    """The backward ring (custom VJP rotating dK/dV accumulators) against
    dense-attention autodiff — with the flash-backward kernels in interpret
    mode (True) and the jnp tile math (False)."""
    from tpu_operator.payload.transformer import make_lm_mesh

    mesh = make_lm_mesh(4, seq_parallel=2)
    q, k, v = qkv(b=2, t=256, h=2, d=64)

    def loss_ring(q, k, v):
        out = ring.ring_attention(q, k, v, mesh, causal=True,
                                  use_pallas=use_pallas)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(
            ring.reference_attention(q, k, v, causal=True) ** 2)

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("offsets", [(0, 0, 1), (64, 0, 1), (0, 64, 1),
                                     (1, 3, 4)])
def test_bwd_kernels_multi_tile_with_offsets(monkeypatch, offsets):
    """Multi-tile backward (nq = nk = 4) at nontrivial global offsets and
    a striped stride: the causal DMA-clamp index maps (k-tiles clamped to
    the last contributing tile in the dq kernel, q-tiles to the first in
    the dkv kernel) must not change any gradient — including when whole
    grid rows are fully masked (negative clamp targets)."""
    monkeypatch.setattr(fa, "_bwd_blocks", lambda tq, tk, g: (64, 64))
    q, k, v = qkv(t=256, h=2)
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, h, t, d = qt.shape
    offs = jnp.array(offsets, jnp.int32)
    carry = fa.init_carry(b, h, t, d)
    o, l, m = fa._merge_ref(qt, kt, vt, *carry, offs, True)
    L = fa._logsumexp_rows(l, m)
    g = jnp.asarray(np.random.default_rng(9).normal(size=qt.shape),
                    jnp.float32)
    out = fa.finalize((o, l, m), jnp.float32)
    D = jnp.sum(g * out, axis=-1, keepdims=True)
    got = fa.attention_block_grads(qt, kt, vt, g, L, out, offs,
                                   causal=True, use_pallas=True)
    want = fa._bwd_ref(qt, kt, vt, g, L, D, offs, True)
    for gg, ww in zip(got, want):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(ww),
                                   rtol=2e-4, atol=2e-4)


def test_fully_masked_rows_have_zero_gradient():
    """A query block entirely before every key (causal): out = 0 and all
    gradients must be exactly 0 (the L = 0 guard in _logsumexp_rows keeps
    the backward P = exp(NEG_INF - 0) = 0, not NaN)."""
    q, k, v = qkv(t=128)
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, h, t, d = qt.shape
    L = jnp.zeros((b, h, t, 1), jnp.float32)
    out = jnp.zeros_like(qt)  # fully-masked forward output is 0
    g = jnp.ones_like(qt)
    for use_pallas in (False, True):
        dq, dk, dv = fa.attention_block_grads(
            qt, kt, vt, g, L, out, jnp.array([0, 10_000], jnp.int32),
            causal=True, use_pallas=use_pallas)
        for name, grad in (("dq", dq), ("dk", dk), ("dv", dv)):
            assert np.all(np.asarray(grad) == 0.0), (use_pallas, name)


def test_fully_masked_rows_are_zero():
    """Queries positioned entirely before every key (causal) must produce
    exactly 0, not mean(V) — the m-based finalize guard."""
    q, k, v = qkv(t=128)
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, h, t, d = qt.shape
    for use_pallas in (False, True):
        carry = fa.init_carry(b, h, t, d)
        # keys start at global position 10_000: every query is in the past
        carry = fa.merge_kv_block(qt, kt, vt, carry,
                                  jnp.array([0, 10_000], jnp.int32),
                                  causal=True, use_pallas=use_pallas)
        out = fa.finalize(carry, q.dtype)
        assert np.all(np.asarray(out) == 0.0), f"use_pallas={use_pallas}"


def qkv_gqa(b=1, t=256, h=4, kv=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda hh: jnp.asarray(rng.normal(size=(b, t, hh, d)), dtype)
    return mk(h), mk(kv), mk(kv)


@pytest.mark.parametrize("kv", [1, 2])
@pytest.mark.parametrize("causal", [True, False])
def test_gqa_flash_matches_repeat_oracle(kv, causal):
    """Grouped-KV kernel vs the jnp.repeat-based oracle (kv=1 is MQA).
    The oracle broadcasts K/V to full heads; the kernel must never need
    to."""
    q, k, v = qkv_gqa(h=4, kv=kv)
    got = fa.flash_attention(q, k, v, causal=causal, use_pallas=True)
    want = ring.reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("use_pallas", [True, False])
def test_gqa_grad_matches_repeat_oracle(use_pallas):
    """GQA gradients through the fused backward: dk/dv come back at KV
    size and must equal the oracle's gradient (which sums the repeated
    heads' contributions via the repeat's transpose)."""
    q, k, v = qkv_gqa(t=128, h=4, kv=2)

    def loss_flash(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, causal=True,
                               use_pallas=use_pallas) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ring.reference_attention(q, k, v, causal=True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert got[1].shape == k.shape and got[2].shape == v.shape
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_fused_forward_multi_tile_recurrence(monkeypatch, causal):
    """Force nq = nk = 4 so the fused forward's cross-tile machinery —
    scratch reset at ik == 0, alpha rescale of the accumulator across
    k-tiles, the clamped causal K/V index map, emit at ik == nk-1 —
    actually executes. At the default block heuristics every t <= 512
    test shape is a single tile, which reduces the kernel to its
    degenerate case and would let a cross-tile rescale bug ship green."""
    monkeypatch.setattr(fa, "_fwd_blocks", lambda tq, tk, g: (64, 64))
    q, k, v = qkv()
    got = fa.flash_attention(q, k, v, causal=causal, use_pallas=True)
    want = ring.reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_fused_forward_multi_tile_gqa_grad(monkeypatch):
    """Multi-tile (nq = nk = 4) GQA forward + fused backward vs the
    oracle — covers the group-flattened panels under cross-tile
    accumulation in both directions."""
    monkeypatch.setattr(fa, "_fwd_blocks", lambda tq, tk, g: (64, 64))
    monkeypatch.setattr(fa, "_bwd_blocks", lambda tq, tk, g: (64, 64))
    q, k, v = qkv_gqa(t=256, h=4, kv=2)

    def loss_flash(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, causal=True, use_pallas=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(ring.reference_attention(q, k, v, causal=True) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_gqa_streamed_blocks_match_reference():
    """Out-of-order merge_kv_block calls with kv-sized K/V blocks — the
    GQA ring step pattern (carry at query heads, visiting blocks at KV
    heads)."""
    q, k, v = qkv_gqa(t=256, h=4, kv=2)
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, h, t, d = qt.shape
    half = t // 2

    carry = fa.init_carry(b, h, t, d)
    carry = fa.merge_kv_block(qt, kt[:, :, half:], vt[:, :, half:], carry,
                              jnp.array([0.0, half]), causal=True,
                              use_pallas=True)
    carry = fa.merge_kv_block(qt, kt[:, :, :half], vt[:, :, :half], carry,
                              jnp.array([0.0, 0.0]), causal=True,
                              use_pallas=True)
    got = jnp.einsum("bhqd->bqhd", fa.finalize(carry, q.dtype))
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gqa_striped_offsets_kernel_matches_ref_math():
    """Grouped causal mask under a strided (striped-layout) offsets triple:
    kernel (interpret) vs the grouped jnp reference recurrence."""
    q, k, v = qkv_gqa(t=128, h=4, kv=2)
    qt = jnp.einsum("bqhd->bhqd", q)
    kt = jnp.einsum("bkhd->bhkd", k)
    vt = jnp.einsum("bkhd->bhkd", v)
    b, h, t, d = qt.shape
    offsets = jnp.array([1, 0, 2], jnp.int32)  # q at 1+2i, k at 2i
    carry = fa.init_carry(b, h, t, d)
    got = fa.merge_kv_block(qt, kt, vt, carry, offsets, causal=True,
                            use_pallas=True)
    want = fa._merge_ref(qt, kt, vt, *carry, offsets, True)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stripe", [False, True])
def test_gqa_ring_attention_matches_reference(stripe):
    """Ring attention with kv-sized K/V rotating the ring (the GQA ICI
    win), forward and backward, contiguous and striped layouts."""
    from tpu_operator.payload.transformer import make_lm_mesh

    mesh = make_lm_mesh(4, seq_parallel=2)
    q, k, v = qkv_gqa(b=2, t=256, h=4, kv=2)
    if stripe:
        perm, inv = ring.stripe_permutation(256, 2)
        qs, ks, vs = q[:, perm], k[:, perm], v[:, perm]
    else:
        qs, ks, vs = q, k, v

    def loss_ring(q_, k_, v_):
        out = ring.ring_attention(q_, k_, v_, mesh, causal=True,
                                  use_pallas=True, stripe=stripe)
        if stripe:
            out = out[:, inv]
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    def loss_ref(q_, k_, v_):
        out = ring.reference_attention(q_, k_, v_, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    (_, got_out), got = jax.value_and_grad(
        loss_ring, argnums=(0, 1, 2), has_aux=True)(qs, ks, vs)
    (_, want_out), want = jax.value_and_grad(
        loss_ref, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(got_out), np.asarray(want_out),
                               rtol=2e-5, atol=2e-5)
    if stripe:
        got = tuple(g[:, inv] for g in got)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


def test_gqa_rejects_non_divisible_heads():
    q, k, v = qkv_gqa(t=128, h=4, kv=3)
    with pytest.raises(ValueError, match="multiple of K/V heads"):
        fa.flash_attention(q, k, v, causal=True, use_pallas=False)


def test_gqa_block_heuristics():
    """GQA groups shrink blk_q to keep the flattened score panel inside
    VMEM; the blk_k budgets are the round-4 steady-state sweep optima
    (flash_attention._fwd_blocks docstring)."""
    assert fa._fwd_blocks(8192, 8192, 1) == (1024, 1024)
    assert fa._fwd_blocks(8192, 8192, 4) == (256, 1024)
    assert fa._fwd_blocks(8192, 8192, 8) == (128, 1024)
    assert fa._fwd_blocks(8192, 8192, 16) == (64, 1024)
    # backward budgets: the round-5 FULL-grad sweep (both kernels live —
    # wrt-q-only grads DCE'd the dK/dV kernel in the round-4 sweep)
    assert fa._bwd_blocks(8192, 8192, 1) == (512, 1024)
    assert fa._bwd_blocks(8192, 8192, 4) == (512, 512)
    assert fa._bwd_blocks(8192, 8192, 16) == (128, 512)
    # non-power-of-two groups (12 heads / 4 kv = group 3): the target is
    # rounded down to a power of two so blk_q still lands on a divisor
    # instead of degenerating to the whole span
    blk_q, blk_k = fa._fwd_blocks(8192, 8192, 3)
    assert blk_q <= 512 and 8192 % blk_q == 0 and blk_q * 3 <= 1024
    blk_q, _ = fa._bwd_blocks(8192, 8192, 3)
    assert blk_q <= 512 and 8192 % blk_q == 0


def test_gqa_non_power_of_two_group_matches_oracle():
    q, k, v = qkv_gqa(t=256, h=6, kv=2)  # group = 3
    got = fa.flash_attention(q, k, v, causal=True, use_pallas=True)
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pick_block():
    assert fa._pick_block(1024) == 512
    assert fa._pick_block(512) == 512
    assert fa._pick_block(256) == 256
    assert fa._pick_block(384) == 128
    assert fa._pick_block(100) == 100  # tiny test shapes: whole span


def test_infeasible_lengths_fall_back_to_jnp():
    """Odd long lengths (not 128-multiples, too big for one block) must not
    reach the kernel — they silently use _merge_ref and still match."""
    assert not fa._kernel_feasible(4000)
    assert fa._kernel_feasible(4096)
    assert fa._kernel_feasible(100)
    q, k, v = qkv(t=516)  # > 512 and not a 128-multiple
    got = fa.flash_attention(q, k, v, causal=True, use_pallas=True)
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
