"""Status server tests: probes, Prometheus metrics, and the job dashboard.

The server is exercised over real HTTP (ephemeral port) against a live
controller running on a FakeClientset — the same harness as the reconcile
tests, plus the observability surface the reference never had.
"""

import json
import threading
import time
import urllib.request

import pytest

from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import Metrics, StatusServer
from tpu_operator.testing.waiting import make_wait_for


def worker_job(name: str, replicas: int = 2) -> dict:
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1",
        "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicaSpecs": [{
            "replicas": replicas,
            "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu", "image": "x"}]}},
        }]},
    }


def get(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.read().decode(), r.headers.get("Content-Type", "")


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=10.0, interval=0.05)


@pytest.fixture()
def harness():
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0))
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(2, stop), daemon=True)
    th.start()
    try:
        yield cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()


def test_healthz_always_ok(harness):
    _cs, _c, server = harness
    status, body, _ = get(server.port, "/healthz")
    assert (status, body) == (200, "ok")


def test_readyz_standby_then_leading(harness):
    _cs, controller, server = harness
    status, body, _ = get(server.port, "/readyz")
    assert status == 200 and "standby" in body

    server.set_controller(controller)
    assert wait_for(
        lambda: "caches synced" in get(server.port, "/readyz")[1])
    status, body, _ = get(server.port, "/readyz")
    assert status == 200


def test_metrics_counts_reconciles_and_jobs_by_phase(harness):
    cs, controller, server = harness
    server.set_controller(controller)
    cs.tpujobs.create("default", worker_job("mjob"))
    assert wait_for(lambda: len(cs.pods.list("default")) == 2)

    status, body, ctype = get(server.port, "/metrics")
    assert status == 200 and "text/plain" in ctype
    assert "# TYPE tpu_operator_reconcile_total counter" in body
    reconciles = next(
        float(line.split()[-1]) for line in body.splitlines()
        if line.startswith("tpu_operator_reconcile_total "))
    assert reconciles >= 1
    assert "tpu_operator_leading 1" in body
    assert 'tpu_operator_jobs{phase="Creating"}' in body \
        or 'tpu_operator_jobs{phase="Running"}' in body
    assert "tpu_operator_workqueue_depth" in body


def test_api_jobs_rollup_and_dashboard(harness):
    cs, controller, server = harness
    server.set_controller(controller)
    cs.tpujobs.create("default", worker_job("djob", replicas=3))
    assert wait_for(lambda: len(cs.pods.list("default")) == 3)
    assert wait_for(lambda: any(
        j["name"] == "djob" and j["phase"]
        for j in json.loads(get(server.port, "/api/jobs")[1])))

    jobs = json.loads(get(server.port, "/api/jobs")[1])
    (job,) = [j for j in jobs if j["name"] == "djob"]
    assert job["namespace"] == "default"
    assert job["replicas"] == {"WORKER": 3}
    assert job["phase"] in ("Creating", "Running")

    status, body, ctype = get(server.port, "/")
    assert status == 200 and "text/html" in ctype
    assert "djob" in body and "tpu-operator" in body


def test_unknown_path_404(harness):
    _cs, _c, server = harness
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(server.port, "/nope")
    assert ei.value.code == 404


def test_metrics_object_thread_safety_smoke():
    m = Metrics()
    threads = [threading.Thread(
        target=lambda: [m.inc("reconcile_total") for _ in range(1000)])
        for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert m.snapshot()["reconcile_total"] == 8000
