"""TPUReplicaSet tests against the fake clientset.

Reference test model: pkg/trainer/replicas_test.go — create pods/services
against fakes, then list and assert names/labels/owner refs/env
(replicas_test.go:90-201), plus the pod-list → state classifier tables
(replicas_test.go:212-368). The reference's copies don't compile
(SURVEY.md §4); these run.
"""

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.client.fake import FakeClientset
from tpu_operator.trainer import replicas as r
from tpu_operator.util import util
from tests.test_types import make_template


class StubJob:
    """Minimal job back-pointer (the reference passes *TrainingJob)."""

    def __init__(self, spec, name="train", namespace="default"):
        self.metadata = {"name": name, "namespace": namespace, "uid": "uid-1"}
        self.job_spec = spec

    @property
    def name(self):
        return self.metadata["name"]

    @property
    def namespace(self):
        return self.metadata["namespace"]


def worker_spec(replicas=2, **kw):
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(replicas=replicas, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.WORKER)
        ],
        runtime_id="a1b2",
        **kw,
    )
    return set_defaults(spec)


def ps_spec():
    """Compat-mode spec: SCHEDULER listed LAST to prove coordinator selection
    is by role, not position (the reference's replicas.go:240-243 bug)."""
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(replicas=2, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.SERVER),
            t.TPUReplicaSpec(replicas=2, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.WORKER),
            t.TPUReplicaSpec(replicas=1, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.SCHEDULER),
        ],
        runtime_id="zz99",
    )
    return set_defaults(spec)


def make_set(spec=None, role=t.TPUReplicaType.WORKER):
    spec = spec or worker_spec()
    cs = FakeClientset()
    job = StubJob(spec)
    rs_spec = next(rs for rs in spec.replica_specs if rs.tpu_replica_type == role)
    return cs, job, r.TPUReplicaSet(cs, None, job, rs_spec)


# --- naming -----------------------------------------------------------------

def test_gen_general_name():
    # ref: replicas.go:570-577 — job-role-runtimeid-index
    assert r.gen_general_name("train", "WORKER", "a1b2", 3) == "train-worker-a1b2-3"


def test_gen_name_truncates_to_dns_label():
    name = r.gen_general_name("j" * 80, "WORKER", "a1b2", 0)
    assert len(name) <= 63
    assert name.endswith("-worker-a1b2-0")


def test_gen_pod_name_has_random_suffix():
    # ref: replicas.go:579-583
    util.seed(1)
    a = r.gen_pod_name("train", "WORKER", "a1b2", 0)
    b = r.gen_pod_name("train", "WORKER", "a1b2", 0)
    assert a != b
    assert a.startswith("train-worker-a1b2-0-")
    assert len(a) <= 63


# --- ctor validation (ref: replicas.go:81-117) -------------------------------

def test_ctor_rejects_bad_type():
    cs = FakeClientset()
    job = StubJob(worker_spec())
    with pytest.raises(ValueError, match="invalid replica type"):
        r.TPUReplicaSet(cs, None, job, t.TPUReplicaSpec(tpu_replica_type="BOSS",
                                                        template=make_template()))


def test_ctor_rejects_multi_scheduler():
    cs = FakeClientset()
    job = StubJob(worker_spec())
    with pytest.raises(ValueError, match="SCHEDULER"):
        r.TPUReplicaSet(
            cs, None, job,
            t.TPUReplicaSpec(replicas=3, template=make_template(),
                             tpu_replica_type=t.TPUReplicaType.SCHEDULER),
        )


def test_ctor_rejects_none_port():
    cs = FakeClientset()
    job = StubJob(worker_spec())
    with pytest.raises(ValueError, match="tpuPort"):
        r.TPUReplicaSet(cs, None, job,
                        t.TPUReplicaSpec(template=make_template(), tpu_port=None))


# --- env contract ------------------------------------------------------------

def env_map(pod):
    container = next(c for c in pod["spec"]["containers"] if c["name"] == "tpu")
    return {e["name"]: e["value"] for e in container.get("env", [])}


def test_worker_env_contract_schedulerless():
    _cs, _job, rset = make_set()
    pod = rset.pod_spec_with_index(1)
    env = env_map(pod)
    # Coordinator is WORKER[0]'s per-index service
    assert env["JAX_COORDINATOR_ADDRESS"] == "train-worker-a1b2-0:8476"
    assert env["JAX_PROCESS_ID"] == "1"
    assert env["JAX_NUM_PROCESSES"] == "2"
    assert env["TPU_WORKER_ID"] == "1"
    assert env["TPU_WORKER_HOSTNAMES"] == "train-worker-a1b2-0,train-worker-a1b2-1"
    assert env["TPUJOB_REPLICA_TYPE"] == "worker"
    assert env["TPUJOB_ATTEMPT"] == "0"
    assert "MEGASCALE_NUM_SLICES" not in env


def test_coordinator_is_scheduler_by_role_not_position():
    # Fixes ref replicas.go:240-243 (hardcoded Replicas[0])
    spec = ps_spec()
    cs = FakeClientset()
    job = StubJob(spec)
    worker_rs = r.TPUReplicaSet(cs, None, job, spec.replica_specs[1])
    env = env_map(worker_rs.pod_spec_with_index(0))
    assert env["JAX_COORDINATOR_ADDRESS"] == "train-scheduler-zz99-0:8476"
    # Global process ids follow spec order: SERVERs 0-1, WORKERs 2-3, SCHED 4
    assert env["JAX_PROCESS_ID"] == "2"
    assert env["JAX_NUM_PROCESSES"] == "5"


def test_multislice_env():
    spec = worker_spec(replicas=4)
    spec.num_slices = 2
    spec.tpu_topology = "2x2x1"
    cs = FakeClientset()
    job = StubJob(spec)
    rset = r.TPUReplicaSet(cs, None, job, spec.replica_specs[0])
    env = env_map(rset.pod_spec_with_index(3))
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    assert env["MEGASCALE_SLICE_ID"] == "1"
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "train-worker-a1b2-0"
    # Slice-local worker identity
    assert env["TPU_WORKER_ID"] == "1"
    assert env["TPU_WORKER_HOSTNAMES"] == "train-worker-a1b2-2,train-worker-a1b2-3"
    assert env["TPU_TOPOLOGY"] == "2x2x1"


def test_user_env_not_clobbered():
    spec = worker_spec()
    spec.replica_specs[0].template["spec"]["containers"][0]["env"] = [
        {"name": "JAX_COORDINATOR_ADDRESS", "value": "user-override:1"}
    ]
    cs = FakeClientset()
    rset = r.TPUReplicaSet(cs, None, StubJob(spec), spec.replica_specs[0])
    env = env_map(rset.pod_spec_with_index(0))
    assert env["JAX_COORDINATOR_ADDRESS"] == "user-override:1"


def test_env_only_into_tpu_container():
    # ref: replicas.go:235 injects only into the container named "mxnet"
    spec = worker_spec()
    spec.replica_specs[0].template["spec"]["containers"].append(
        {"name": "sidecar", "image": "busybox"}
    )
    cs = FakeClientset()
    rset = r.TPUReplicaSet(cs, None, StubJob(spec), spec.replica_specs[0])
    pod = rset.pod_spec_with_index(0)
    sidecar = next(c for c in pod["spec"]["containers"] if c["name"] == "sidecar")
    assert "env" not in sidecar


# --- pod construction --------------------------------------------------------

def test_pod_metadata_and_spec():
    spec = worker_spec(scheduler_name="gang-scheduler")
    cs = FakeClientset()
    rset = r.TPUReplicaSet(cs, None, StubJob(spec), spec.replica_specs[0])
    pod = rset.pod_spec_with_index(1, attempt=2)
    md = pod["metadata"]
    assert md["labels"]["job_name"] == "train"
    assert md["labels"]["task_index"] == "1"
    assert md["labels"]["attempt"] == "2"
    assert md["labels"]["job_type"] == "worker"
    assert md["ownerReferences"][0]["uid"] == "uid-1"
    assert md["ownerReferences"][0]["blockOwnerDeletion"] is True
    ps = pod["spec"]
    assert ps["schedulerName"] == "gang-scheduler"  # ref: replicas.go:178
    assert ps["hostname"] == "train-worker-a1b2-1"
    assert ps["subdomain"] == "train-a1b2"
    # whole-group default → operator owns restarts
    assert ps["restartPolicy"] == "Never"


def test_pod_keeps_template_restart_policy_in_per_pod_mode():
    spec = ps_spec()  # compat → PER_POD
    cs = FakeClientset()
    rset = r.TPUReplicaSet(cs, None, StubJob(spec), spec.replica_specs[1])
    pod = rset.pod_spec_with_index(0)
    assert pod["spec"]["restartPolicy"] == "OnFailure"  # from template


# --- service construction ----------------------------------------------------

def test_service_spec():
    _cs, _job, rset = make_set()
    svc = rset.service_spec_with_index(0)
    assert svc["metadata"]["name"] == "train-worker-a1b2-0"
    assert svc["spec"]["ports"][0]["port"] == 8476
    sel = svc["spec"]["selector"]
    assert sel["task_index"] == "0"
    assert "attempt" not in sel  # must keep matching across group restarts
    assert svc["metadata"]["ownerReferences"][0]["name"] == "train"


# --- sync loops --------------------------------------------------------------

def test_sync_services_idempotent():
    cs, _job, rset = make_set()
    rset.sync_services()
    assert len(cs.services.list("default")) == 2
    rset.sync_services()
    assert len(cs.services.list("default")) == 2


def test_sync_pods_creates_and_is_idempotent():
    cs, _job, rset = make_set()
    rset.sync_pods()
    pods = cs.pods.list("default")
    assert len(pods) == 2
    rset.sync_pods()
    assert len(cs.pods.list("default")) == 2
    indices = sorted(p["metadata"]["labels"]["task_index"] for p in pods)
    assert indices == ["0", "1"]


def test_sync_pods_replaces_failed_in_per_pod_mode():
    # ref: replicas.go:497 filters phase==Failed so a new pod is created
    spec = ps_spec()
    cs = FakeClientset()
    rset = r.TPUReplicaSet(cs, None, StubJob(spec), spec.replica_specs[1])
    rset.sync_pods()
    pods = cs.pods.list("default", label_selector="job_type=worker")
    victim = next(p for p in pods if p["metadata"]["labels"]["task_index"] == "0")
    victim["status"] = {"phase": "Failed"}
    cs.pods.update("default", victim)
    rset.sync_pods()
    alive = [
        p for p in cs.pods.list("default", label_selector="job_type=worker,task_index=0")
    ]
    assert len(alive) == 2  # failed original + fresh replacement
    assert any((p.get("status") or {}).get("phase") != "Failed" for p in alive)


def test_sync_pods_does_not_replace_failed_in_whole_group_mode():
    cs, _job, rset = make_set()
    rset.sync_pods()
    victim = cs.pods.list("default")[0]
    victim["status"] = {"phase": "Failed"}
    cs.pods.update("default", victim)
    rset.sync_pods()
    idx = victim["metadata"]["labels"]["task_index"]
    same_idx = cs.pods.list("default", label_selector=f"task_index={idx}")
    assert len(same_idx) == 1  # no silent replacement; group restart decides


# --- delete ------------------------------------------------------------------

def test_delete_removes_pods_and_services():
    cs, _job, rset = make_set()
    rset.sync_pods()
    rset.sync_services()
    rset.delete()
    assert cs.pods.list("default") == []
    assert cs.services.list("default") == []


def test_delete_pods_for_attempt_keeps_services():
    cs, _job, rset = make_set()
    rset.sync_services()
    rset.sync_pods(attempt=0)
    rset.delete_pods_for_attempt(0)
    assert cs.pods.list("default") == []
    assert len(cs.services.list("default")) == 2


# --- classifier tables (ref: replicas_test.go:212-368) -----------------------

def pod_with(phase="Running", container_state=None, last_state=None,
             name="p1", ts="2026-07-29T00:00:00Z", container="tpu"):
    cstatus = {"name": container}
    if container_state:
        cstatus["state"] = container_state
    if last_state:
        cstatus["lastState"] = last_state
    return {
        "metadata": {"name": name, "creationTimestamp": ts},
        "status": {"phase": phase, "containerStatuses": [cstatus]},
    }


CLASSIFIER_CASES = [
    # (pods, expected)
    ([], t.ReplicaState.STARTING),  # fixed: ref reported Running (replicas.go:358-360)
    ([pod_with(phase="Pending")], t.ReplicaState.STARTING),
    ([pod_with(container_state={"running": {}})], t.ReplicaState.RUNNING),
    ([pod_with(phase="Succeeded",
               container_state={"terminated": {"exitCode": 0}})], t.ReplicaState.SUCCEEDED),
    # permanent failure: exit 1
    ([pod_with(phase="Failed",
               container_state={"terminated": {"exitCode": 1}})], t.ReplicaState.FAILED),
    # retryable: exit 137 (SIGKILL) → replacement coming
    ([pod_with(phase="Failed",
               container_state={"terminated": {"exitCode": 137}})], t.ReplicaState.STARTING),
    # OOMKilled never retryable even at exit 137 (training.go:183-192)
    ([pod_with(phase="Failed",
               container_state={"terminated": {"exitCode": 137, "reason": "OOMKilled"}})],
     t.ReplicaState.FAILED),
    # CrashLoopBackOff waiting + lastState override (replicas.go:372-388)
    ([pod_with(container_state={"waiting": {"reason": "CrashLoopBackOff"}},
               last_state={"terminated": {"exitCode": 1}})], t.ReplicaState.FAILED),
    # waiting, never run
    ([pod_with(container_state={"waiting": {"reason": "ContainerCreating"}})],
     t.ReplicaState.STARTING),
    # no tpu-named container status → fall back to pod phase
    ([pod_with(phase="Running", container="other")], t.ReplicaState.RUNNING),
]


@pytest.mark.parametrize("pods,expected", CLASSIFIER_CASES)
def test_replica_state_from_pod_list(pods, expected):
    assert r.TPUReplicaSet.replica_state_from_pod_list(pods) == expected


def test_classifier_uses_newest_pod():
    # ref: replicas_test.go newest-pod case — old failed pod superseded
    old = pod_with(phase="Failed", container_state={"terminated": {"exitCode": 1}},
                   name="old", ts="2026-07-29T00:00:00Z")
    new = pod_with(container_state={"running": {}}, name="new",
                   ts="2026-07-29T01:00:00Z")
    assert r.TPUReplicaSet.replica_state_from_pod_list([old, new]) == t.ReplicaState.RUNNING


# --- status roll-up ----------------------------------------------------------

def set_pod_state(cs, pod, phase, terminated=None):
    pod["status"] = {
        "phase": phase,
        "containerStatuses": [
            {"name": "tpu",
             "state": {"terminated": terminated} if terminated else {"running": {}}}
        ],
    }
    cs.pods.update("default", pod)


def test_get_status_all_running():
    cs, _job, rset = make_set()
    rset.sync_pods()
    for p in cs.pods.list("default"):
        set_pod_state(cs, p, "Running")
    st = rset.get_status()
    assert st.state == t.ReplicaState.RUNNING
    assert st.replicas_states == {t.ReplicaState.RUNNING: 2}


def test_get_status_mixed_failure_wins():
    cs, _job, rset = make_set()
    rset.sync_pods()
    pods = cs.pods.list("default")
    set_pod_state(cs, pods[0], "Running")
    set_pod_state(cs, pods[1], "Failed", terminated={"exitCode": 1})
    st = rset.get_status()
    assert st.state == t.ReplicaState.FAILED


def test_get_status_starting_before_pods_exist():
    _cs, _job, rset = make_set()
    st = rset.get_status()
    assert st.state == t.ReplicaState.STARTING
    assert st.replicas_states == {t.ReplicaState.STARTING: 2}
