"""adam8 (int8 block-quantized moments) — quantizer properties and
trajectory parity against f32 optax.adam.

The parity bar: on a convex regression and on gradient streams with
realistic scale spread, the 8-bit trajectory must track f32 adam closely
enough that a user switching ``--optimizer adam8`` sees the same training
curve, not a subtly different optimizer.
"""

import numpy as np

import jax
import jax.numpy as jnp
import optax

from tpu_operator.payload import optimizers
from tpu_operator.payload.optimizers import BLOCK


def test_quantize_roundtrip_error_bound():
    """|x - deq(quant(x))| <= scale per element (stochastic rounding adds
    at most one ulp on top of the half-ulp nearest bound)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, BLOCK)) * 10.0, jnp.float32)
    t = optimizers._quantize(x, None, False)
    assert t.q.dtype == jnp.int8
    back = optimizers._dequantize(t, False)
    scale = np.asarray(t.scale)[:, None]
    assert np.all(np.abs(np.asarray(back - x)) <= scale * 0.5 + 1e-9)

    key = jax.random.key(1)
    t2 = optimizers._quantize(x, key, False)
    back2 = optimizers._dequantize(t2, False)
    assert np.all(np.abs(np.asarray(back2 - x)) <= scale * 1.0 + 1e-9)


def test_quantize_sqrt_domain_nonnegative():
    """sqrt-domain roundtrip: relative error on v is bounded by ~2 ulp of
    the sqrt (error doubles through the square), and results stay >= 0."""
    rng = np.random.default_rng(2)
    # 4 orders of magnitude within one block — the hostile case for
    # linear-domain int8, survivable in sqrt domain.
    v = jnp.asarray(10.0 ** rng.uniform(-4, 0, size=(2, BLOCK)), jnp.float32)
    t = optimizers._quantize(v, None, True)
    back = optimizers._dequantize(t, True)
    assert np.all(np.asarray(back) >= 0.0)
    scale = np.asarray(t.scale)[:, None]
    err_sqrt = np.abs(np.sqrt(np.asarray(back)) - np.sqrt(np.asarray(v)))
    assert np.all(err_sqrt <= scale * 0.5 + 1e-9)


def test_stochastic_rounding_unbiased():
    """The mean of many stochastic quantizations recovers values far
    below one ulp — the property that keeps slow EMAs from freezing."""
    x = jnp.full((1, BLOCK), 0.3, jnp.float32)
    # Plant one large element so the block scale is 1.0 (absmax 127).
    x = x.at[0, 0].set(127.0)
    keys = jax.random.split(jax.random.key(3), 256)
    deqs = jnp.stack([
        optimizers._dequantize(optimizers._quantize(x, k, False), False)
        for k in keys])
    mean = float(jnp.mean(deqs[:, 0, 1]))
    # 0.3 is 0.3 ulp at scale 1; nearest rounding would give 0.0 always.
    assert abs(mean - 0.3) < 0.1


def test_adam8_matches_adam_trajectory():
    """Convex regression, 60 steps: adam8's loss curve tracks f32 adam
    within a few percent at every step — the drop-in guarantee."""
    rng = np.random.default_rng(4)
    a = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    w0 = {"w": jnp.zeros((32,), jnp.float32),
          "bias": jnp.zeros((1,), jnp.float32)}

    def loss_fn(p):
        pred = a @ p["w"] + p["bias"][0]
        return jnp.mean((pred - b) ** 2)

    def run(tx):
        p = {k: v for k, v in w0.items()}
        state = tx.init(p)
        losses = []
        step = jax.jit(lambda p, s: _step(tx, p, s))
        for _ in range(60):
            p, state, l = step(p, state)
            losses.append(float(l))
        return np.asarray(losses)

    def _step(tx, p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        upd, s = tx.update(g, s, p)
        return optax.apply_updates(p, upd), s, l

    ref = run(optax.adam(1e-1))
    got = run(optimizers.adam8(1e-1, seed=7))
    # same curve: every step within 5% relative (plus small abs floor
    # once the loss is near zero)
    np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-3)


def test_adam8_constant_gradient_moments_converge():
    """Feeding a constant gradient, the dequantized moments must converge
    to m = g and v = g^2 despite per-step increments far below one int8
    ulp — the swamping case stochastic rounding exists for."""
    g = {"w": jnp.asarray(np.linspace(-2.0, 2.0, BLOCK), jnp.float32)}
    tx = optimizers.adam8(1e-3, seed=5)
    state = tx.init(g)
    update = jax.jit(lambda gr, s: tx.update(gr, s))
    for _ in range(300):
        _, state = update(g, state)
    m = optimizers._dequantize(
        jax.tree_util.tree_leaves(
            state.m, is_leaf=lambda x: isinstance(x, optimizers.Quantized)
        )[0], False)[0, :]
    v = optimizers._dequantize(
        jax.tree_util.tree_leaves(
            state.v, is_leaf=lambda x: isinstance(x, optimizers.Quantized)
        )[0], True)[0, :]
    gw = np.asarray(g["w"])
    # EMA bias after 300 steps at b2=0.999 is ~26%: compare against the
    # biased EMA targets, not the asymptote.
    m_target = gw * (1 - 0.9 ** 300)
    v_target = gw ** 2 * (1 - 0.999 ** 300)
    np.testing.assert_allclose(np.asarray(m), m_target, rtol=0.05,
                               atol=0.02 * np.max(np.abs(gw)))
    np.testing.assert_allclose(np.asarray(v), v_target, rtol=0.12,
                               atol=0.02 * np.max(gw ** 2))


def test_adam8_heterogeneous_block_update_bounded():
    """Regression: an element whose |m| survives the linear int8 code but
    whose v (~m²) underflows the sqrt-domain code used to divide by
    ~eps and produce ~1e6·lr steps (flagship divergence, loss 1e9). The
    denominator's quantization-noise floor must keep every update within
    Adam's normal step-size envelope."""
    tx = optimizers.adam8(1e-2, seed=11)
    # one dominant element per block, the rest 1e-3 of it: m resolvable,
    # v below sqrt-code resolution
    g = {"w": jnp.concatenate([
        jnp.asarray([1.0], jnp.float32),
        jnp.full((BLOCK - 1,), 1e-3, jnp.float32)])}
    state = tx.init(g)
    update = jax.jit(lambda gr, s: tx.update(gr, s))
    for _ in range(50):
        upd, state = update(g, state)
        # bias correction allows a few x lr early; 1e6 x lr is the bug
        assert float(jnp.max(jnp.abs(upd["w"]))) < 5 * 1e-2


def test_adam8_nonaligned_shapes_and_dtypes():
    """Leaves whose sizes do not divide BLOCK (padding path) and bf16
    gradients round-trip with correct update shapes/dtypes."""
    params = {"a": jnp.ones((7, 33), jnp.float32),
              "b": jnp.ones((5,), jnp.bfloat16)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 0.5, p.dtype), params)
    tx = optimizers.adam8(1e-2)
    state = tx.init(params)
    upd, state = jax.jit(lambda g, s: tx.update(g, s))(grads, state)
    assert upd["a"].shape == (7, 33) and upd["a"].dtype == jnp.float32
    assert upd["b"].shape == (5,) and upd["b"].dtype == jnp.bfloat16
    # all-equal gradients -> all-equal updates (padding must not leak in)
    au = np.asarray(upd["a"], np.float32)
    np.testing.assert_allclose(au, au.ravel()[0], rtol=1e-6)


def test_adam8_composes_with_pipeline_and_fsdp_shardings():
    """Regression: the flat [nblocks, 256] moment layout broke
    device_put under the pipeline's path-based sharding rule (a stage-
    stacked P('pipe', ...) spec cannot apply to a reshaped moment). The
    last-axis block layout must keep leading axes so moments shard like
    their parameter under every payload rule."""
    from tpu_operator.payload import pipeline, transformer
    from tpu_operator.payload import data as data_mod
    from jax.sharding import PartitionSpec as P

    args = pipeline.parse_args(
        ["--dim", "32", "--layers", "4", "--heads", "2", "--batch", "16",
         "--seq-len", "64", "--vocab", "128", "--pipeline", "2",
         "--microbatches", "4", "--optimizer", "adam8"])
    mesh, _m, state, step, batches = pipeline.build(args)
    batch = next(iter(batches))
    placed = data_mod.put_global_batch(mesh, *batch, spec=P("data", None))
    state, metrics = step(state, *placed)
    assert np.isfinite(float(metrics["loss"]))

    targs = transformer.parse_args(
        ["--dim", "32", "--layers", "2", "--heads", "2", "--batch", "8",
         "--seq-len", "64", "--vocab", "128", "--fsdp",
         "--optimizer", "adam8"])
    tmesh, _tm, tstate, tstep, tbatches = transformer.build(targs)
    tb = data_mod.put_global_batch(tmesh, *next(iter(tbatches)),
                                   spec=P("data", None))
    tstate, tmetrics = tstep(tstate, *tb)
    assert np.isfinite(float(tmetrics["loss"]))


def test_adam8_moments_shard_like_params_under_name_keyed_rules():
    """Regression: the Quantized NamedTuple hop appends '.q'/'.scale'
    path keys and changes rank, so name/rank-keyed rules (MoE expert
    sharding, Megatron TP) fell through to replicate — forfeiting the
    moment sharding. train.quantized_aware must map the parameter's spec
    onto the block layout."""
    from jax.sharding import Mesh, PartitionSpec as P
    from tpu_operator.payload import train

    devices = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devices, ("expert", "model"))

    def rule(keys, leaf):
        if keys[-1] == "w1" and leaf.ndim == 3:
            return P("expert", None, "model")
        if keys[-1] == "kernel" and leaf.ndim == 2:
            return P(None, "model")
        return P()

    wrapped = train.quantized_aware(mesh, rule)
    params = {"moe": {"w1": jnp.zeros((2, 8, 1024), jnp.float32)},
              "attn": {"kernel": jnp.zeros((8, 1024), jnp.float32)}}
    state = optimizers.adam8(1e-3).init(params)

    def keys_of(path):
        return tuple(getattr(p, "key", str(p)) for p in path)

    specs = jax.tree_util.tree_map_with_path(
        lambda path, leaf: wrapped(keys_of(path), leaf), state.m)
    # w1 [2,8,1024] -> q [2,8,4,256]: expert on dim0, model on nb (4 % 4
    # == 0... 1024/256 = 4 blocks, model axis size 4)
    assert specs["moe"]["w1"].q == P("expert", None, "model", None)
    assert specs["moe"]["w1"].scale == P("expert", None, "model")
    assert specs["attn"]["kernel"].q == P(None, "model", None)
    # params themselves still go through the raw rule untouched
    assert wrapped(("moe", "w1"),
                   params["moe"]["w1"]) == P("expert", None, "model")
    # non-divisible block count must drop the axis, not crash: last dim
    # 256 -> nb 1, model size 4 does not divide 1
    small = optimizers.adam8(1e-3).init({"attn": {"kernel":
                                        jnp.zeros((8, 256), jnp.float32)}})
    sp = jax.tree_util.tree_map_with_path(
        lambda path, leaf: wrapped(keys_of(path), leaf), small.m)
    assert sp["attn"]["kernel"].q == P(None, None, None)


def test_adam8_schedule_sees_preincrement_count():
    """Callable learning rates must see count 0 on the first update,
    matching optax.scale_by_schedule — a warmup-from-zero schedule must
    produce a zero first step."""
    schedule = lambda count: 0.0 if count < 1 else 1e-2
    tx = optimizers.adam8(schedule)
    g = {"w": jnp.ones((4,), jnp.float32)}
    state = tx.init(g)
    upd, state = tx.update(g, state)
    np.testing.assert_allclose(np.asarray(upd["w"]), 0.0)
    upd, state = tx.update(g, state)
    assert float(jnp.max(jnp.abs(upd["w"]))) > 0.0


def test_adam8_state_memory_is_8bit():
    """The point of the exercise: moment state bytes ~= 1 byte/param
    (plus 1/BLOCK of f32 scales), vs 8 for f32 adam."""
    params = {"w": jnp.zeros((1024, 256), jnp.float32)}
    state = optimizers.adam8(1e-3).init(params)
    n = 1024 * 256

    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(tree))

    total = nbytes(state.m) + nbytes(state.v)
    assert total <= n * 2 * (1 + 4 / BLOCK) + 64
