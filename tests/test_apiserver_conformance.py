"""Real-apiserver conformance for the hand-built client stack.

The reference's client stack was *generated* against pinned client-go
(/root/reference/hack/update-codegen.sh:31-34, glide.yaml:1-20), so wire
compatibility was structural; this repo's rest.py/informer.py are
hand-written. This tier drives the REAL HTTP client and informer against
the in-process apiserver (testing/apiserver.py — the strongest apiserver
this hermetic environment supports; no kube-apiserver/etcd binaries exist
in the image) and pins down the watch-protocol semantics a real apiserver
imposes: list-envelope resourceVersions, RV-anchored gap-free watches,
410 Gone on compacted RVs (HTTP-level and in-stream), BOOKMARK tolerance,
status-subresource isolation, and conflict-retry on stale RVs.

Each behavior pinned here mirrors a documented upstream Kubernetes
contract (the conformance anchor, since no kube-apiserver/etcd binaries
exist in this image):

- resourceVersion list envelopes and RV-anchored watches: Kubernetes API
  Concepts, "Efficient detection of changes" — a watch started from a
  list's RV must deliver exactly the events after that snapshot.
- 410 Gone on a compacted RV (both as the watch-open HTTP status and as
  an in-stream ERROR event with code 410): same chapter, "410 Gone"
  responses; client-go's Reflector handles both by falling back to
  re-list (k8s.io/client-go tools/cache/reflector.go behavior).
- BOOKMARK events: API Concepts, "Watch bookmarks" — progress markers
  carrying only resourceVersion; they must not dispatch handlers or
  mutate the cache.
- status subresource isolation: API Conventions, "Spec and Status" — a
  PUT to /status updates only .status and bumps the RV.
- 409 Conflict on stale-RV writes + read-retry: API Conventions,
  optimistic concurrency via metadata.resourceVersion.
"""

from __future__ import annotations

import threading
import time

import pytest

from tpu_operator.client import errors
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import Informer
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.testing.apiserver import ApiServerHarness


@pytest.fixture()
def srv_cs():
    with ApiServerHarness() as srv:
        yield srv, Clientset(RestConfig(host=srv.url, timeout=5.0))


def _pod(name, labels=None):
    return {"kind": "Pod", "metadata": {"name": name,
                                        "labels": labels or {}}}


def test_list_envelope_carries_resource_version(srv_cs):
    srv, cs = srv_cs
    cs.pods.create("default", _pod("a"))
    items, rv = cs.pods.list_with_version("default")
    assert [i["metadata"]["name"] for i in items] == ["a"]
    assert rv and int(rv) >= 1
    cs.pods.create("default", _pod("b"))
    _, rv2 = cs.pods.list_with_version("default")
    assert int(rv2) > int(rv)


def test_anchored_watch_replays_only_post_list_events(srv_cs):
    """Create → list (grab RV) → create more → watch@RV: only the events
    after the list replay; the snapshot is never re-delivered."""
    srv, cs = srv_cs
    cs.pods.create("default", _pod("before"))
    _items, rv = cs.pods.list_with_version("default")
    cs.pods.create("default", _pod("after-1"))
    cs.pods.create("default", _pod("after-2"))
    watch = cs.pods.watch("default", resource_version=rv)
    got = []
    timer = threading.Timer(5.0, watch.stop)
    timer.start()
    try:
        for ev, obj in watch:
            got.append((ev, obj["metadata"]["name"]))
            if len(got) == 2:
                break
    finally:
        timer.cancel()
        watch.stop()
    assert got == [("ADDED", "after-1"), ("ADDED", "after-2")]


def test_expired_rv_gets_http_410(srv_cs):
    """Age an RV out of the server's bounded event window: the anchored
    watch open must fail with 410 Gone (errors.is_expired), the signal the
    informer's re-list path exists for."""
    srv, cs = srv_cs
    cs.pods.create("default", _pod("anchor"))
    _items, rv = cs.pods.list_with_version("default")
    # Roll the event log over its window so `rv` predates the horizon.
    for i in range(FakeClientset.EVENT_LOG_SIZE + 8):
        srv.clientset.configmaps.create("default", {
            "kind": "ConfigMap", "metadata": {"name": f"churn-{i}"}})
    with pytest.raises(errors.ApiError) as exc:
        cs.pods.watch("default", resource_version=rv)
    assert errors.is_expired(exc.value)


def test_informer_survives_410_and_stays_current(srv_cs):
    """End to end: informer syncs against the real HTTP stack, the server
    compacts past its anchor (410 on the next cycle's anchored watch), and
    the informer converges anyway — cache still tracks reality."""
    srv, cs = srv_cs
    cs.pods.create("default", _pod("p0"))
    inf = Informer(cs.pods, "default", resync_period=0.3)
    seen = []
    inf.add_event_handler(on_add=lambda o: seen.append(
        o["metadata"]["name"]))
    stop = threading.Event()
    inf.start(stop)
    try:
        deadline = time.monotonic() + 5
        while not inf.has_synced() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert inf.has_synced()
        assert inf.store.get("default", "p0") is not None
        # Compact the window out from under the informer's position, then
        # keep mutating; the informer's re-list must converge on reality.
        for i in range(FakeClientset.EVENT_LOG_SIZE + 8):
            srv.clientset.configmaps.create("default", {
                "kind": "ConfigMap", "metadata": {"name": f"churn-{i}"}})
        cs.pods.create("default", _pod("p1"))
        deadline = time.monotonic() + 5
        while (inf.store.get("default", "p1") is None
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert inf.store.get("default", "p1") is not None
        assert "p1" in seen
    finally:
        stop.set()
        time.sleep(0.05)


class _ScriptedClient:
    """Stub resource client: one scripted watch stream, then live queues."""

    kind = "Pod"

    def __init__(self, events_per_cycle):
        self._cycles = list(events_per_cycle)
        self.watch_calls = 0

    def list(self, namespace=""):
        return []

    def watch(self, namespace="", resource_version=""):
        self.watch_calls += 1
        events = self._cycles.pop(0) if self._cycles else []

        class _W:
            def __init__(self, evs):
                self._evs = evs

            def stop(self):
                pass

            def __iter__(self):
                yield from self._evs

        return _W(events)


def test_informer_handles_in_stream_410_and_bookmarks():
    """ERROR events with code 410 end the cycle (→ re-list); BOOKMARK
    events are progress markers and must not dispatch or disturb the
    cache."""
    pod = {"kind": "Pod", "metadata": {"namespace": "default", "name": "x"}}
    client = _ScriptedClient([
        [("BOOKMARK", {"metadata": {"resourceVersion": "7"}}),
         ("ADDED", pod),
         ("ERROR", {"kind": "Status", "code": 410,
                    "reason": "Expired"})],
        [],  # second cycle: clean stream end
    ])
    inf = Informer(client, "default", resync_period=0)
    adds, deletes = [], []
    inf.add_event_handler(on_add=lambda o: adds.append(o),
                          on_delete=lambda o: deletes.append(o))
    stop = threading.Event()
    inf.start(stop)
    deadline = time.monotonic() + 5
    while client.watch_calls < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    stop.set()
    assert client.watch_calls >= 2  # the 410 triggered a re-list cycle
    assert [o["metadata"]["name"] for o in adds][:1] == ["x"]
    assert not deletes  # bookmark/410 never fabricated object events


def test_status_subresource_and_conflict_retry(srv_cs):
    """Status writes touch only .status; spec writes with a stale RV 409
    until retried from a fresh read — the optimistic-concurrency loop every
    controller write path relies on."""
    srv, cs = srv_cs
    cs.tpujobs.create("default", {
        "apiVersion": "hyperml.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "j"},
        "spec": {"replicaSpecs": [
            {"tpuReplicaType": "WORKER", "replicas": 1,
             "template": {"spec": {"containers": [
                 {"name": "tpu", "image": "img"}]}}}]}})
    live = cs.tpujobs.get("default", "j")

    # status subresource: only .status lands, spec edits are ignored
    st = dict(live)
    st["spec"] = dict(live["spec"], suspend=True)
    st["status"] = {"phase": "Running"}
    cs.tpujobs.update_status("default", st)
    after = cs.tpujobs.get("default", "j")
    assert after["status"]["phase"] == "Running"
    assert "suspend" not in after["spec"]

    # conflict retry: write against the pre-status RV → 409; re-read → 200
    stale = dict(live)
    stale["metadata"] = dict(live["metadata"])
    stale.setdefault("spec", {})
    with pytest.raises(errors.ApiError) as exc:
        cs.tpujobs.update("default", stale)
    assert errors.is_conflict(exc.value)
    fresh = cs.tpujobs.get("default", "j")
    cs.tpujobs.update("default", fresh)  # succeeds with the current RV
