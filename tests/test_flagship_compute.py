"""Flagship compute-path tests (payload/compute.py + the optimized
classifier step) on the CPU mesh.

Three contracts from the compute-path overhaul:

1. Numerics parity — the optimized path (remat + fused loss) trains the
   SAME trajectory as the seed path at a fixed seed, within tolerance
   (fused loss changes summation order, remat is semantics-preserving).
2. Option round-trips — the shared flag surface parses, builds, and
   rejects exactly what it claims, for the classifier AND the LM family.
3. Resume across a path change — a job checkpointed on the seed path
   restarts cleanly onto the optimized path through the PR-4 verified
   walk (train_loop + Checkpointer), because remat/fused-loss change
   the compiled program but not the state tree.
"""

import numpy as np
import pytest

import jax

from tpu_operator.payload import checkpoint, compute, data as data_mod, train


# The optimized-path flags that preserve the TrainState tree (the
# resume-compatible subset: scan_blocks and optimizer flips are excluded
# by design — both change the tree, as their --help text says).
OPTIMIZED = ["--remat-policy", "dots", "--fused-loss"]


def tiny_build(extra=(), seed=0):
    from tpu_operator.payload.cifar import build, parse_args

    args = parse_args([
        "--steps", "6", "--batch", "16", "--blocks", "1",
        "--widths", "8", "8", "8", "--log-every", "0",
        "--seed", str(seed), *extra,
    ])
    return args, build(args)


def run_losses(build_out, n_steps):
    mesh, _model, state, step, batches = build_out
    losses = []
    for _ in range(n_steps):
        arrays = data_mod.put_global_batch(mesh, *next(batches))
        state, metrics = step(state, *arrays)
        losses.append(float(jax.device_get(metrics["loss"])))
    return state, losses


# ---------------------------------------------------------------- parity

def test_optimized_path_matches_seed_trajectory():
    """Seed path vs remat+fused at the same seed: the synthetic stream is
    seed-deterministic, so both builds train on identical batches; the
    loss trajectories must agree to tolerance every step (bf16 model, f32
    loss — the fused form only reorders the row reduction)."""
    _a, seed_build = tiny_build()
    _b, opt_build = tiny_build(OPTIMIZED)
    _s1, ref = run_losses(seed_build, 5)
    _s2, opt = run_losses(opt_build, 5)
    np.testing.assert_allclose(opt, ref, rtol=1e-2, atol=1e-2)
    # and the trajectory actually moved — parity of constants is vacuous
    assert ref[0] != ref[-1]


def test_fused_cross_entropy_matches_reference_loss():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(32, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, size=(32,)), jnp.int32)
    fused = float(train.fused_cross_entropy(logits, labels))
    ref = float(train.cross_entropy(logits, labels))
    np.testing.assert_allclose(fused, ref, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------- round-trips

def test_classifier_defaults_are_seed_path():
    args, _ = tiny_build()
    assert args.remat_policy == "full"
    assert args.optimizer == "sgd"
    assert args.fused_loss is False
    assert args.scan_blocks is False
    assert args.aot is False
    assert compute.classifier_step_options(args) == {
        "remat_policy": "full", "fused_loss": False}


def test_classifier_rejects_unknown_remat_policy():
    from tpu_operator.payload import cifar

    with pytest.raises(SystemExit):
        cifar.parse_args(["--remat-policy", "bogus"])


def test_adam8_round_trips_into_opt_state():
    from tpu_operator.payload import optimizers

    _args, (_mesh, _m, state, step, batches) = tiny_build(
        ["--optimizer", "adam8"])
    found = [s for s in jax.tree_util.tree_leaves(
        state.opt_state, is_leaf=lambda x: isinstance(
            x, optimizers.Adam8State))
        if isinstance(s, optimizers.Adam8State)]
    assert found, "adam8 selection must land an Adam8State in opt_state"
    # and the step still trains
    mesh = _mesh
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    state, metrics = step(state, *arrays)
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_scan_blocks_stacks_stage_params():
    _args, (_mesh, _m, state, _step, _b) = tiny_build(
        ["--blocks", "2", "--scan-blocks"])
    params = state.params
    # stride entry block keeps its own leaves; the stride-1 tail is one
    # scanned body with a leading [blocks-1] axis
    assert "stage0_block0" in params
    assert "stage0_scan" in params
    scan_kernel = jax.tree_util.tree_leaves(params["stage0_scan"])[0]
    assert scan_kernel.shape[0] == 1  # blocks_per_stage - 1


def test_lm_parsers_share_the_compute_surface():
    from tpu_operator.payload import moe, pipeline, transformer

    for mod, extra in ((transformer, []), (moe, []), (pipeline, [])):
        args = mod.parse_args(["--remat", "--remat-policy", "dots",
                               "--optimizer", "adam8", *extra])
        assert args.remat is True
        assert args.remat_policy == "dots"
        assert args.optimizer == "adam8"


def test_lm_block_gates_remat_on_flag():
    import argparse

    from tpu_operator.payload import models

    on = argparse.Namespace(remat=True, remat_policy="dots")
    off = argparse.Namespace(remat=False, remat_policy="dots")
    assert compute.lm_block(off) is models.DecoderBlock
    assert compute.lm_block(on) is not models.DecoderBlock


def test_aot_compile_cached_round_trip():
    _args, (mesh, _m, state, step, batches) = tiny_build()
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    compiled, compile_seconds, cache_hit = compute.aot_compile_cached(
        step, state, arrays, env={})
    assert compiled is not None
    assert compile_seconds > 0.0
    assert isinstance(cache_hit, bool)
    # the AOT executable is the live step: runs for the compiled shapes
    state, metrics = compiled(state, *arrays)
    assert int(jax.device_get(state.step)) == 1
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


def test_aot_compile_cached_none_for_unjitted():
    compiled, _secs, hit = compute.aot_compile_cached(
        lambda s, *a: s, object(), (), env={})
    assert compiled is None
    assert hit is False


# --------------------------------------- resume across the path change

def test_resume_across_path_change_restores_exactly(tmp_path):
    """Seed-path checkpoint → restore into a remat+fused build: same
    optimizer (sgd+momentum) → same state tree → the PR-4 restore walk
    must return the saved leaves bit-for-bit."""
    _a, (mesh, _m, state, step, batches) = tiny_build()
    for _ in range(3):
        arrays = data_mod.put_global_batch(mesh, *next(batches))
        state, _metrics = step(state, *arrays)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(3, state)
    ck.close()

    _b, (_mesh2, _m2, fresh, _step2, _b2) = tiny_build(OPTIMIZED)
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    restored, start = ck2.restore(fresh)
    ck2.close()
    assert start == 3
    for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_train_loop_resumes_onto_optimized_path(tmp_path):
    """The e2e restart contract across the path flip: attempt 0 trains 4
    seed-path steps; the restarted attempt builds the OPTIMIZED step,
    resumes from the drained checkpoint via train_loop, and lands on the
    target total — the exact walk a TPUJob takes when its operator spec
    gains the new flags between attempts."""
    ckdir = str(tmp_path / "ck")
    _a, (mesh, _m, state, step, batches) = tiny_build()
    ck = checkpoint.Checkpointer(ckdir, save_every=2)
    state, _ = train.train_loop(mesh, step, state, batches, steps=4,
                                checkpointer=ck)
    ck.close()
    assert int(jax.device_get(state.step)) == 4

    _b, (mesh2, _m2, fresh, step2, batches2) = tiny_build(OPTIMIZED)
    ck2 = checkpoint.Checkpointer(ckdir, save_every=2)
    assert ck2.latest_step() == 4
    final, metrics = train.train_loop(mesh2, step2, fresh, batches2,
                                      steps=6, checkpointer=ck2)
    ck2.close()
    assert int(jax.device_get(final.step)) == 6
    assert np.isfinite(float(metrics["loss"]))
