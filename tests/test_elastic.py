"""Elastic gangs: inventory-sized attempts, reshard-restore, straggler
remediation.

The graceful-degradation layer (ROADMAP item 3): ``spec.elastic
{minSlices, maxSlices, stragglerPolicy}`` lets each attempt's world size
be granted from the LIVE slice inventory — preferring maxSlices,
shrinking instead of queueing, re-expanding when capacity returns — with
the chosen size recorded in ``status.elastic`` + the failure ledger and
the env contract regenerated for the actual size. Persistently flagged
stragglers are replaced (same rendezvous, excluded node) or shed (group
restart one slice smaller, preemption budget).

The e2e at the bottom is the acceptance flow over the in-process
apiserver: a Running 8-slice elastic job is preempted while the
inventory shrinks to 4 → the next attempt gangs at 4 with the resize in
status and metrics; a sibling e2e proves ``stragglerPolicy: replace``
swaps a flagged member without consuming crash-loop budget. The
payload half (a checkpoint saved at one world size restoring onto
another, through the remote store) is in
tests/test_checkpoint_durability.py's reshard matrix plus the
store-composed test here.
"""

import contextlib
import io
import threading

import pytest

from tpu_operator.apis.tpujob import validation
from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import Metrics, StatusServer
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.scheduler.fleet import FleetScheduler
from tpu_operator.scheduler.inventory import SliceInventory, slice_key
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.trainer import elastic as elastic_mod
from tpu_operator.trainer.training import TrainingJob

V4 = "cloud-tpus.google.com/v4"
KEY = slice_key(V4, "2x2x2")

wait_for = make_wait_for(timeout=20.0, interval=0.05)


def make_template(tpu_chips=4):
    return {"spec": {"containers": [{"name": "tpu", "image": "x",
                                     "resources": {"requests": {
                                         V4: str(tpu_chips)}}}]}}


def elastic_job(name="el", replicas=8, num_slices=8, min_slices=2,
                max_slices=0, policy=t.StragglerPolicy.NONE, patience=300,
                uid=None, **spec_kw):
    """A WORKER gang of ``replicas`` processes over ``num_slices`` v4
    slices whose attempts may gang anywhere in [min, max]."""
    spec_kw.setdefault("restart_backoff",
                       t.RestartBackoffSpec(base_seconds=0))
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=replicas, template=make_template(),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="el01",
        tpu_topology="2x2x2",
        num_slices=num_slices,
        elastic=t.ElasticSpec(min_slices=min_slices, max_slices=max_slices,
                              straggler_policy=policy,
                              straggler_patience_seconds=patience),
        **spec_kw,
    )
    return t.TPUJob(metadata={"name": name, "namespace": "default",
                              "uid": uid or f"uid-{name}"}, spec=spec)


def mark_pods(cs, phase="Running", state=None, only_live=False):
    state = state if state is not None else {"running": {}}
    for pod in cs.pods.list("default"):
        if only_live and (pod.get("status") or {}).get("phase") in (
                "Succeeded", "Failed"):
            continue
        pod["status"] = {"phase": phase, "containerStatuses": [
            {"name": "tpu", "state": state}]}
        cs.pods.update("default", pod)


def live_pods(cs):
    return [p for p in cs.pods.list("default")
            if (p.get("status") or {}).get("phase") not in ("Succeeded",
                                                            "Failed")]


def pod_env(pod):
    return {e["name"]: e["value"]
            for e in pod["spec"]["containers"][0]["env"]}


# --- spec plumbing (types/schema/defaults/validation round-trip) -------------


def test_elastic_spec_roundtrip():
    job = elastic_job(min_slices=2, max_slices=6, policy="replace",
                      patience=120)
    wire = job.to_dict()
    assert wire["spec"]["elastic"] == {
        "minSlices": 2, "maxSlices": 6, "stragglerPolicy": "replace",
        "stragglerPatienceSeconds": 120}
    back = t.TPUJob.from_dict(wire)
    assert back.spec.elastic.min_slices == 2
    assert back.spec.elastic.max_slices == 6
    assert back.spec.elastic.straggler_policy == "replace"
    # Absent block stays absent (specs round-trip unchanged).
    bare = t.TPUJobSpec.from_dict({"replicaSpecs": []})
    assert bare.elastic is None
    assert "elastic" not in bare.to_dict()


def test_elastic_defaults_and_validation():
    job = elastic_job(min_slices=3, max_slices=0, num_slices=8)
    set_defaults(job.spec)
    assert job.spec.elastic.max_slices == 8  # defaulted from numSlices
    validation.validate_tpujob_spec(job.spec)

    bad = elastic_job(min_slices=5, max_slices=3)
    set_defaults(bad.spec)
    with pytest.raises(validation.ValidationError, match="maxSlices"):
        validation.validate_tpujob_spec(bad.spec)

    # maxSlices past numSlices demands processes the template never
    # provisioned.
    over = elastic_job(min_slices=2, max_slices=16, num_slices=8)
    set_defaults(over.spec)
    with pytest.raises(validation.ValidationError, match="numSlices"):
        validation.validate_tpujob_spec(over.spec)

    perpod = elastic_job(restart_policy=t.RestartPolicy.PER_POD)
    set_defaults(perpod.spec)
    with pytest.raises(validation.ValidationError, match="WholeGroup"):
        validation.validate_tpujob_spec(perpod.spec)

    bad_policy = elastic_job(policy="evict")
    set_defaults(bad_policy.spec)
    with pytest.raises(validation.ValidationError, match="stragglerPolicy"):
        validation.validate_tpujob_spec(bad_policy.spec)

    bad_patience = elastic_job(policy="replace", patience=0)
    set_defaults(bad_patience.spec)
    with pytest.raises(validation.ValidationError, match="Patience"):
        validation.validate_tpujob_spec(bad_patience.spec)

    # Worker replicas must scale evenly across the range.
    uneven = elastic_job(replicas=6, num_slices=4, min_slices=2,
                         max_slices=4)
    set_defaults(uneven.spec)
    with pytest.raises(validation.ValidationError, match="divisible"):
        validation.validate_tpujob_spec(uneven.spec)


def test_elastic_strict_schema():
    job = elastic_job(min_slices=2, policy="shed")
    set_defaults(job.spec)
    job.status.elastic = {
        "slices": 4, "workers": 4, "minSlices": 2, "maxSlices": 8,
        "attempt": 1, "resizes": 1, "lastResizeDirection": "down",
        "capNextAttempt": 3, "time": "2026-08-04T00:00:00.000000Z",
        "remediations": [{"attempt": 1, "processId": 2,
                          "policy": "replace", "node": "n-2",
                          "time": "2026-08-04T00:00:00.000000Z"}]}
    job.status.failures = [t.FailureRecord(
        attempt=0, kind=t.FailureKind.PREEMPTION, reason="x",
        time="2026-08-04T00:00:00.000000Z", resume_step=6, world_slices=8)]
    ok, msg = schema_mod.validate_tpujob_strict(job.to_dict())
    assert ok, msg
    # Unknown elastic field rejected (the typo-catching contract).
    wire = job.to_dict()
    wire["spec"]["elastic"]["minSlice"] = 1
    ok, msg = schema_mod.validate_tpujob_strict(wire)
    assert not ok and "minSlice" in msg


def test_elastic_helpers():
    job = elastic_job(replicas=8, num_slices=8, min_slices=2)
    set_defaults(job.spec)
    assert elastic_mod.elastic_range(job.spec) == (2, 8)
    eff = elastic_mod.scaled_spec(job.spec, 4)
    assert eff.num_slices == 4
    assert eff.replica_specs[0].replicas == 4
    assert elastic_mod.world_workers(job.spec, 4) == 4
    # Two workers per slice scale together.
    wide = elastic_job(replicas=16, num_slices=8, min_slices=2)
    set_defaults(wide.spec)
    assert elastic_mod.scaled_spec(wide.spec, 3).replica_specs[0].replicas \
        == 6
    # granted == numSlices or nothing recorded → the spec applies as-is
    assert elastic_mod.granted_slices(job.spec, None) is None
    assert elastic_mod.granted_slices(job.spec, {"slices": 8}) is None
    assert elastic_mod.granted_slices(job.spec, {"slices": 4}) == 4
    # shed cap clamps the next sizing only within [lo, hi]
    assert elastic_mod.capped_max({"capNextAttempt": 3}, 2, 8) == 3
    assert elastic_mod.capped_max({"capNextAttempt": 1}, 2, 8) == 2
    assert elastic_mod.capped_max({}, 2, 8) == 8


# --- scheduler: range demand, granted accounting, resize ---------------------


def test_admission_grants_largest_fitting_size():
    s = FleetScheduler(SliceInventory({KEY: 6}))
    assert s.ensure_admitted("default/el", uid="u", demand=(KEY, 8),
                             min_slices=2)
    # Preferred 8 does not fit; the gang shrinks to the 6 that do.
    assert s.granted_slices("default/el") == 6
    # Satellite (fleet.py elastic-parallelism stub): the inventory
    # accounts the GRANTED size, not the spec's — no phantom capacity.
    assert s.summary()["inventory"][KEY]["used"] == 6


def test_admission_queues_below_min_and_floor_drives_impossible():
    s = FleetScheduler(SliceInventory({KEY: 1}))
    assert not s.ensure_admitted("default/el", uid="u", demand=(KEY, 8),
                                 min_slices=2)
    # The floor (2) exceeds total capacity (1): sidelined unschedulable
    # — the preferred size (8) must not be what decides.
    assert "2 slice(s)" in s.unschedulable_reason("default/el")
    # A rigid 1-slice job is not blocked by the sidelined elastic head.
    assert s.ensure_admitted("default/one", uid="u2", demand=(KEY, 1))


def test_elastic_head_preempts_only_its_floor():
    s = FleetScheduler(SliceInventory({KEY: 4}))
    assert s.ensure_admitted("default/lo-a", uid="a", demand=(KEY, 2))
    assert s.ensure_admitted("default/lo-b", uid="b", demand=(KEY, 2))
    # Elastic high-priority head [2, 8]: needs only its floor — ONE
    # victim frees 2 slices; evicting both for the preferred 8 would
    # trade a running gang for capacity the head can live without.
    assert not s.ensure_admitted("default/hi", uid="h", demand=(KEY, 8),
                                 min_slices=2, priority=10)
    marked = [k for k in ("default/lo-a", "default/lo-b")
              if s.pop_eviction(k) is not None]
    assert len(marked) == 1
    # The pop released the victim's 2 slices; the head admits shrunk.
    assert s.is_admitted("default/hi")
    assert s.granted_slices("default/hi") == 2


def test_resize_shrinks_grows_and_requeues():
    s = FleetScheduler(SliceInventory({KEY: 8}))
    assert s.ensure_admitted("default/el", uid="u", demand=(KEY, 8),
                             min_slices=2)
    assert s.granted_slices("default/el") == 8
    # The pool shrank to 4 (honest over-commit until the resize).
    s.update_inventory({KEY: 4})
    assert s.resize("default/el", uid="u", min_slices=2, max_slices=8) == 4
    assert s.summary()["inventory"][KEY]["used"] == 4
    # Capacity returned: the next attempt re-expands to the preferred 8.
    s.update_inventory({KEY: 8})
    assert s.resize("default/el", uid="u", min_slices=2, max_slices=8) == 8
    # Below the floor: the reservation releases and the job re-queues.
    s.update_inventory({KEY: 1})
    assert s.resize("default/el", uid="u", min_slices=2, max_slices=8) \
        is None
    assert not s.is_admitted("default/el")
    assert s.summary()["inventory"][KEY]["used"] == 0


def test_resize_shrink_wakes_queued_jobs():
    wakes = []
    s = FleetScheduler(SliceInventory({KEY: 8}),
                       enqueue=wakes.append)
    assert s.ensure_admitted("default/el", uid="u", demand=(KEY, 8),
                             min_slices=2)
    assert not s.ensure_admitted("default/waiter", uid="w",
                                 demand=(KEY, 3))
    # el re-sizes down to its floor: the freed 6 slices admit the waiter
    # without any external release.
    assert s.resize("default/el", uid="u", min_slices=2, max_slices=2) == 2
    assert s.is_admitted("default/waiter")
    assert "default/waiter" in wakes


# --- TrainingJob: sizing, env regeneration, ledger ---------------------------


def fleet_tj(job, scheduler, metrics=None, cs=None):
    from tpu_operator.controller.events import EventRecorder

    cs = cs or FakeClientset()
    try:
        cs.tpujobs.get(job.namespace, job.name)
    except Exception:
        cs.tpujobs.create(job.namespace, job.to_dict())
    tj = TrainingJob(cs, EventRecorder(cs), job, scheduler=scheduler,
                     metrics=metrics)
    return cs, tj


def test_fresh_elastic_job_gangs_at_granted_size():
    metrics = Metrics()
    s = FleetScheduler(SliceInventory({KEY: 4}), metrics=metrics)
    cs, tj = fleet_tj(elastic_job(replicas=8, num_slices=8, min_slices=2),
                      s, metrics=metrics)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    pods = cs.pods.list("default")
    assert len(pods) == 4  # 8 spec'd, 4 granted: one worker per slice
    el = tj.job.status.elastic
    assert el["slices"] == 4 and el["workers"] == 4 and el["attempt"] == 0
    # A first sizing is not a resize.
    assert el["resizes"] == 0
    envs = pod_env(sorted(pods,
                          key=lambda p: p["metadata"]["name"])[0])
    assert envs["JAX_NUM_PROCESSES"] == "4"
    assert envs["MEGASCALE_NUM_SLICES"] == "4"
    assert metrics.counter_value("job_world_size",
                                 labels={"namespace": "default",
                                         "name": "el"}) == 4


def test_restart_resizes_down_then_reexpands_with_ledger_world():
    metrics = Metrics()
    s = FleetScheduler(SliceInventory({KEY: 8}), metrics=metrics)
    cs, tj = fleet_tj(elastic_job(), s, metrics=metrics)
    tj.reconcile()
    assert len(cs.pods.list("default")) == 8
    mark_pods(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING

    # Preempted while the inventory shrinks to 4.
    s.update_inventory({KEY: 4})
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}})
    tj.reconcile()   # teardown, attempt bump
    tj.reconcile()   # size + re-gang
    el = tj.job.status.elastic
    assert el["slices"] == 4 and el["attempt"] == 1
    assert el["resizes"] == 1 and el["lastResizeDirection"] == "down"
    assert len(live_pods(cs)) == 4
    envs = pod_env(live_pods(cs)[0])
    assert envs["JAX_NUM_PROCESSES"] == "4"
    assert len(envs["TPU_WORKER_HOSTNAMES"].split(",")) == 1
    assert metrics.counter_value("job_elastic_resizes_total",
                                 labels={"direction": "down"}) == 1
    # Satellite: the ledger records the failed attempt's world size
    # NEXT TO its resume step — auditable from one record.
    rec = tj.job.status.failures[-1]
    assert rec.kind == t.FailureKind.PREEMPTION
    assert rec.world_slices == 8
    events = [e["reason"] for e in cs.events.list("default")]
    assert "ElasticResized" in events

    # Capacity returns: the next restart re-expands to the full spec.
    s.update_inventory({KEY: 8})
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}},
              only_live=True)
    tj.reconcile()
    tj.reconcile()
    el = tj.job.status.elastic
    assert el["slices"] == 8 and el["lastResizeDirection"] == "up"
    assert el["resizes"] == 2
    assert metrics.counter_value("job_elastic_resizes_total",
                                 labels={"direction": "up"}) == 1
    assert tj.job.status.failures[-1].world_slices == 4


def test_resize_below_min_parks_queued_until_capacity_returns():
    s = FleetScheduler(SliceInventory({KEY: 8}))
    cs, tj = fleet_tj(elastic_job(min_slices=2), s)
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    # The pool collapses below the floor while the gang is preempted.
    s.update_inventory({KEY: 1})
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}})
    tj.reconcile()
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert live_pods(cs) == []
    # Capacity returns: the next reconcile admits and gangs shrunk.
    s.update_inventory({KEY: 2})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    assert tj.job.status.elastic["slices"] == 2
    assert len(live_pods(cs)) == 2


def test_rebuild_reaccounts_granted_not_spec_size():
    """Operator restart: the eager rebuild re-reserves what the
    persisted status.elastic says the gang holds (4), never the spec's
    8 — phantom capacity would starve the rest of the pool."""
    s1 = FleetScheduler(SliceInventory({KEY: 4}))
    cs, tj = fleet_tj(elastic_job(min_slices=2), s1)
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.elastic["slices"] == 4

    factory = SharedInformerFactory(cs, resync_period=0)
    config = t.ControllerConfig(slice_inventory={KEY: 8})
    controller = Controller(cs, factory, config)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(1, stop),
                              daemon=True)
    runner.start()
    try:
        assert wait_for(
            lambda: controller.scheduler.is_admitted("default/el"))
        assert controller.scheduler.granted_slices("default/el") == 4
        assert controller.scheduler.summary()["inventory"][KEY]["used"] == 4
    finally:
        stop.set()
        runner.join(timeout=5.0)


def test_shrunk_gang_teardown_deletes_all_services():
    """Explicit delete of a gang running SHRUNK must remove the services
    its full-width attempt created: index enumeration over the effective
    (4-wide) world would leak services 4..7 forever."""
    s = FleetScheduler(SliceInventory({KEY: 8}))
    cs, tj = fleet_tj(elastic_job(min_slices=2), s)
    tj.reconcile()
    assert len(cs.services.list("default")) == 8 + 1  # per-index + headless
    mark_pods(cs)
    tj.reconcile()
    # Preempted while the pool shrinks: re-gang at 4.
    s.update_inventory({KEY: 4})
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}})
    tj.reconcile()
    tj.reconcile()
    assert tj.job.status.elastic["slices"] == 4
    tj.delete()
    assert cs.services.list("default") == []
    assert live_pods(cs) == []


# --- straggler remediation ---------------------------------------------------


def remediation_harness(policy, patience=5, replicas=4, num_slices=4,
                        min_slices=1, capacity=8):
    now = [1000.0]
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=0.0,
                            wall_clock=lambda: now[0])
    controller.scheduler.update_inventory({KEY: capacity})
    job = elastic_job("rem", replicas=replicas, num_slices=num_slices,
                      min_slices=min_slices, policy=policy,
                      patience=patience)
    cs.tpujobs.create("default", job.to_dict())
    tj = TrainingJob(cs, controller.recorder, job,
                     metrics=controller.metrics,
                     scheduler=controller.scheduler)
    controller.jobs["default/rem"] = tj
    tj.reconcile()
    for pod in cs.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        pod["spec"]["nodeName"] = \
            f"node-{pod['metadata']['labels']['task_index']}"
        cs.pods.update("default", pod)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING

    def beat(pid, local_p95):
        return controller.record_heartbeat("default", "rem", {
            "time": "2026-08-04T00:00:00.000000Z", "step": 50,
            "attempt": tj.job.status.attempt, "processId": pid,
            "stepTiming": {"steps": 10, "stepLocalP95Seconds": local_p95,
                           "stepP95Seconds": 1.0}})

    return cs, controller, tj, now, beat


def test_replace_swaps_flagged_member_without_budget():
    cs, controller, tj, now, beat = remediation_harness("replace")
    n = tj.job_spec.replica_specs[0].replicas
    for pid in range(n):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert [s["processId"] for s in tj.job.status.stragglers] == [2]
    # Flagged but the patience window has not elapsed: nothing pending.
    assert tj._pending_remediation is None
    now[0] += 6.0
    for pid in range(n):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert tj._pending_remediation is not None

    before = {p["metadata"]["name"] for p in cs.pods.list("default")}
    tj.reconcile()   # executes the replace: straggler pod deleted
    assert len(cs.pods.list("default")) == n - 1
    tj.reconcile()   # gang sync re-creates the member
    pods = cs.pods.list("default")
    assert len(pods) == n
    (new_pod,) = [p for p in pods if p["metadata"]["name"] not in before]
    envs = pod_env(new_pod)
    # Same rendezvous slot: same process id, same coordinator address.
    assert envs["JAX_PROCESS_ID"] == "2"
    terms = (new_pod["spec"]["affinity"]["nodeAffinity"]
             ["requiredDuringSchedulingIgnoredDuringExecution"]
             ["nodeSelectorTerms"])
    assert terms[0]["matchExpressions"][0] == {
        "key": "kubernetes.io/hostname", "operator": "NotIn",
        "values": ["node-2"]}
    # No budget consumed, no attempt bump, no ledger entry.
    assert tj.job.status.restart_counts == {}
    assert tj.job.status.attempt == 0
    assert tj.job.status.failures == []
    trail = tj.job.status.elastic["remediations"]
    assert trail[-1]["policy"] == "replace" and trail[-1]["node"] == "node-2"
    assert controller.metrics.counter_value(
        "job_straggler_remediations_total",
        labels={"policy": "replace"}) == 1
    assert "StragglerReplaced" in [e["reason"]
                                   for e in cs.events.list("default")]


def test_replace_fires_once_per_attempt_and_flag():
    cs, controller, tj, now, beat = remediation_harness("replace")
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    now[0] += 6.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    tj.reconcile()
    tj.reconcile()
    # More flagged beats for the SAME process: already remediated this
    # attempt — no second replace, the replacement earns its own window.
    now[0] += 30.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert tj._pending_remediation is None
    assert controller.metrics.counter_value(
        "job_straggler_remediations_total",
        labels={"policy": "replace"}) == 1


def test_shed_restarts_one_slice_smaller_on_preemption_budget():
    cs, controller, tj, now, beat = remediation_harness("shed")
    for pid in range(4):
        beat(pid, 0.99 if pid == 1 else 0.1)
    now[0] += 6.0
    for pid in range(4):
        beat(pid, 0.99 if pid == 1 else 0.1)
    tj.reconcile()   # shed: teardown billed preemption + cap recorded
    assert tj.job.status.attempt == 1
    assert tj.job.status.restart_counts == \
        {t.FailureKind.PREEMPTION: 1}
    rec = tj.job.status.failures[-1]
    assert rec.reason.startswith("StragglerShed")
    assert rec.world_slices == 4
    assert tj.job.status.elastic["capNextAttempt"] == 3
    tj.reconcile()   # re-gang one slice smaller
    el = tj.job.status.elastic
    assert el["slices"] == 3 and el["lastResizeDirection"] == "down"
    assert "capNextAttempt" not in el   # one-attempt cap, consumed
    assert len(live_pods(cs)) == 3
    assert controller.scheduler.granted_slices("default/rem") == 3
    assert controller.metrics.counter_value(
        "job_straggler_remediations_total", labels={"policy": "shed"}) == 1


def test_shed_at_floor_replaces_instead():
    cs, controller, tj, now, beat = remediation_harness(
        "shed", replicas=2, num_slices=2, min_slices=2)
    for pid in range(2):
        beat(pid, 0.9 if pid == 1 else 0.1)
    # A 2-member gang's even median needs a sensitive threshold; drive
    # the flag via a direct request instead of cadence statistics.
    tj.request_remediation(1, t.StragglerPolicy.SHED,
                           tj.job.status.attempt)
    tj.reconcile()
    # No slice to shed (already at minSlices): the member is replaced.
    assert tj.job.status.attempt == 0
    assert tj.job.status.restart_counts == {}
    assert len(cs.pods.list("default")) == 1
    trail = tj.job.status.elastic["remediations"]
    assert trail[-1]["policy"] == "replace"


def test_cleared_flag_resets_patience_window_even_when_gang_shrinks():
    """A flag that clears via the detector's EMPTY evaluation paths
    (the flagged member's cadence expired, procs dropped below 2) must
    reset the patience window too: a later one-beat re-flag within the
    same attempt starts a fresh window instead of firing an instant
    remediation off the stale one."""
    cs, controller, tj, now, beat = remediation_harness("replace",
                                                        patience=100)
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert [s["processId"] for s in tj.job.status.stragglers] == [2]
    # Everyone but process 0 stops posting; past the cadence expiry the
    # next beat prunes the map below 2 — the empty evaluation clears
    # the flag AND (the fix) the tracker's window.
    now[0] += 400.0
    beat(0, 0.1)
    assert tj.job.status.stragglers == []
    # Fresh flagged round, 400 s after the ORIGINAL first flag: without
    # the window reset this would be instantly "due".
    now[0] += 1.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert tj._pending_remediation is None
    # The new window elapses normally: now it is due.
    now[0] += 101.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert tj._pending_remediation is not None


def test_failed_replace_delete_rearms_remediation():
    """A transient API error on the straggler pod's delete must not
    consume the once-per-attempt remediation: the tracker re-arms and
    the next flagged beat re-issues it (the window already elapsed)."""
    from tpu_operator.client import errors as client_errors

    cs, controller, tj, now, beat = remediation_harness("replace")
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    now[0] += 6.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert tj._pending_remediation is not None

    real_delete = cs.pods.delete
    fails = []

    def flaky_delete(ns, name, *a, **kw):
        fails.append(name)
        raise client_errors.ApiError(500, message="etcd hiccup")

    cs.pods.delete = flaky_delete
    tj.reconcile()          # the delete fails; remediation re-armed
    cs.pods.delete = real_delete
    assert fails
    assert len(cs.pods.list("default")) == 4     # nothing deleted
    # A failed delete must not leave a stale node exclusion behind.
    assert tj.excluded_node("WORKER", 2) is None
    assert controller.metrics.counter_value(
        "job_straggler_remediations_total",
        labels={"policy": "replace"}) == 0
    # Next flagged beat: due again immediately (window already served).
    now[0] += 1.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    assert tj._pending_remediation is not None
    tj.reconcile()
    assert len(cs.pods.list("default")) == 3     # replaced this time
    assert controller.metrics.counter_value(
        "job_straggler_remediations_total",
        labels={"policy": "replace"}) == 1


def test_no_remediation_when_policy_none():
    cs, controller, tj, now, beat = remediation_harness(
        t.StragglerPolicy.NONE, patience=1)
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    now[0] += 600.0
    for pid in range(4):
        beat(pid, 0.5 if pid == 2 else 0.1)
    # Flagged (detector unchanged) but never handed to the reconcile.
    assert [s["processId"] for s in tj.job.status.stragglers] == [2]
    assert tj._pending_remediation is None


def test_remediation_tracker_window_resets_on_unflag_and_attempt():
    tr = elastic_mod.RemediationTracker()
    assert tr.observe("j", 0, {2}, 100.0, 30.0) == []
    # Still flagged at +29: not yet due; at +30: due exactly once.
    assert tr.observe("j", 0, {2}, 129.0, 30.0) == []
    assert tr.observe("j", 0, {2}, 130.0, 30.0) == [2]
    assert tr.observe("j", 0, {2}, 200.0, 30.0) == []
    # A flag that CLEARS resets the clock for a later re-flag.
    assert tr.observe("j", 0, {2, 3}, 210.0, 30.0) == []
    assert tr.observe("j", 0, {2}, 230.0, 30.0) == []       # 3 unflagged
    assert tr.observe("j", 0, {2, 3}, 240.0, 30.0) == []    # 3 re-flagged
    assert tr.observe("j", 0, {2, 3}, 269.0, 30.0) == []
    assert tr.observe("j", 0, {2, 3}, 270.0, 30.0) == [3]
    # New attempt: everything (done-marks included) starts fresh.
    assert tr.observe("j", 1, {2}, 300.0, 30.0) == []
    assert tr.observe("j", 1, {2}, 330.0, 30.0) == [2]
    tr.forget("j")
    assert tr.observe("j", 1, {2}, 400.0, 30.0) == []


# --- reshard-restore through the remote store --------------------------------


def test_resized_gang_reshard_restores_via_remote_store(tmp_path):
    """The donor snapshot reaches the resized gang through the remote
    store: a checkpoint saved (and write-behind uploaded) by an 8-device
    mesh is prefetched into a FRESH local dir — the fresh-node landing
    of a resized gang — and restores onto a 4-device mesh."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpu_operator.payload import checkpoint, models, train, warmstore
    from tpu_operator.store import WarmStartStore, blob, writebehind

    def build(ndev):
        mesh = train.make_mesh(ndev)
        model = models.LinearRegressor()
        tx = optax.sgd(0.1)
        sample = jnp.zeros((8, 8), jnp.float32)
        state = train.create_train_state(model, jax.random.key(0), sample,
                                         tx)
        return mesh, train.place_state(mesh, state)

    backend = blob.from_uri("fake://elastic-reshard")
    store = WarmStartStore(backend, prefix="default/el")
    uploader = writebehind.WriteBehindUploader(store)

    mesh8, state8 = build(8)
    state8 = state8.replace(step=jnp.int32(6))
    donor = checkpoint.Checkpointer(str(tmp_path / "donor"), save_every=1,
                                    uploader=uploader)
    assert donor.maybe_save(6, state8)
    donor.close()   # drains the write-behind upload

    # Fresh node of the shrunken gang: empty local dir, warm store.
    fresh = tmp_path / "fresh"
    prefetched = warmstore.store_from_env({
        "TPUJOB_STORE_URI": "fake://elastic-reshard",
        "TPUJOB_NAMESPACE": "default", "TPUJOB_NAME": "el"})
    step, fallbacks = prefetched.prefetch_checkpoint(str(fresh))
    assert step == 6 and fallbacks == 0

    mesh4, state4 = build(4)
    ck = checkpoint.Checkpointer(str(fresh), save_every=100)
    restored, start = ck.restore(state4)
    ck.close()
    assert start == 6
    assert int(restored.step) == 6
    # Every leaf landed on the LIVE (4-device) mesh's shardings.
    leaf = restored.params["linear"]["kernel"]
    assert leaf.sharding.mesh.shape["data"] == 4


# --- e2e over the in-process apiserver ---------------------------------------


@pytest.fixture()
def harness():
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    config = t.ControllerConfig(slice_inventory={KEY: 8})
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            config, heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop),
                          daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


def phase_of(cs, name):
    return (cs.tpujobs.get("default", name).get("status") or {}) \
        .get("phase")


def test_e2e_preemption_with_shrunken_inventory_gangs_at_4(harness):
    """Acceptance: a Running 8-slice elastic job is preempted while the
    inventory shrinks to 4 → the next attempt gangs at 4, reaches Done
    with status.elastic showing the resize and the down-direction
    resize counter ticked. (The payload half — the checkpoint saved at
    8 reshard-restoring through the remote store — is proven in
    test_resized_gang_reshard_restores_via_remote_store and the
    durability matrix.)"""
    api, cs, controller, _server = harness
    job = elastic_job("grow", min_slices=2)
    cs.tpujobs.create("default", job.to_dict())
    assert wait_for(lambda: len(api.clientset.pods.list("default")) == 8)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: phase_of(cs, "grow") == "Running")

    # The node pool shrinks to 4 slices; the gang is then preempted.
    controller.scheduler.update_inventory({KEY: 4})
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Failed", "containerStatuses": [
            {"name": "tpu", "state": {"terminated": {"exitCode": 137}}}]}
        api.clientset.pods.update("default", pod)

    def attempt1_live():
        return [p for p in api.clientset.pods.list("default")
                if (p.get("status") or {}).get("phase")
                not in ("Failed", "Succeeded")]

    assert wait_for(lambda: len(attempt1_live()) == 4,
                    describe=lambda: cs.tpujobs.get("default",
                                                    "grow")["status"])
    status = cs.tpujobs.get("default", "grow")["status"]
    assert status["elastic"]["slices"] == 4
    assert status["elastic"]["lastResizeDirection"] == "down"
    assert status["failures"][-1]["worldSlices"] == 8
    envs = pod_env(attempt1_live()[0])
    assert envs["JAX_NUM_PROCESSES"] == "4"
    assert controller.metrics.counter_value(
        "job_elastic_resizes_total", labels={"direction": "down"}) == 1

    # The shrunk gang finishes: Done, never Queued.
    for pod in attempt1_live():
        pod["status"] = {"phase": "Succeeded", "containerStatuses": [
            {"name": "tpu", "state": {"terminated": {"exitCode": 0}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: phase_of(cs, "grow") == "Done")
    # describe prints the elastic state + the per-attempt world sizes.
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main(["--master", api.url, "describe", "grow"])
    assert rc == 0
    text = out.getvalue()
    assert "Elastic:" in text and "4/8 slices" in text
    assert "world 8" in text


def test_e2e_straggler_replace_preserves_restart_budget(harness):
    """Acceptance sibling: stragglerPolicy: replace swaps a persistently
    flagged member over the full controller loop — heartbeats through
    the real status server, pod deleted and re-created into the same
    rendezvous — without consuming crash-loop restart budget."""
    api, cs, controller, server = harness
    job = elastic_job("swap", replicas=4, num_slices=4, min_slices=1,
                      policy="replace", patience=1)
    cs.tpujobs.create("default", job.to_dict())
    assert wait_for(lambda: len(api.clientset.pods.list("default")) == 4)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        pod["spec"]["nodeName"] = \
            f"node-{pod['metadata']['labels']['task_index']}"
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: phase_of(cs, "swap") == "Running")
    before = {p["metadata"]["name"]
              for p in api.clientset.pods.list("default")}

    env = {"TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
           "TPUJOB_NAME": "swap", "TPUJOB_NAMESPACE": "default",
           "TPUJOB_ATTEMPT": "0"}

    def post_round(step):
        for pid in range(4):
            reporter = heartbeat_mod.from_env(
                {**env, "JAX_PROCESS_ID": str(pid)})
            digest = {"steps": 20, "stepP95Seconds": 1.0,
                      "stepLocalP95Seconds": 0.5 if pid == 2 else 0.1}
            assert reporter.report(step, {"loss": 2.0},
                                   steptiming=digest)

    post_round(100)
    assert wait_for(lambda: [s.get("processId") for s in
                             (cs.tpujobs.get("default", "swap")["status"]
                              .get("stragglers") or [])] == [2])
    import time as time_mod
    time_mod.sleep(1.2)   # the patience window (1 s) elapses flagged
    post_round(120)

    # The flagged member's pod is deleted and re-created; the gang never
    # restarts (attempt stays 0, no budget spent).
    assert wait_for(lambda: {
        p["metadata"]["name"]
        for p in api.clientset.pods.list("default")} != before
        and len(api.clientset.pods.list("default")) == 4,
        describe=lambda: sorted(
            p["metadata"]["name"]
            for p in api.clientset.pods.list("default")))
    (new_pod,) = [p for p in api.clientset.pods.list("default")
                  if p["metadata"]["name"] not in before]
    envs = pod_env(new_pod)
    assert envs["JAX_PROCESS_ID"] == "2"
    terms = (new_pod["spec"]["affinity"]["nodeAffinity"]
             ["requiredDuringSchedulingIgnoredDuringExecution"]
             ["nodeSelectorTerms"])
    assert {"key": "kubernetes.io/hostname", "operator": "NotIn",
            "values": ["node-2"]} in terms[0]["matchExpressions"]
    status = cs.tpujobs.get("default", "swap")["status"]
    assert status["attempt"] == 0
    assert status.get("restartCounts") is None \
        or status["restartCounts"] == {}
    assert (status["elastic"]["remediations"][-1]["policy"]
            == "replace")
    events = [e for e in cs.events.list("default")
              if e.get("reason") == "StragglerReplaced"]
    assert events and "process 2" in events[0]["message"]
    assert controller.metrics.counter_value(
        "job_straggler_remediations_total",
        labels={"policy": "replace"}) == 1


# --- tpujobctl surfacing -----------------------------------------------------


def test_describe_shows_elastic_state():
    with ApiServerHarness() as srv:
        cs = Clientset(RestConfig(host=srv.url, timeout=5.0))
        job = elastic_job("shape", min_slices=2, policy="shed")
        set_defaults(job.spec)
        job.status.phase = t.TPUJobPhase.RUNNING
        job.status.elastic = {
            "slices": 4, "workers": 4, "minSlices": 2, "maxSlices": 8,
            "attempt": 2, "resizes": 2, "lastResizeDirection": "down",
            "time": "2026-08-04T00:00:00.000000Z",
            "remediations": [{"attempt": 1, "processId": 3,
                              "policy": "shed",
                              "time": "2026-08-04T00:00:00.000000Z"}]}
        job.status.failures = [
            t.FailureRecord(attempt=0, kind=t.FailureKind.PREEMPTION,
                            reason="slice preempted",
                            time="2026-08-04T00:00:00Z", resume_step=6,
                            world_slices=8),
            t.FailureRecord(attempt=1, kind=t.FailureKind.PREEMPTION,
                            reason="StragglerShed: process 3",
                            time="2026-08-04T00:01:00Z", resume_step=8,
                            world_slices=5)]
        cs.tpujobs.create("default", job.to_dict())

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = ctl.main(["--master", srv.url, "describe", "shape"])
        text = out.getvalue()
    assert rc == 0
    assert "Elastic:    4/8 slices" in text
    assert "range 2-8" in text
    assert "resizes 2" in text and "policy shed" in text
    assert "Remediated: attempt 1: shed process 3" in text
    # Each ledger line carries world size AND resume step together.
    assert "resume@6" in text and "world 8" in text
    assert "resume@8" in text and "world 5" in text
