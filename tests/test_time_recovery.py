"""Time-aware failure recovery: stall watchdog, restart backoff, per-kind
retry budgets, active deadline, finished-TTL, and the deadline manager —
all driven by injected clocks so every release time is asserted exactly.
"""

import pytest

from tpu_operator.apis.tpujob import validation
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.workqueue import RateLimitingQueue
from tpu_operator.controller.deadlines import GRACE_SECONDS, DeadlineManager
from tpu_operator.controller.events import EventRecorder
from tpu_operator.controller.statusserver import Metrics
from tpu_operator.trainer import policy
from tpu_operator.trainer import training
from tpu_operator.trainer.training import TrainingJob
from tpu_operator.util.util import format_rfc3339, parse_rfc3339
from tests.test_types import make_template

T0 = 1_700_000_000.0  # arbitrary fixed epoch


class FakeNow:
    """Injectable wall clock for trainer.training._now (RFC3339 strings)."""

    def __init__(self, start: float = T0):
        self.t = start

    def __call__(self) -> str:
        return format_rfc3339(self.t)

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def clock(monkeypatch):
    fake = FakeNow()
    monkeypatch.setattr(training, "_now", fake)
    return fake


def make_job(name="timely", replicas=2, max_restarts=3, **spec_kw):
    return t.TPUJob(
        metadata={"name": name, "namespace": "default", "uid": "uid-t",
                  "creationTimestamp": format_rfc3339(T0)},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=replicas, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER)
            ],
            runtime_id="tm01",
            max_restarts=max_restarts,
            **spec_kw,
        ),
    )


def new_tj(job, metrics=None):
    cs = FakeClientset()
    cs.tpujobs.create(job.namespace, job.to_dict())
    return cs, TrainingJob(cs, EventRecorder(cs), job, metrics=metrics)


def set_pod_state(cs, pod, phase, state=None, reason=""):
    status = {"phase": phase}
    if reason:
        status["reason"] = reason
    if state is not None:
        status["containerStatuses"] = [{"name": "tpu", "state": state}]
    pod["status"] = status
    cs.pods.update("default", pod)


def all_running(cs):
    for p in cs.pods.list("default"):
        set_pod_state(cs, p, "Running", state={"running": {}})


def fail_pod(cs, exit_code=None, reason=""):
    victim = cs.pods.list("default")[0]
    if exit_code is not None:
        set_pod_state(cs, victim, "Failed",
                      state={"terminated": {"exitCode": exit_code}})
    else:
        set_pod_state(cs, victim, "Failed", reason=reason)


# --- failure classification --------------------------------------------------

@pytest.mark.parametrize("pod_status,expected_kind", [
    ({"phase": "Failed", "reason": "Evicted"}, "preemption"),
    ({"phase": "Failed", "reason": "Preempted"}, "preemption"),
    ({"phase": "Failed", "containerStatuses": [
        {"name": "tpu", "state": {"terminated": {"exitCode": 137}}}]},
     "preemption"),  # SIGKILL, non-OOM: external termination
    ({"phase": "Failed", "containerStatuses": [
        {"name": "tpu", "state": {"terminated": {"exitCode": 143}}}]},
     "preemption"),  # SIGTERM: node drain
    ({"phase": "Failed", "containerStatuses": [
        {"name": "tpu", "state": {"terminated": {"exitCode": 139}}}]},
     "application"),  # SIGSEGV: payload crash
    ({"phase": "Failed", "containerStatuses": [
        {"name": "tpu", "state": {"terminated": {"exitCode": 1}}}]},
     None),  # permanent, not retryable
    ({"phase": "Failed", "containerStatuses": [
        {"name": "tpu", "state": {"terminated":
                                  {"exitCode": 137, "reason": "OOMKilled"}}}]},
     None),  # OOM never retries
])
def test_classify_pod_failure(pod_status, expected_kind):
    pod = {"metadata": {"name": "p"}, "status": pod_status}
    info = policy.classify_pod_failure(pod)
    if expected_kind is None:
        assert info is None
    else:
        assert info is not None and info[0] == expected_kind


# --- restart backoff (exact release times via injected clock) ----------------

def test_backoff_parks_then_releases_exact_times(clock):
    job = make_job(restart_backoff=t.RestartBackoffSpec(base_seconds=10,
                                                        max_seconds=360))
    cs, tj = new_tj(job, metrics=Metrics())
    tj.reconcile()
    all_running(cs)
    tj.reconcile()

    # restart 1: teardown is immediate, gang-create parks for base seconds
    fail_pod(cs, exit_code=139)
    clock.advance(5.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    assert tj.job.status.attempt == 1
    assert cs.pods.list("default") == []  # slice freed immediately
    release1 = parse_rfc3339(tj.job.status.backoff_until)
    assert release1 == pytest.approx(clock.t + 10.0)

    # before the release time: still parked, no pods
    clock.advance(9.5)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    assert cs.pods.list("default") == []

    # past the release time: re-gangs attempt 1
    clock.advance(1.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    assert tj.job.status.backoff_until == ""
    pods = cs.pods.list("default")
    assert len(pods) == 2
    assert all(p["metadata"]["labels"]["attempt"] == "1" for p in pods)
    events = [e["reason"] for e in cs.events.list("default")]
    assert "BackoffComplete" in events

    # restart 2 doubles the delay: exactly 20 s
    all_running(cs)
    tj.reconcile()
    fail_pod(cs, exit_code=139)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    release2 = parse_rfc3339(tj.job.status.backoff_until)
    assert release2 == pytest.approx(clock.t + 20.0)

    hist = tj.metrics.histogram_snapshot("group_restart_backoff_seconds")
    assert hist["count"] == 2
    assert hist["sum"] == pytest.approx(30.0)


def test_backoff_delay_capped_at_max():
    bo = t.RestartBackoffSpec(base_seconds=10, max_seconds=60)
    assert [bo.delay_for_restart(n) for n in (1, 2, 3, 4, 5)] == \
        [10.0, 20.0, 40.0, 60.0, 60.0]
    assert t.RestartBackoffSpec(base_seconds=0).delay_for_restart(1) == 0.0


def test_zero_base_backoff_regangs_instantly(clock):
    job = make_job(restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    cs, tj = new_tj(job)
    tj.reconcile()
    fail_pod(cs, exit_code=139)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    tj.reconcile()
    assert len(cs.pods.list("default")) == 2


def test_reason_cleared_when_job_recovers(clock):
    """Bugfix: a recovered job must not report its last restart forever."""
    job = make_job(restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    cs, tj = new_tj(job)
    tj.reconcile()
    fail_pod(cs, exit_code=139)
    tj.reconcile()
    assert "group restart" in tj.job.status.reason
    tj.reconcile()  # recreate generation
    all_running(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.reason == ""


def test_backoff_exponent_decays_after_sustained_health(clock):
    """An old crash burst must not inflate the delay applied to a failure
    weeks later: the consecutive-failure streak resets after the job has
    been Running healthily for BACKOFF_RESET_SECONDS."""
    job = make_job(restart_backoff=t.RestartBackoffSpec(base_seconds=10,
                                                        max_seconds=360),
                   max_restarts=10)
    cs, tj = new_tj(job)
    tj.reconcile()
    # two quick failures escalate the delay to 2*base
    for _ in range(2):
        fail_pod(cs, exit_code=143)
        tj.reconcile()
        clock.advance(400.0)  # past any backoff
        tj.reconcile()
    assert tj.job.status.consecutive_failures == 2

    # a long healthy stretch resets the streak...
    all_running(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    clock.advance(training.BACKOFF_RESET_SECONDS + 1.0)
    tj.reconcile()
    assert tj.job.status.consecutive_failures == 0

    # ...so the next failure waits the BASE delay again, not 4*base
    fail_pod(cs, exit_code=143)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    release = parse_rfc3339(tj.job.status.backoff_until)
    assert release == pytest.approx(clock.t + 10.0)
    # lifetime counters kept the full history for the budget math
    assert tj.job.status.restart_counts["preemption"] == 3


def test_backoff_spec_base_only_defaults_sane_max():
    """Omitting maxSeconds must never contradict an explicit large base."""
    spec = t.TPUJobSpec.from_dict({
        "replicaSpecs": [{"template": {"spec": {"containers": [
            {"name": "tpu"}]}}}],
        "restartBackoff": {"baseSeconds": 600},
    })
    set_defaults(spec)
    validation.validate_tpujob_spec(spec)  # must not raise
    assert spec.restart_backoff.max_seconds >= 600


def test_backoff_spec_max_only_defaults_sane_base():
    """Omitting baseSeconds must never contradict an explicit small max."""
    spec = t.TPUJobSpec.from_dict({
        "replicaSpecs": [{"template": {"spec": {"containers": [
            {"name": "tpu"}]}}}],
        "restartBackoff": {"maxSeconds": 5},
    })
    set_defaults(spec)
    validation.validate_tpujob_spec(spec)  # must not raise
    assert spec.restart_backoff.base_seconds <= 5
    assert spec.restart_backoff.max_seconds == 5


# --- per-kind retry budgets --------------------------------------------------

def test_application_crash_wins_across_replica_sets(clock):
    """A crash in a later replica set must be billed to the application
    budget even when an earlier set's collateral SIGKILL (preemption-kind)
    is discovered first — same application-wins rule as within one set."""
    job = t.TPUJob(
        metadata={"name": "ps", "namespace": "default", "uid": "uid-ps",
                  "creationTimestamp": format_rfc3339(T0)},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=1, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.SCHEDULER),
                t.TPUReplicaSpec(replicas=1, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.SERVER),
            ],
            runtime_id="ps01",
            max_restarts=3,
            restart_policy=t.RestartPolicy.WHOLE_GROUP,
            restart_backoff=t.RestartBackoffSpec(base_seconds=0),
        ),
    )
    cs, tj = new_tj(job)
    tj.reconcile()
    # scheduler pod (first set) dies by SIGKILL, server pod segfaults
    for p in cs.pods.list("default"):
        code = 137 if "scheduler" in p["metadata"]["name"] else 139
        set_pod_state(cs, p, "Failed",
                      state={"terminated": {"exitCode": code}})
    tj.reconcile()
    assert [f.kind for f in tj.job.status.failures] == ["application"]


def test_ledger_dedups_per_attempt_and_kind(clock):
    """Re-entry with the same attempt+kind (teardown died mid-restart) must
    not double-bill; a different kind on the same attempt (deadline expiring
    before the attempt bump persisted) must still be recorded, or the
    postmortem trail would contradict the terminal reason."""
    cs, tj = new_tj(make_job())
    tj._record_failure(0, "application", "segfault")
    tj._record_failure(0, "application", "segfault (requeue)")
    tj._record_failure(0, "deadline", "activeDeadlineSeconds exceeded")
    assert [(f.attempt, f.kind) for f in tj.job.status.failures] == [
        (0, "application"), (0, "deadline")]
    assert tj.job.status.restart_counts == {"application": 1, "deadline": 1}


def test_preemptions_do_not_spend_application_budget(clock):
    # maxRestarts=1 → 1 application restart, 4 preemption restarts.
    job = make_job(max_restarts=1,
                   restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    cs, tj = new_tj(job)
    tj.reconcile()

    # three consecutive preemptions: all restart, none fails the job
    for round_ in range(3):
        fail_pod(cs, reason="Evicted")
        tj.reconcile()
        assert tj.job.status.phase == t.TPUJobPhase.CREATING, round_
        tj.reconcile()  # recreate
    assert tj.job.status.attempt == 3
    assert [f.kind for f in tj.job.status.failures] == ["preemption"] * 3

    # application budget is still intact: one crash restarts...
    fail_pod(cs, exit_code=139)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    tj.reconcile()
    # ...the second exhausts it
    fail_pod(cs, exit_code=139)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "retry budget exhausted" in tj.job.status.reason


def test_preemption_budget_is_larger_but_finite(clock):
    job = make_job(max_restarts=1,
                   restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    cs, tj = new_tj(job)
    tj.reconcile()
    budget = 1 * t.PREEMPTION_BUDGET_FACTOR
    for round_ in range(budget):
        fail_pod(cs, reason="Preempted")
        tj.reconcile()
        assert tj.job.status.phase == t.TPUJobPhase.CREATING, round_
        tj.reconcile()
    fail_pod(cs, reason="Preempted")
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "retry budget exhausted" in tj.job.status.reason


def test_failure_ledger_bounded_but_counters_lifetime(clock):
    job = make_job(max_restarts=1000,
                   restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    cs, tj = new_tj(job)
    tj.reconcile()
    n = t.FAILURE_LEDGER_CAP + 5
    for _ in range(n):
        fail_pod(cs, exit_code=143)
        tj.reconcile()
        tj.reconcile()
    assert len(tj.job.status.failures) == t.FAILURE_LEDGER_CAP
    # the budget counters are NOT bounded by the ledger
    assert tj.job.status.restart_counts["preemption"] == n


def test_budget_enforced_beyond_ledger_cap(clock):
    """The retry budget must stay armed even when it exceeds the ledger's
    retention: eviction of old entries cannot re-arm an exhausted budget."""
    job = make_job(max_restarts=10,  # preemption budget 40 > ledger cap 32
                   restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    cs, tj = new_tj(job)
    tj.reconcile()
    budget = 10 * t.PREEMPTION_BUDGET_FACTOR
    for round_ in range(budget):
        fail_pod(cs, reason="Preempted")
        tj.reconcile()
        assert tj.job.status.phase == t.TPUJobPhase.CREATING, round_
        tj.reconcile()
    fail_pod(cs, reason="Preempted")
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "retry budget exhausted" in tj.job.status.reason
    assert len(tj.job.status.failures) == t.FAILURE_LEDGER_CAP  # still capped


# --- stall watchdog ----------------------------------------------------------

def stalled_job(clock, stall=60, **kw):
    job = make_job(stall_timeout_seconds=stall,
                   restart_backoff=t.RestartBackoffSpec(base_seconds=0), **kw)
    cs, tj = new_tj(job, metrics=Metrics())
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    return cs, tj


def test_stall_detected_without_any_heartbeat(clock):
    """Payload hung before its first heartbeat: the baseline falls back to
    the last phase transition (entry into Running)."""
    cs, tj = stalled_job(clock)
    clock.advance(59.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING  # not yet
    clock.advance(2.0)
    tj.reconcile()
    assert tj.job.status.attempt == 1
    assert "StallDetected" in tj.job.status.reason
    assert tj.job.status.failures[-1].kind == "stall"
    assert tj.metrics.snapshot()["job_stalls_total"] == 1
    assert any(e["reason"] == "StallDetected"
               for e in cs.events.list("default"))
    # hung pods were torn down with the generation
    assert all(p["metadata"]["labels"]["attempt"] == "1"
               for p in cs.pods.list("default"))


def test_fresh_heartbeat_defers_stall(clock):
    cs, tj = stalled_job(clock)
    clock.advance(50.0)
    tj.job.status.last_heartbeat = {"time": training._now(), "step": 10,
                                    "attempt": 0}
    clock.advance(50.0)  # 100 s after Running, 50 s after the heartbeat
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.attempt == 0
    clock.advance(11.0)  # now 61 s since the heartbeat
    tj.reconcile()
    assert tj.job.status.attempt == 1
    assert tj.job.status.failures[-1].kind == "stall"


def test_stall_restart_respects_backoff(clock):
    """Stale heartbeat drives the same teardown + backoff path as pod
    death: teardown immediate, re-gang parked."""
    job = make_job(stall_timeout_seconds=30,
                   restart_backoff=t.RestartBackoffSpec(base_seconds=15,
                                                        max_seconds=60))
    cs, tj = new_tj(job)
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    clock.advance(31.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    assert cs.pods.list("default") == []
    release = parse_rfc3339(tj.job.status.backoff_until)
    assert release == pytest.approx(clock.t + 15.0)


def test_no_stall_when_not_configured(clock):
    cs, tj = stalled_job(clock, stall=None)
    clock.advance(100000.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.attempt == 0


# --- active deadline ---------------------------------------------------------

def test_deadline_exceeded_fails_job_and_frees_slice(clock):
    job = make_job(active_deadline_seconds=300)
    cs, tj = new_tj(job, metrics=Metrics())
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    clock.advance(299.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    clock.advance(2.0)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "DeadlineExceeded" in tj.job.status.reason
    assert tj.job.status.failures[-1].kind == "deadline"
    assert tj.metrics.snapshot()["job_deadline_exceeded_total"] == 1
    assert any(e["reason"] == "DeadlineExceeded"
               for e in cs.events.list("default"))
    # running pods were deleted (slice freed), terminal state persisted
    assert cs.pods.list("default") == []
    stored = cs.tpujobs.get("default", "timely")
    assert stored["status"]["phase"] == "Failed"


def test_deadline_counts_from_first_creating(clock):
    job = make_job(active_deadline_seconds=100)
    cs, tj = new_tj(job)
    tj.reconcile()  # stamps Creating at T0
    # a group restart later must not reset the deadline clock
    fail_pod(cs, exit_code=143)
    clock.advance(50.0)
    tj.reconcile()
    tj.reconcile()
    clock.advance(51.0)  # 101 s since Creating, 51 s since restart
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert "DeadlineExceeded" in tj.job.status.reason


# --- TTL after finished ------------------------------------------------------

def test_ttl_reaps_finished_job(clock):
    job = make_job(ttl_seconds_after_finished=120)
    cs, tj = new_tj(job)
    tj.reconcile()
    for p in cs.pods.list("default"):
        set_pod_state(cs, p, "Succeeded",
                      state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE

    clock.advance(119.0)
    tj.reconcile()
    assert cs.tpujobs.list("default")  # still there
    clock.advance(2.0)
    tj.reconcile()
    assert cs.tpujobs.list("default") == []  # object reaped
    assert cs.pods.list("default") == []     # children reaped
    assert cs.services.list("default") == []
    assert any(e["reason"] == "TTLExpired"
               for e in cs.events.list("default"))


def test_ttl_reap_disarms_obligation_and_is_idempotent(clock):
    """After the reap, the informer cache may echo the object for a few more
    reconciles; the past TTL must not be re-armed (50 ms wakeup hot loop)
    and the reap path must not re-run (duplicate TTLExpired events)."""
    job = make_job(ttl_seconds_after_finished=120)
    cs, tj = new_tj(job)
    tj.reconcile()
    for p in cs.pods.list("default"):
        set_pod_state(cs, p, "Succeeded",
                      state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    clock.advance(121.0)
    tj.reconcile()
    assert cs.tpujobs.list("default") == []
    assert tj.next_time_obligation() is None
    ttl_events = [e for e in cs.events.list("default")
                  if e["reason"] == "TTLExpired"]
    tj.reconcile()  # cache echo: must be a no-op
    assert tj.next_time_obligation() is None
    assert [e for e in cs.events.list("default")
            if e["reason"] == "TTLExpired"] == ttl_events


def test_no_ttl_keeps_finished_job_forever(clock):
    cs, tj = new_tj(make_job())
    tj.reconcile()
    for p in cs.pods.list("default"):
        set_pod_state(cs, p, "Succeeded",
                      state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    clock.advance(10_000_000.0)
    tj.reconcile()
    assert cs.tpujobs.list("default")
    assert len(cs.pods.list("default")) == 2  # logs retained


# --- next_time_obligation ----------------------------------------------------

def test_next_time_obligation_picks_earliest(clock):
    job = make_job(active_deadline_seconds=1000, stall_timeout_seconds=60)
    cs, tj = new_tj(job)
    tj.reconcile()
    # Creating: only the deadline applies (stall arms on Running)
    assert tj.next_time_obligation() == pytest.approx(T0 + 1000.0)
    all_running(cs)
    tj.reconcile()
    # Running: the stall check (entry into Running + 60) is sooner
    assert tj.next_time_obligation() == pytest.approx(
        (parse_rfc3339(tj.job.status.last_transition_time)) + 60.0)


def test_next_time_obligation_backoff_and_ttl(clock):
    job = make_job(ttl_seconds_after_finished=500,
                   restart_backoff=t.RestartBackoffSpec(base_seconds=40))
    cs, tj = new_tj(job)
    tj.reconcile()
    fail_pod(cs, exit_code=143)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    assert tj.next_time_obligation() == pytest.approx(clock.t + 40.0)

    # drive to Done, expect the TTL obligation
    clock.advance(41.0)
    tj.reconcile()
    for p in cs.pods.list("default"):
        set_pod_state(cs, p, "Succeeded",
                      state={"terminated": {"exitCode": 0}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE
    assert tj.next_time_obligation() == pytest.approx(clock.t + 500.0)


def test_no_obligation_for_plain_running_job(clock):
    cs, tj = new_tj(make_job())
    tj.reconcile()
    all_running(cs)
    tj.reconcile()
    assert tj.next_time_obligation() is None


# --- deadline manager --------------------------------------------------------

class SharedClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_deadline_manager_schedules_exact_wakeup():
    clock = SharedClock()
    q = RateLimitingQueue(clock=clock)
    dm = DeadlineManager(q, clock=clock)
    dm.sync("default/j", clock.now + 30.0)
    assert q.get(timeout=0) is None  # not due yet
    clock.now += 30.0 + GRACE_SECONDS
    assert q.get(timeout=0) == "default/j"


def test_deadline_manager_dedups_pending_wakeups():
    clock = SharedClock()
    q = RateLimitingQueue(clock=clock)
    dm = DeadlineManager(q, clock=clock)
    # every reconcile re-syncs the same obligation: only one timer armed
    for _ in range(5):
        dm.sync("k", clock.now + 10.0)
    clock.now += 60.0
    assert q.get(timeout=0) == "k"
    q.done("k")
    assert q.get(timeout=0) is None


def test_deadline_manager_earlier_obligation_wins():
    clock = SharedClock()
    q = RateLimitingQueue(clock=clock)
    dm = DeadlineManager(q, clock=clock)
    dm.sync("k", clock.now + 100.0)
    dm.sync("k", clock.now + 10.0)  # new, earlier obligation re-arms
    clock.now += 10.0 + GRACE_SECONDS
    assert q.get(timeout=0) == "k"


def test_timer_wakeups_stay_out_of_workqueue_metrics():
    """Deadline wakeups are scheduled work, not error requeues: they must
    not tick workqueue_retries_total, and their queue latency counts from
    the due time, not from (possibly hours-earlier) scheduling."""
    clock = SharedClock()
    metrics = Metrics()
    q = RateLimitingQueue(clock=clock, metrics=metrics)
    dm = DeadlineManager(q, clock=clock)
    dm.sync("k", clock.now + 86400.0)  # a day-long TTL park
    assert metrics.snapshot()["workqueue_retries_total"] == 0
    clock.now += 86400.0 + GRACE_SECONDS
    assert q.get(timeout=0) == "k"
    hist = metrics.histogram_snapshot("workqueue_queue_duration_seconds")
    # latency sample reflects due→pop (~0), not the 86400 s park
    assert hist["sum"] < 60.0, hist
    # an error requeue still counts as before
    q.add_rate_limited("k2")
    assert metrics.snapshot()["workqueue_retries_total"] == 1


def test_deadline_manager_forget():
    clock = SharedClock()
    q = RateLimitingQueue(clock=clock)
    dm = DeadlineManager(q, clock=clock)
    dm.sync("k", clock.now + 5.0)
    assert dm.pending("k") is not None
    dm.forget("k")
    assert dm.pending("k") is None
    dm.sync("k", None)
    assert len(dm) == 0


# --- spec plumbing -----------------------------------------------------------

def test_new_spec_fields_roundtrip_and_default():
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(template=make_template())],
        active_deadline_seconds=600,
        stall_timeout_seconds=120,
        ttl_seconds_after_finished=0,
        restart_backoff=t.RestartBackoffSpec(base_seconds=5, max_seconds=50),
    )
    wire = spec.to_dict()
    assert wire["activeDeadlineSeconds"] == 600
    assert wire["stallTimeoutSeconds"] == 120
    assert wire["ttlSecondsAfterFinished"] == 0
    assert wire["restartBackoff"] == {"baseSeconds": 5, "maxSeconds": 50}
    back = t.TPUJobSpec.from_dict(wire)
    assert back.active_deadline_seconds == 600
    assert back.stall_timeout_seconds == 120
    assert back.ttl_seconds_after_finished == 0
    assert back.restart_backoff.base_seconds == 5

    # unset: absent from the wire; defaulting fills only the backoff
    plain = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(template=make_template())])
    wire = plain.to_dict()
    for key in ("activeDeadlineSeconds", "stallTimeoutSeconds",
                "ttlSecondsAfterFinished", "restartBackoff"):
        assert key not in wire
    set_defaults(plain)
    assert plain.restart_backoff.base_seconds == t.DEFAULT_RESTART_BACKOFF_BASE
    assert plain.restart_backoff.max_seconds == t.DEFAULT_RESTART_BACKOFF_MAX
    assert plain.active_deadline_seconds is None

    # an explicit zero-base backoff survives defaulting (opt-out)
    zero = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(template=make_template())],
        restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    set_defaults(zero)
    assert zero.restart_backoff.base_seconds == 0


def test_status_ledger_roundtrip():
    st = t.TPUJobStatus(
        phase=t.TPUJobPhase.BACKOFF,
        backoff_until=format_rfc3339(T0),
        last_transition_time=format_rfc3339(T0),
        failures=[t.FailureRecord(attempt=0, kind="preemption",
                                  reason="pod x failed: Evicted",
                                  time=format_rfc3339(T0))],
    )
    wire = st.to_dict()
    assert wire["backoffUntil"] == format_rfc3339(T0)
    assert wire["failures"][0]["kind"] == "preemption"
    back = t.TPUJobStatus.from_dict(wire)
    assert back.phase == t.TPUJobPhase.BACKOFF
    assert back.failures[0].reason == "pod x failed: Evicted"
    assert back.to_dict() == wire


@pytest.mark.parametrize("kw,msg", [
    ({"active_deadline_seconds": 0}, "activeDeadlineSeconds"),
    ({"stall_timeout_seconds": -5}, "stallTimeoutSeconds"),
    ({"ttl_seconds_after_finished": -1}, "ttlSecondsAfterFinished"),
    ({"restart_backoff": t.RestartBackoffSpec(base_seconds=-1)},
     "baseSeconds"),
    ({"restart_backoff": t.RestartBackoffSpec(base_seconds=10,
                                              max_seconds=5)},
     "maxSeconds"),
])
def test_validation_rejects_bad_time_fields(kw, msg):
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(template=make_template())], **kw)
    set_defaults(spec)
    with pytest.raises(validation.ValidationError) as exc:
        validation.validate_tpujob_spec(spec)
    assert msg in str(exc.value)
