"""Helper + util table tests.

Reference test model: pkg/apis/mxnet/helper/helpers_test.go:28-248
(accelerator volume/env injection outcomes) — rebuilt to compile and to cover
the TPU env-injection path the reference never had.
"""

from tpu_operator.apis.tpujob import helper
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.util import util
from tests.test_types import make_spec, make_template


def test_as_owner():
    # ref: helpers.go:40-52
    owner = helper.as_owner({"name": "job1", "uid": "uid-42"})
    assert owner == {
        "apiVersion": "tpuoperator.dev/v1alpha1",
        "kind": "TPUJob",
        "name": "job1",
        "uid": "uid-42",
        "controller": True,
        "blockOwnerDeletion": True,
    }


def test_configure_accelerators_gpu_style_volumes():
    # ref: helpers_test.go:28-150 shape — GPU resource gets hostPath volumes
    spec = make_spec()
    spec.replica_specs[0].template["spec"]["containers"][0]["resources"] = {
        "limits": {"alpha.kubernetes.io/nvidia-gpu": 1}
    }
    cfg = t.ControllerConfig.from_dict(
        {
            "accelerators": {
                "alpha.kubernetes.io/nvidia-gpu": {
                    "volumes": [
                        {"name": "cuda-lib", "hostPath": "/usr/lib/cuda",
                         "mountPath": "/usr/local/cuda"}
                    ],
                    "envVars": {"CUDA_HOME": "/usr/local/cuda"},
                }
            }
        }
    )
    helper.configure_accelerators(spec, cfg)
    pod_spec = spec.replica_specs[0].template["spec"]
    assert pod_spec["volumes"] == [{"name": "cuda-lib", "hostPath": {"path": "/usr/lib/cuda"}}]
    container = pod_spec["containers"][0]
    assert container["volumeMounts"] == [{"name": "cuda-lib", "mountPath": "/usr/local/cuda"}]
    assert {"name": "CUDA_HOME", "value": "/usr/local/cuda"} in container["env"]


def test_configure_accelerators_tpu_env_only():
    # The TPU path: resource cloud-tpus.google.com/v4 → env only, no volumes
    spec = make_spec()
    spec.replica_specs[0].template["spec"]["containers"][0]["resources"] = {
        "requests": {"cloud-tpus.google.com/v4": 4}
    }
    cfg = t.ControllerConfig.from_dict(
        {"accelerators": {"cloud-tpus.google.com/v4": {"envVars": {"TPU_RUNTIME": "tpu-vm"}}}}
    )
    helper.configure_accelerators(spec, cfg)
    container = spec.replica_specs[0].template["spec"]["containers"][0]
    assert {"name": "TPU_RUNTIME", "value": "tpu-vm"} in container["env"]
    assert "volumes" not in spec.replica_specs[0].template["spec"]


def test_configure_accelerators_no_match_no_change():
    spec = make_spec()
    before = spec.to_dict()
    cfg = t.ControllerConfig.from_dict(
        {"accelerators": {"cloud-tpus.google.com/v4": {"envVars": {"X": "y"}}}}
    )
    helper.configure_accelerators(spec, cfg)
    assert spec.to_dict() == before


def test_configure_accelerators_does_not_clobber_user_env():
    spec = make_spec()
    container = spec.replica_specs[0].template["spec"]["containers"][0]
    container["resources"] = {"limits": {"cloud-tpus.google.com/v4": 4}}
    container["env"] = [{"name": "TPU_RUNTIME", "value": "user-set"}]
    cfg = t.ControllerConfig.from_dict(
        {"accelerators": {"cloud-tpus.google.com/v4": {"envVars": {"TPU_RUNTIME": "tpu-vm"}}}}
    )
    helper.configure_accelerators(spec, cfg)
    assert container["env"] == [{"name": "TPU_RUNTIME", "value": "user-set"}]


def test_tpu_chips_requested():
    assert helper.tpu_chips_requested(make_template(tpu_chips=4)) == 4
    assert helper.tpu_chips_requested(make_template()) == 0
    assert helper.tpu_chips_requested(None) == 0
    # limits win over requests
    tmpl = make_template()
    tmpl["spec"]["containers"][0]["resources"] = {
        "requests": {"cloud-tpus.google.com/v4": 2},
        "limits": {"cloud-tpus.google.com/v4": 8},
    }
    assert helper.tpu_chips_requested(tmpl) == 8


# --- util -------------------------------------------------------------------

def test_rand_string_dns_safe():
    # ref: util.go:58-74
    util.seed(7)
    s = util.rand_string(16)
    assert len(s) == 16
    assert s == s.lower()
    assert all(c.isalnum() for c in s)


def test_rand_string_deterministic_with_seed():
    util.seed(123)
    a = util.rand_string(8)
    util.seed(123)
    assert util.rand_string(8) == a


def test_pformat_handles_unserializable():
    class Odd:
        pass

    out = util.pformat({"x": 1})
    assert '"x": 1' in out
    assert util.pformat(Odd())  # falls back without raising


def test_operator_namespace_env(monkeypatch):
    monkeypatch.delenv("TPU_OPERATOR_NAMESPACE", raising=False)
    monkeypatch.delenv("MY_POD_NAMESPACE", raising=False)
    assert util.get_operator_namespace() == "default"
    monkeypatch.setenv("MY_POD_NAMESPACE", "kube-pods")
    assert util.get_operator_namespace() == "kube-pods"
    monkeypatch.setenv("TPU_OPERATOR_NAMESPACE", "tpu-system")
    assert util.get_operator_namespace() == "tpu-system"
