"""Sequence-chunked lm_head/loss (train.chunked_next_token_nll*).

The chunked form is a pure re-association of the unchunked loss — same
bf16 head matmul, same f32 lse/target gather per position, chunk-partial
sums — so value AND gradient parity must hold to f32 reduction tolerance.
These tests pin that, the validation contract, and the end-to-end
transformer step with --loss-chunk (the 32k-context activation lever,
docs/benchmarks.md round 5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_operator.payload import train


def _case(b=2, t=64, d=32, v=96, seed=0):
    rng = np.random.default_rng(seed)
    hidden = jnp.asarray(rng.standard_normal((b, t, d)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.float32)
    tokens = jnp.asarray(rng.integers(0, v, size=(b, t)), jnp.int32)
    return hidden, w, tokens


def _dense_loss(hidden, w, tokens):
    logits = hidden @ w.astype(hidden.dtype)
    return train.next_token_nll(logits, tokens)


@pytest.mark.parametrize("chunk", [8, 16, 64])
def test_chunked_nll_matches_dense(chunk):
    hidden, w, tokens = _case()
    dense = _dense_loss(hidden, w, tokens)
    chunked = train.chunked_next_token_nll(hidden, w, tokens, chunk)
    assert float(chunked) == pytest.approx(float(dense), rel=1e-5)


def test_chunked_nll_grad_parity():
    hidden, w, tokens = _case()

    g_dense = jax.grad(_dense_loss, argnums=(0, 1))(hidden, w, tokens)
    g_chunk = jax.grad(train.chunked_next_token_nll, argnums=(0, 1))(
        hidden, w, tokens, 16)
    for gd, gc in zip(g_dense, g_chunk):
        np.testing.assert_allclose(np.asarray(gd, np.float32),
                                   np.asarray(gc, np.float32),
                                   rtol=2e-2, atol=3e-4)


def test_chunked_masked_matches_dense_masked():
    hidden, w, tokens = _case()
    b, t = tokens.shape
    rng = np.random.default_rng(7)
    targets = jnp.asarray(rng.integers(0, w.shape[1], (b, t)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (b, t)), bool)
    logits = hidden @ w.astype(hidden.dtype)
    dense = train.next_token_nll_masked(logits, targets, mask)
    chunked = train.chunked_next_token_nll_masked(hidden, w, targets, mask,
                                                  16)
    assert float(chunked) == pytest.approx(float(dense), rel=1e-5)


def test_chunk_must_divide_t():
    hidden, w, tokens = _case(t=64)
    with pytest.raises(ValueError, match="divide"):
        train.chunked_next_token_nll(hidden, w, tokens, 24)
    with pytest.raises(ValueError, match="positive"):
        train.chunked_next_token_nll(hidden, w, tokens, 0)


def _lm_args(extra):
    from tpu_operator.payload import transformer

    return transformer.parse_args(
        ["--dim", "64", "--layers", "2", "--heads", "2", "--batch", "8",
         "--seq-len", "128", "--vocab", "256", "--steps", "1"] + extra)


def test_transformer_step_parity_with_loss_chunk():
    """Same seed, same batch: the --loss-chunk step's loss equals the
    unchunked step's (the whole pipeline — trunk, head, reduction — is
    numerically the same computation)."""
    from tpu_operator.payload import transformer

    losses = {}
    for extra in ([], ["--loss-chunk", "32"]):
        args = _lm_args(extra)
        mesh, _model, state, step, batches = transformer.build(args)
        from tpu_operator.payload import data as data_mod

        batch = data_mod.put_global_batch(
            mesh, *next(batches), spec=transformer.lm_token_spec(mesh))
        _state, metrics = step(state, *batch)
        losses[bool(extra)] = float(metrics["loss"])
    assert losses[True] == pytest.approx(losses[False], rel=1e-4), losses


def test_loss_chunk_trains_with_remat_attn():
    """--loss-chunk composes with --remat --remat-policy attn (the
    32k-context configuration) and the loss descends."""
    from tpu_operator.payload import bootstrap, transformer

    args = _lm_args(["--loss-chunk", "32", "--remat",
                     "--remat-policy", "attn", "--steps", "20",
                     "--log-every", "0"])
    info = bootstrap.ProcessInfo("", 0, 1, 0, ())
    metrics = transformer.run(info, args)
    assert np.isfinite(metrics["loss"])
    assert metrics["loss"] < 5.6  # ln(256) = 5.545; synthetic stream learns


def test_loss_chunk_rejects_sequence_parallel():
    from tpu_operator.payload import transformer

    args = _lm_args(["--loss-chunk", "32", "--seq-parallel", "2"])
    with pytest.raises(ValueError, match="seq-parallel"):
        transformer.build(args)


def test_loss_chunk_must_divide_seq_len():
    from tpu_operator.payload import transformer

    args = _lm_args(["--loss-chunk", "48"])
    with pytest.raises(ValueError, match="divide"):
        transformer.build(args)
