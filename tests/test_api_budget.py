"""API-call budget conformance: the control-plane cost contract.

The reference reconciled by interrogating the apiserver per replica index
(one Service GET + ~3 pod LISTs per index per pass — replicas.go:400-478,
481-535, 538-568), so reconcile cost scaled O(N) in *reads*. The
cache-backed redesign (informer indexers + per-reconcile ReplicaSnapshot)
pins a hard budget instead, enforced here through a call-counting shim
wrapped around the clientset:

(a) steady-state reconcile of a Running N-replica job issues ZERO read
    RPCs and zero writes beyond (at most) the status PUT;
(b) the first reconcile issues exactly N pod creates + N+1 service creates
    (per-index Services + the job-scoped headless Service) and no child
    reads at all;
(c) a stale informer cache that misses an existing Service produces a
    duplicate create answered 409 AlreadyExists — absorbed as benign, not
    surfaced as a reconcile error.

These are the budgets `bench.py --suite`'s control-plane rows measure;
hack/verify.sh gates this file standalone so a reads-per-reconcile
regression fails CI by name.
"""

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import Listers, Store, add_child_indexes
from tpu_operator.controller.events import EventRecorder
from tpu_operator.trainer import replicas as replicas_mod
from tpu_operator.trainer.training import TrainingJob
from tests.test_types import make_template

READ_VERBS = frozenset({"get", "list", "list_with_version", "watch"})
WRITE_VERBS = frozenset({"create", "update", "update_status", "delete",
                         "delete_collection"})


class CountingResourceClient:
    """Pass-through proxy recording every (verb, kind) that reaches the
    wrapped resource client."""

    def __init__(self, inner, calls):
        self._inner = inner
        self._calls = calls

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name in READ_VERBS or name in WRITE_VERBS:
            def wrapper(*args, **kwargs):
                self._calls.append((name, self._inner.kind))
                return attr(*args, **kwargs)
            return wrapper
        return attr


class CountingClientset:
    """The call-counting shim: wraps every resource client of a clientset
    so a test can assert exact API budgets. ``calls`` is the flat
    (verb, kind) ledger; non-resource attributes pass through."""

    RESOURCES = ("pods", "services", "events", "endpoints", "configmaps",
                 "leases", "tpujobs")

    def __init__(self, inner):
        self._inner = inner
        self.calls = []
        for resource in self.RESOURCES:
            setattr(self, resource,
                    CountingResourceClient(getattr(inner, resource),
                                           self.calls))

    def __getattr__(self, name):
        return getattr(self._inner, name)

    # -- ledger queries -------------------------------------------------------

    def reads(self, kinds=None):
        return [c for c in self.calls if c[0] in READ_VERBS
                and (kinds is None or c[1] in kinds)]

    def writes(self, kinds=None):
        return [c for c in self.calls if c[0] in WRITE_VERBS
                and (kinds is None or c[1] in kinds)]


# --- fixtures ----------------------------------------------------------------

def worker_job(replicas=4, name="budget"):
    return t.TPUJob(
        metadata={"name": name, "namespace": "default", "uid": "uid-b1"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=replicas, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER)
            ],
            runtime_id="r1d2",
            restart_backoff=t.RestartBackoffSpec(base_seconds=0),
        ),
    )


def make_listers():
    """Informer-shaped stores with the controller's child indexes, populated
    by hand (``sync_listers``) instead of watch threads — deterministic."""
    pods, services = Store(), Store()
    add_child_indexes(pods)
    add_child_indexes(services)
    return Listers(tpujobs=Store(), pods=pods, services=services)


def sync_listers(listers, cs, namespace="default"):
    """Simulate the watch catching up: mirror the fake's truth into the
    stores (reads go through the RAW fake, so the ledger stays clean)."""
    listers.tpujobs.replace(cs.tpujobs.list(namespace))
    listers.pods.replace(cs.pods.list(namespace))
    listers.services.replace(cs.services.list(namespace))


def cached_training_job(replicas=4):
    cs = FakeClientset()
    job = worker_job(replicas)
    cs.tpujobs.create("default", job.to_dict())
    counting = CountingClientset(cs)
    listers = make_listers()
    recorder = EventRecorder(counting)
    tj = TrainingJob(counting, recorder, job, listers=listers)
    sync_listers(listers, cs)
    return cs, counting, listers, tj


def all_running(cs):
    for p in cs.pods.list("default"):
        p["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        cs.pods.update("default", p)


# --- (b) first reconcile: exact create budget, zero reads --------------------

def test_first_reconcile_exact_create_budget():
    n = 4
    cs, counting, listers, tj = cached_training_job(replicas=n)
    tj.reconcile()

    assert counting.reads() == [], (
        f"first reconcile must be fully cache-served, saw {counting.reads()}")
    pod_writes = counting.writes(kinds={"Pod"})
    svc_writes = counting.writes(kinds={"Service"})
    assert pod_writes == [("create", "Pod")] * n
    assert svc_writes == [("create", "Service")] * (n + 1)
    # the only other writes are the job's own status/spec persistence
    # (and Events, which are observability, not reconcile I/O)
    other = [c for c in counting.writes()
             if c[1] not in ("Pod", "Service", "Event")]
    assert set(other) <= {("update", "TPUJob")}


# --- (a) steady state: zero reads, nothing beyond the status PUT -------------

def test_steady_state_reconcile_is_zero_rpc():
    n = 4
    cs, counting, listers, tj = cached_training_job(replicas=n)
    tj.reconcile()                      # creates the gang
    all_running(cs)                     # kubelet runs everything
    sync_listers(listers, cs)           # watch catches up
    tj.reconcile()                      # transitions to Running (status PUT)
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    sync_listers(listers, cs)

    counting.calls.clear()
    tj.reconcile()                      # steady state
    assert counting.reads() == [], (
        f"steady-state reconcile must issue zero read RPCs, "
        f"saw {counting.reads()}")
    writes = [c for c in counting.writes() if c[1] != "Event"]
    # status unchanged → not even the status PUT
    assert writes == [] or writes == [("update", "TPUJob")]

    # and it stays zero-RPC across repeated passes
    counting.calls.clear()
    for _ in range(5):
        tj.reconcile()
    assert counting.reads() == []
    assert [c for c in counting.writes() if c[1] != "Event"] == []


def test_steady_state_status_put_is_the_only_write_on_change():
    cs, counting, listers, tj = cached_training_job(replicas=2)
    tj.reconcile()
    all_running(cs)
    sync_listers(listers, cs)
    counting.calls.clear()
    tj.reconcile()                      # Creating → Running: one status PUT
    assert counting.reads() == []
    assert [c for c in counting.writes() if c[1] != "Event"] == [
        ("update", "TPUJob")]


# --- (c) stale cache → benign 409, not a reconcile error ---------------------

def test_stale_cache_duplicate_service_create_is_benign():
    n = 2
    cs, counting, listers, tj = cached_training_job(replicas=n)
    # The apiserver already holds index-0's Service AND the headless
    # Service (e.g. created moments ago, watch event still in flight) —
    # but the informer cache doesn't show them.
    job = tj.job
    idx0 = replicas_mod.gen_general_name(
        job.name, t.TPUReplicaType.WORKER, job.spec.runtime_id, 0)
    headless = replicas_mod.headless_service_name(job.name,
                                                  job.spec.runtime_id)
    for name in (idx0, headless):
        cs.services.create("default", {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name}, "spec": {}})
    listers.tpujobs.replace(cs.tpujobs.list("default"))  # job cached,
    # services deliberately NOT synced — the cache lags.

    tj.reconcile()  # must not raise: both 409s are absorbed

    svcs = {(s["metadata"] or {})["name"] for s in cs.services.list("default")}
    assert idx0 in svcs and headless in svcs
    assert len(svcs) == n + 1  # nothing duplicated, nothing missing
    # duplicate creates happened (and were answered 409, benignly)
    assert counting.writes(kinds={"Service"}).count(
        ("create", "Service")) == n + 1


def test_pending_expectations_arm_a_time_obligation():
    """While a create expectation is outstanding (pod created, cache hasn't
    shown it), the job must report a time obligation ~TTL away: if the pod
    dies before any watch event records it, no event will ever requeue the
    job (and resync no longer re-dispatches unchanged objects), so this
    wakeup is what guarantees the gang gets repaired."""
    import time as time_mod

    from tpu_operator.trainer import training as training_mod

    cs, counting, listers, tj = cached_training_job(replicas=2)
    tj.reconcile()              # creates pods; cache still lags
    assert tj._expected_pods
    ob = tj.next_time_obligation()
    assert ob is not None, "outstanding expectations must arm a wakeup"
    assert ob - time_mod.time() <= training_mod.EXPECTATION_TTL_SECONDS + 2

    # once the cache observes the pods, the expectations (and with them
    # the wakeup) go away
    sync_listers(listers, cs)
    tj.reconcile()
    assert not tj._expected_pods


def test_status_write_on_lagging_cache_never_reverts_persisted_spec():
    """Within one first reconcile, setup persists the generated runtimeId
    and the end-of-pass status write follows — while the job cache still
    holds the pre-setup object. The status write must base on our own last
    write, not the lagging cache: a cached base would full-object-PUT the
    old spec back, so an operator restart regenerates a different
    runtime_id and orphans every child already named with the first one."""
    cs = FakeClientset()
    job = t.TPUJob(
        metadata={"name": "spec-keep", "namespace": "default",
                  "uid": "uid-sk"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=2, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER)
            ],
            # no runtime_id: setup must generate and persist one
        ),
    )
    cs.tpujobs.create("default", job.to_dict())
    listers = make_listers()
    tj = TrainingJob(cs, EventRecorder(cs), job, listers=listers)
    sync_listers(listers, cs)  # cache snapshot BEFORE setup's spec write

    tj.reconcile()  # setup spec write, then the status write — no re-sync

    rid = tj.job_spec.runtime_id
    assert rid
    server_spec = cs.tpujobs.get("default", "spec-keep")["spec"]
    assert server_spec.get("runtimeId") == rid, (
        "status write based on the lagging cache reverted the persisted "
        "runtimeId")
    for pod in cs.pods.list("default"):
        assert rid in pod["metadata"]["name"]


def test_expectations_suppress_pod_recreate_on_stale_cache():
    """A pod created last pass but not yet visible in the cache must NOT be
    created again (pod names are random-suffixed, so a 409 can't save us —
    the in-flight create expectation does)."""
    n = 3
    cs, counting, listers, tj = cached_training_job(replicas=n)
    tj.reconcile()                      # creates n pods
    assert len(cs.pods.list("default")) == n
    # cache still shows ZERO pods (watch lagging); reconcile again
    counting.calls.clear()
    tj.reconcile()
    assert counting.writes(kinds={"Pod"}) == [], (
        "lagging cache must not double-create gang members")
    assert len(cs.pods.list("default")) == n
