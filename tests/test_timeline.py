"""Fleet observability plane: unified timelines, on-demand profiling,
and the fleet goodput rollup.

Unit half: the timeline store (bounds, lifecycle residue), the pure
assemblers (span ordering, Chrome export), the quantile helper, and the
fleet rollup math (cluster goodput must equal the fold of the per-job
``status.goodput`` folds by construction).

Integration half: the operator runs in-process against the HTTP test
apiserver (strict status-subresource schema — the new ``status.profile``
fields prove they pass admission), a simulated payload posts heartbeats
the way ``payload/heartbeat.py`` does, and the profile directive makes
the full round trip: ``tpujobctl profile`` annotation → reconcile admits
``status.profile`` Requested → heartbeat ACK carries the directive →
capture result folds back Captured with a ``ProfileCaptured`` event.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from tpu_operator.apis.tpujob.v1alpha1.types import PROFILE_ANNOTATION
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer, \
    _sanitize_profile
from tpu_operator.obs import timeline as timeline_mod
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import profile as profile_mod
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.util import joblife, tracing

wait_for = make_wait_for(timeout=20.0, interval=0.05)


# --- timeline store ----------------------------------------------------------


def test_store_bounds_and_lifecycle_residue():
    store = timeline_mod.TimelineStore()
    for i in range(timeline_mod.EVENTS_PER_JOB_CAP + 50):
        store.record_event("default", "tl", "Normal", "Tick", f"m{i}")
    events = store.events("default", "tl")
    assert len(events) == timeline_mod.EVENTS_PER_JOB_CAP
    # Oldest rotated out, newest kept.
    assert events[-1]["message"] == f"m{timeline_mod.EVENTS_PER_JOB_CAP + 49}"
    assert store.job_count() == 1
    # The PR-15 lifecycle contract: after the deletion prune the witness
    # must see zero residue for the job's identity tokens.
    store.forget_job("default", "tl")
    assert store.job_count() == 0
    assert joblife.residuals([("default", "tl")]) == []
    assert store.events("default", "tl") == []
    store.forget_job("default", "never-seen")  # prune is idempotent


def test_store_events_carry_reconcile_trace_id():
    store = timeline_mod.TimelineStore()
    with tracing.span("reconcile", key="default/tr"):
        store.record_event("default", "tr", "Normal", "Admitted", "go")
    (event,) = store.events("default", "tr")
    assert event["traceId"]
    # The id is the cross-reference into /api/traces?job=<ns>/<name>.
    spans = tracing.recent_spans(10)
    assert any(s["traceId"] == event["traceId"] for s in spans)


# --- assembly ----------------------------------------------------------------


def _rich_status():
    return {
        "phase": "Running",
        "phaseTimeline": {
            "Queued": "2026-08-06T10:00:00Z",
            "Creating": "2026-08-06T10:00:05Z",
            "Running": "2026-08-06T10:00:30Z",
        },
        "failures": [{"attempt": 0, "kind": "preemption",
                      "reason": "spot reclaim",
                      "time": "2026-08-06T10:05:00Z",
                      "resumeStep": 90, "worldSlices": 2,
                      "lostSteps": 10}],
        "startup": {"attempt": 1, "time": "2026-08-06T10:06:00Z",
                    "rendezvousSeconds": 2.0, "restoreSeconds": 3.0,
                    "compileSeconds": 10.0, "firstStepSeconds": 1.0,
                    "cacheHit": True},
        "stepTiming": {"attempt": 1, "time": "2026-08-06T10:07:00Z",
                       "steps": 50, "stepP50Seconds": 0.1,
                       "stepP95Seconds": 0.12, "stepMaxSeconds": 0.2},
        "elastic": {"slices": 2, "attempt": 1, "resizes": 1,
                    "lastResizeDirection": "down",
                    "time": "2026-08-06T10:06:30Z",
                    "remediations": [{"attempt": 1, "processId": 3,
                                      "policy": "shed",
                                      "time": "2026-08-06T10:08:00Z"}]},
        "store": {"lastUploadedStep": 100,
                  "time": "2026-08-06T10:08:30Z"},
        "profile": {"id": "abc", "state": "Captured", "steps": 8,
                    "capturedSteps": 8, "time": "2026-08-06T10:09:00Z",
                    "artifactKey": "artifacts/profile-abc.json"},
        "goodput": {"ratio": 0.91, "usefulStepSeconds": 91.0,
                    "wallclockSeconds": 100.0},
        "scheduling": {"queue": "batch", "priority": 5},
    }


def test_assemble_timeline_merges_every_signal_in_order():
    events = [{"time": "2026-08-06T10:00:04Z", "type": "Normal",
               "reason": "Admitted", "message": "queue batch",
               "traceId": "t1"}]
    tl = timeline_mod.assemble_timeline("default", "rich", _rich_status(),
                                        events)
    assert tl["job"] == "default/rich"
    assert tl["phase"] == "Running"
    spans = tl["spans"]
    starts = [s["start"] for s in spans]
    assert starts == sorted(starts)
    kinds = {s["kind"] for s in spans}
    assert {"phase", "decision", "failure", "startup", "steps",
            "elastic", "store", "profile"} <= kinds
    # The ledger span carries the restart's audit trail.
    (ledger,) = [s for s in spans if s["kind"] == "failure"]
    assert ledger["attrs"]["resumeStep"] == 90
    assert ledger["attrs"]["lostSteps"] == 10
    # The decision span carries its reconcile trace id.
    (decision,) = [s for s in spans if s["kind"] == "decision"]
    assert decision["traceId"] == "t1"
    # Phase spans: non-terminal phases have durations that chain.
    queued = next(s for s in spans if s["name"] == "phase:Queued")
    assert queued["durationSeconds"] == pytest.approx(5.0)
    # Elastic: both the resize and the remediation appear.
    elastic_names = {s["name"] for s in spans if s["kind"] == "elastic"}
    assert any(n.startswith("elastic:resize") for n in elastic_names)
    assert any(n.startswith("elastic:remediation") for n in elastic_names)


def test_chrome_export_is_perfetto_shaped():
    tl = timeline_mod.assemble_timeline("default", "rich", _rich_status(),
                                        [])
    trace = timeline_mod.to_chrome_trace(tl)
    # Must survive a JSON round trip (the CLI dumps it verbatim).
    parsed = json.loads(json.dumps(trace))
    phases = {e["ph"] for e in parsed}
    assert {"M", "X", "i"} <= phases
    names = {e["name"] for e in parsed if e["ph"] == "M"}
    assert "process_name" in names and "thread_name" in names
    for e in parsed:
        if e["ph"] == "X":
            assert e["dur"] >= 0 and isinstance(e["ts"], int)


def test_quantiles_nearest_rank():
    q = timeline_mod.quantiles([3.0, 1.0, 2.0, 4.0])
    assert q["count"] == 4
    assert q["p50"] == 2.0
    assert q["p95"] == 4.0
    single = timeline_mod.quantiles([7.5])
    assert single["p50"] == single["p95"] == 7.5


# --- fleet rollup ------------------------------------------------------------


def test_fleet_rollup_matches_per_job_goodput_fold():
    jobs = [
        {"namespace": "default", "name": "a", "status": {
            "phase": "Running",
            "goodput": {"usefulStepSeconds": 80.0,
                        "wallclockSeconds": 100.0, "ratio": 0.8,
                        "lastStep": 100},
            "lastHeartbeat": {"step": 100, "stepTimeSeconds": 0.5},
            "failures": [{"attempt": 0, "kind": "preemption",
                          "lostSteps": 20}],
            "checkpoint": {"lastCheckpointStep": 80},
            "scheduling": {"queue": "batch"},
            "stragglers": [{"processId": 1, "ratio": 1.7}],
            "elastic": {"remediations": [{"processId": 1}]},
        }},
        {"namespace": "default", "name": "b", "status": {
            "phase": "Queued",
            "goodput": {"usefulStepSeconds": 40.0,
                        "wallclockSeconds": 60.0, "ratio": 0.667},
            "scheduling": {"queue": "batch", "position": 0},
        }},
    ]
    rollup = timeline_mod.fleet_rollup(
        jobs, {"batch": {"p50": 1.0, "p95": 2.0, "count": 3}})
    # THE acceptance invariant: the cluster ratio is the fold of the
    # per-job folds — Σ useful / Σ wallclock, not an average of ratios.
    assert rollup["goodput"]["ratio"] == pytest.approx(120.0 / 160.0)
    assert rollup["preemption"]["restarts"] == 1
    assert rollup["preemption"]["lostSteps"] == 20
    # 20 lost steps × 0.5 s/step = 10 lost step-seconds.
    assert rollup["preemption"]["lostStepSeconds"] == pytest.approx(10.0)
    assert rollup["stragglers"] == {"flagged": 1, "remediations": 1}
    assert rollup["queues"]["batch"]["p95"] == 2.0
    rows = rollup["jobs"]
    assert [r["name"] for r in rows] == ["a", "b"]
    assert rows[0]["worstStragglerRatio"] == pytest.approx(1.7)
    assert rows[0]["lastDurableStep"] == 80
    assert rows[1]["queuePosition"] == 0
    # Empty fleet: well-formed zeros, not a division crash.
    empty = timeline_mod.fleet_rollup([])
    assert empty["goodput"]["ratio"] == 0.0 and empty["jobs"] == []


# --- heartbeat directive channel (payload side) ------------------------------


def test_reporter_takes_ack_directive_once_and_resends_result():
    acks = []
    posts = []

    def poster(_url, body):
        posts.append(body)
        return acks.pop(0) if acks else {"ok": True}

    r = heartbeat_mod.HeartbeatReporter("http://x:1", "j", poster=poster,
                                        clock=lambda: 0.0)
    acks.append({"ok": True, "profile": {"id": "p1", "steps": 4}})
    assert r.report(1, {"loss": 1.0})
    assert r.take_profile_directive() == {"id": "p1", "steps": 4}
    assert r.take_profile_directive() is None  # one-shot swap

    # The same directive id on a later ACK is deduplicated — re-delivery
    # while status.profile is still Requested must not restart a capture.
    acks.append({"ok": True, "profile": {"id": "p1", "steps": 4}})
    assert r.report(2, {"loss": 1.0})
    assert r.take_profile_directive() is None

    # The capture result rides every beat until a post succeeds.
    r.attach_profile_result({"id": "p1", "capturedSteps": 4})
    assert r.report(3, {"loss": 1.0})
    assert posts[-1]["profile"] == {"id": "p1", "capturedSteps": 4}
    assert r.report(4, {"loss": 1.0})
    assert "profile" not in posts[-1]  # cleared after the 200


def test_profile_capture_laps_and_artifact(tmp_path):
    cap = profile_mod.ProfileCapture({"id": "cap/1", "steps": 3},
                                     base_dir=str(tmp_path),
                                     allow_jax_trace=False)
    cap.start(completed_step=10)
    done = []
    for step in (11, 12, 13):
        done.append(cap.tick(step))
    assert done == [False, False, True]
    path, result = cap.finish()
    assert result["id"] == "cap/1" and result["capturedSteps"] == 3
    body = json.loads(open(path, encoding="utf-8").read())
    assert body["kind"] == profile_mod.ARTIFACT_KIND
    assert [row["step"] for row in body["steps"]] == [11, 12, 13]
    assert all(row["wallSeconds"] >= 0 for row in body["steps"])
    # Path-hostile directive ids are sanitized into the file name.
    assert "/" not in path.rsplit("profile-", 1)[1]


def test_sanitize_profile_rejects_garbage():
    clean, err = _sanitize_profile({"id": "p1", "capturedSteps": 4,
                                    "artifactKey": "artifacts/x.json"})
    assert not err and clean["capturedSteps"] == 4
    _clean, err = _sanitize_profile({"capturedSteps": 4})
    assert err  # id is mandatory
    _clean, err = _sanitize_profile({"id": "p1", "capturedSteps": -2})
    assert err
    _clean, err = _sanitize_profile("not a dict")
    assert err


# --- integration: operator + strict apiserver --------------------------------


def worker_job(name, replicas=1):
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicaSpecs": [{
            "replicas": replicas, "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu",
                                                  "image": "x"}]}}}]},
    }


def get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


@pytest.fixture()
def harness():
    tracing.clear_spans()
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop), daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


def _run_job(api, cs, name):
    cs.tpujobs.create("default", worker_job(name))
    assert wait_for(lambda: len(api.clientset.pods.list("default")) >= 1)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: cs.tpujobs.get("default", name)
                    .get("status", {}).get("phase") == "Running")


def test_timeline_endpoint_and_trace_filter(harness):
    api, cs, controller, server = harness
    _run_job(api, cs, "tljob")

    tl = json.loads(get(server.port, "/api/jobs/default/tljob/timeline"))
    assert tl["job"] == "default/tljob"
    spans = tl["spans"]
    assert spans, "a running job must have phase + decision spans"
    assert [s["start"] for s in spans] == sorted(s["start"] for s in spans)
    assert any(s["kind"] == "phase" for s in spans)
    decisions = [s for s in spans if s["kind"] == "decision"]
    assert any("SuccessfulCreate" in s["name"] for s in decisions)

    # Decision spans cross-reference the reconcile trace that caused
    # them, and ?job= filters /api/traces down to that job's traces.
    traced = [s for s in decisions if s.get("traceId")]
    assert traced
    body = json.loads(get(server.port,
                          "/api/traces?job=default/tljob&limit=500"))
    trace_ids = {s["traceId"] for s in body["spans"]}
    assert traced[0]["traceId"] in trace_ids
    other = json.loads(get(server.port,
                           "/api/traces?job=default/absent&limit=500"))
    assert other["spans"] == []

    # Chrome export over HTTP parses and carries the lane metadata.
    chrome = json.loads(get(
        server.port, "/api/jobs/default/tljob/timeline?format=chrome"))
    assert any(e["ph"] == "M" for e in chrome)

    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}"
            f"/api/jobs/default/absent/timeline", timeout=5)
    assert ei.value.code == 404


def test_fleet_endpoint_matches_status_goodput(harness):
    api, cs, controller, server = harness
    _run_job(api, cs, "fljob")

    reporter = heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "fljob", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": "0", "TPUJOB_ATTEMPT": "0",
    }, tokens_per_batch=64)
    assert reporter.report(10, {"loss": 1.0})
    assert wait_for(lambda: (cs.tpujobs.get("default", "fljob")
                             .get("status", {}).get("goodput")
                             or {}).get("ratio") is not None)

    status = cs.tpujobs.get("default", "fljob")["status"]
    fleet = json.loads(get(server.port, "/api/fleet"))
    (row,) = [j for j in fleet["jobs"] if j["name"] == "fljob"]
    # The acceptance invariant: the rollup's per-job ratio IS the
    # persisted status.goodput fold, and with one job the cluster ratio
    # must reduce to it.
    assert row["goodputRatio"] == status["goodput"]["ratio"]
    assert fleet["goodput"]["ratio"] == pytest.approx(
        min(1.0, status["goodput"]["usefulStepSeconds"]
            / status["goodput"]["wallclockSeconds"]), abs=1e-4)

    # The fleet metric families render alongside the rollup.
    body = get(server.port, "/metrics")
    assert "fleet_goodput_ratio" in body
    assert "fleet_preemption_lost_step_seconds" in body
    assert "fleet_straggler_count" in body
    assert "fleet_remediation_count" in body


def test_profile_directive_full_round_trip(harness):
    api, cs, controller, server = harness
    _run_job(api, cs, "prjob")

    # tpujobctl profile: stamp the directive annotation.
    job = cs.tpujobs.get("default", "prjob")
    job["metadata"].setdefault("annotations", {})[PROFILE_ANNOTATION] = \
        json.dumps({"id": "req-1", "steps": 4})
    cs.tpujobs.update("default", job)

    # Reconcile admits it: status.profile goes Requested (strict schema).
    assert wait_for(lambda: (cs.tpujobs.get("default", "prjob")
                             .get("status", {}).get("profile")
                             or {}).get("state") == "Requested")
    pr = cs.tpujobs.get("default", "prjob")["status"]["profile"]
    assert pr["id"] == "req-1" and pr["steps"] == 4

    # Process 0's next heartbeat ACK carries the directive...
    reporter = heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "prjob", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": "0", "TPUJOB_ATTEMPT": "0",
    }, tokens_per_batch=64)
    assert reporter.report(5, {"loss": 2.0})
    assert wait_for(lambda: reporter.take_profile_directive() is not None
                    or reporter.report(6, {"loss": 2.0}) is False)
    # ...but a non-zero process never receives it.
    cadence = heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "prjob", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": "1", "TPUJOB_ATTEMPT": "0",
    }, tokens_per_batch=64)
    assert cadence.report(5, None)
    assert cadence.take_profile_directive() is None

    # The capture result folds back: Captured + ProfileCaptured event.
    reporter.attach_profile_result({
        "id": "req-1", "capturedSteps": 4,
        "artifactKey": "artifacts/profile-req-1.json"})
    assert reporter.report(7, {"loss": 1.9})
    assert wait_for(lambda: (cs.tpujobs.get("default", "prjob")
                             .get("status", {}).get("profile")
                             or {}).get("state") == "Captured")
    pr = cs.tpujobs.get("default", "prjob")["status"]["profile"]
    assert pr["capturedSteps"] == 4
    assert pr["artifactKey"] == "artifacts/profile-req-1.json"
    events = api.clientset.events.list("default")
    assert any(e.get("reason") == "ProfileRequested" for e in events)
    assert any(e.get("reason") == "ProfileCaptured" for e in events)

    # The profile span joins the unified timeline.
    tl = json.loads(get(server.port, "/api/jobs/default/prjob/timeline"))
    assert any(s["kind"] == "profile" for s in tl["spans"])

    # Once Captured, the directive stops riding ACKs (one-shot).
    fresh = heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "prjob", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": "0", "TPUJOB_ATTEMPT": "0",
    }, tokens_per_batch=64)
    assert fresh.report(8, {"loss": 1.8})
    assert fresh.take_profile_directive() is None


# --- tpujobctl ---------------------------------------------------------------


def test_top_column_contract(monkeypatch, capsys):
    # The column set is an interface: scripts parse it. Pin it.
    assert ctl.TOP_COLUMNS == ["NAME", "PHASE", "QUEUE", "POS", "GOODPUT",
                               "STRAGGLER", "DURABLE", "STEP", "RESTARTS"]
    fleet = timeline_mod.fleet_rollup([
        {"namespace": "default", "name": "a", "status": {
            "phase": "Running",
            "goodput": {"ratio": 0.9, "usefulStepSeconds": 90.0,
                        "wallclockSeconds": 100.0, "lastStep": 120},
            "checkpoint": {"lastCheckpointStep": 100},
            "scheduling": {"queue": "batch"},
        }},
    ], {"batch": {"p50": 1.0, "p95": 2.0, "count": 3}})
    monkeypatch.setattr(ctl, "_status_get", lambda _o, _p: fleet)
    opts = ctl.build_parser().parse_args(["top"])
    assert ctl.cmd_top(None, opts) == 0
    out = capsys.readouterr().out
    header = next(line for line in out.splitlines()
                  if line.startswith("NAME"))
    assert header.split() == ctl.TOP_COLUMNS
    assert "default/a" in out and "90.0%" in out and "batch" in out
    assert "Fleet: goodput" in out


def test_ctl_timeline_renders_table_and_chrome(monkeypatch, capsys):
    tl = timeline_mod.assemble_timeline("default", "rich", _rich_status(),
                                        [])
    monkeypatch.setattr(
        ctl, "_status_get",
        lambda _o, path: (timeline_mod.to_chrome_trace(tl)
                          if "format=chrome" in path else tl))
    opts = ctl.build_parser().parse_args(["timeline", "rich"])
    assert ctl.cmd_timeline(None, opts) == 0
    out = capsys.readouterr().out
    assert "Timeline: default/rich" in out
    assert "phase:Running" in out
    opts = ctl.build_parser().parse_args(["timeline", "rich", "--chrome"])
    assert ctl.cmd_timeline(None, opts) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert any(e["ph"] == "X" for e in parsed)


def test_ctl_profile_stamps_annotation(harness):
    api, cs, controller, server = harness
    _run_job(api, cs, "ctlprof")
    opts = ctl.build_parser().parse_args(
        ["profile", "ctlprof", "--steps", "6"])
    opts.namespace = "default"
    assert ctl.cmd_profile(cs, opts) == 0
    raw = cs.tpujobs.get("default", "ctlprof")["metadata"][
        "annotations"][PROFILE_ANNOTATION]
    directive = json.loads(raw)
    assert directive["steps"] == 6 and directive["id"]
    assert wait_for(lambda: (cs.tpujobs.get("default", "ctlprof")
                             .get("status", {}).get("profile")
                             or {}).get("state") == "Requested")
