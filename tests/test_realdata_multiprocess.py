"""Per-process real-data sharding (VERDICT round-3 item 7).

Round 3's token_file_lm materialized the FULL global batch on every
process — N× the mmap reads a job needs. data.local_batch_rows now gives
each process its contiguous global-row range from the batch sharding's
own device→index map, and token_file_lm fills only those rows. This test
runs a real 2-process CPU jax.distributed group (tests/realdata_worker.py)
training from one shared token file and asserts:

- the two processes' materialized row ranges are disjoint and cover the
  global batch;
- both processes observe the identical (allreduced) loss sequence;
- that sequence equals a single-process run of the same config on the
  same file — the sharded-read path changes I/O, not training.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "realdata_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_processes_read_disjoint_rows_and_match_single_process(tmp_path):
    rng = np.random.default_rng(7)
    token_path = str(tmp_path / "tokens.npy")
    np.save(token_path, rng.integers(0, 128, size=40_000, dtype=np.uint16))
    out_dir = tmp_path / "out"
    out_dir.mkdir()
    port = _free_port()

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PALLAS_AXON_POOL_IPS"] = ""

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), "2",
             token_path, str(out_dir)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO)
        for pid in range(2)
    ]
    try:
        deadline = time.time() + 180
        for p in procs:
            p.wait(timeout=max(5, deadline - time.time()))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p in procs:
        assert p.returncode == 0, p.stdout.read()

    recs = [json.load(open(out_dir / f"{pid}.json")) for pid in range(2)]
    ranges = [tuple(r["rows"]) for r in recs]
    assert all(r is not None for r in ranges)
    # disjoint, covering [0, 4)
    (lo0, hi0), (lo1, hi1) = sorted(ranges)
    assert hi0 <= lo1 and lo0 == 0 and hi1 == 4, ranges
    assert (hi0 - lo0) + (hi1 - lo1) == 4, ranges
    # identical allreduced losses on both processes
    np.testing.assert_allclose(recs[0]["losses"], recs[1]["losses"],
                               rtol=1e-6)

    # single-process reference on the same file: same mesh shape (data=2),
    # same batches — the sharded-read path must not change training.
    from tpu_operator.payload import data as data_mod, transformer

    import jax

    args = transformer.parse_args(
        ["--batch", "4", "--seq-len", "64", "--dim", "32", "--heads", "2",
         "--layers", "1", "--vocab", "128", "--data", token_path,
         "--lr", "1e-2"])
    mesh = transformer.make_lm_mesh(2, devices=jax.devices()[:2])
    mesh, _m, state, step, batches = transformer.build(args, mesh=mesh)
    spec = transformer.lm_token_spec(mesh)
    ref = []
    it = iter(batches)
    for _ in range(3):
        arrays = data_mod.put_global_batch(mesh, *next(it), spec=spec)
        state, metrics = step(state, *arrays)
        ref.append(float(jax.device_get(metrics["loss"])))
    np.testing.assert_allclose(recs[0]["losses"], ref, rtol=2e-5)


def test_local_batch_rows_single_process_is_none():
    from tpu_operator.payload import data as data_mod, transformer

    mesh = transformer.make_lm_mesh(8)
    assert data_mod.local_batch_rows(mesh, 8, 64) is None


def test_token_file_lm_local_rows_fills_only_local_rows(tmp_path):
    """Unit: rows outside local_rows stay zero (placeholders), rows inside
    match the full-read stream exactly."""
    from tpu_operator.payload import data as data_mod

    rng = np.random.default_rng(3)
    path = str(tmp_path / "t.npy")
    np.save(path, rng.integers(1, 100, size=4096, dtype=np.uint16))
    full = data_mod.token_file_lm(path, seed=5, batch=4, seq_len=32)
    part = data_mod.token_file_lm(path, seed=5, batch=4, seq_len=32,
                                  local_rows=(1, 3))
    for _ in range(3):
        (f,) = next(full)
        (p,) = next(part)
        np.testing.assert_array_equal(p[1:3], f[1:3])
        assert (p[0] == 0).all() and (p[3] == 0).all()
        assert (f[0] != 0).any()
