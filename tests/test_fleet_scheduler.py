"""Fleet-scheduler tests: slice-inventory admission, fair-share +
priority ordering, preemption victim selection, inventory release on
teardown/TTL, rebuild-from-cache after operator restart, shard affinity,
and the status-writeback rate limiter.

The e2e at the bottom is the acceptance flow: a higher-priority job
preempts a lower-priority one over the full controller loop (informers →
sharded workqueue → reconcile), acquires its slice, and the victim
requeues and finishes.
"""

import threading
import time

import pytest

from tpu_operator.apis.tpujob import validation
from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.events import EventRecorder
from tpu_operator.controller.statusserver import Metrics
from tpu_operator.scheduler.fleet import FleetScheduler
from tpu_operator.scheduler.inventory import (
    SliceInventory,
    job_demand,
    slice_key,
)
from tpu_operator.scheduler.sharding import ShardedWorkQueue
from tpu_operator.scheduler.writeback import WritebackLimiter
from tpu_operator.trainer.training import TrainingJob
from tpu_operator.testing.waiting import make_wait_for
from tests.test_types import make_template

V4 = "cloud-tpus.google.com/v4"
KEY = slice_key(V4, "2x2x2")


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=5.0, interval=0.02)


def tpu_job(name="fleet", replicas=1, priority=0, queue="default",
            chips=4, uid=None, **spec_kw):
    """A WORKER job whose gang demands one 2x2x2 slice of v4."""
    spec_kw.setdefault("restart_backoff",
                       t.RestartBackoffSpec(base_seconds=0))
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=replicas,
            template=make_template(tpu_chips=chips),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="fl33",
        tpu_topology="2x2x2",
        scheduling=t.SchedulingSpec(priority=priority, queue=queue),
        **spec_kw,
    )
    return t.TPUJob(metadata={"name": name, "namespace": "default",
                              "uid": uid or f"uid-{name}"}, spec=spec)


# --- spec plumbing (types/schema/defaults/validation round-trip) -------------

def test_scheduling_spec_roundtrip():
    job = tpu_job(priority=7, queue="research")
    wire = job.to_dict()
    assert wire["spec"]["scheduling"] == {"priority": 7, "queue": "research"}
    back = t.TPUJob.from_dict(wire)
    assert back.spec.scheduling.priority == 7
    assert back.spec.scheduling.queue == "research"
    # Absent block stays absent (specs round-trip unchanged).
    bare = t.TPUJobSpec.from_dict({"replicaSpecs": []})
    assert bare.scheduling is None
    assert "scheduling" not in bare.to_dict()


def test_scheduling_strict_schema():
    job = tpu_job(priority=3, queue="batch")
    set_defaults(job.spec)
    ok, msg = schema_mod.validate_tpujob_strict(job.to_dict())
    assert ok, msg
    # Phase Queued + status.scheduling admit through the status schema.
    job.status.phase = t.TPUJobPhase.QUEUED
    job.status.scheduling = {"queue": "batch", "priority": 3, "position": 4}
    ok, msg = schema_mod.validate_tpujob_strict(job.to_dict())
    assert ok, msg
    # Unknown scheduling field rejected (the typo-catching contract).
    wire = job.to_dict()
    wire["spec"]["scheduling"]["prio"] = 1
    ok, msg = schema_mod.validate_tpujob_strict(wire)
    assert not ok and "prio" in msg


def test_scheduling_defaults_and_validation():
    job = tpu_job()
    job.spec.scheduling = t.SchedulingSpec(priority=5, queue="")
    set_defaults(job.spec)
    assert job.spec.scheduling.queue == t.DEFAULT_SCHEDULING_QUEUE
    validation.validate_tpujob_spec(job.spec)

    job.spec.scheduling = t.SchedulingSpec(
        priority=t.MAX_SCHEDULING_PRIORITY + 1)
    with pytest.raises(validation.ValidationError, match="priority"):
        validation.validate_tpujob_spec(job.spec)
    job.spec.scheduling = t.SchedulingSpec(queue="q" * 64)
    with pytest.raises(validation.ValidationError, match="queue"):
        validation.validate_tpujob_spec(job.spec)


# --- inventory model ---------------------------------------------------------

def test_job_demand_derivation():
    job = tpu_job(chips=4)
    job.spec.num_slices = 2
    assert job_demand(job.spec) == (KEY, 2)
    # No TPU request anywhere → zero-footprint → None (never queued).
    cpu = t.TPUJobSpec(replica_specs=[t.TPUReplicaSpec(
        template=make_template())])
    assert job_demand(cpu) is None


def test_inventory_accounting_and_unmodeled_keys():
    inv = SliceInventory({KEY: 2})
    assert inv.fits(KEY, 2) and not inv.fits(KEY, 3)
    inv.reserve(KEY, 2)
    assert inv.free(KEY) == 0 and not inv.fits(KEY, 1)
    inv.release(KEY, 1)
    assert inv.fits(KEY, 1)
    # Unmodeled key: always fits, never tracked (a config typo must not
    # queue a job forever).
    other = slice_key(V4, "4x4x4")
    assert inv.fits(other, 99)
    inv.reserve(other, 99)
    assert inv.fits(other, 99)
    # Empty inventory = no admission control at all.
    assert SliceInventory().empty and SliceInventory().fits(KEY, 10)


def test_inventory_from_node_objects():
    def node(name, sid=None, topology="2x2x2"):
        labels = {"cloud.google.com/gke-tpu-topology": topology}
        if sid:
            labels["tpuoperator.dev/slice-id"] = sid
        return {"metadata": {"name": name, "labels": labels},
                "status": {"allocatable": {V4: "4", "cpu": "8"}}}

    inv = SliceInventory.from_node_objects([
        node("a0", "slice-a"), node("a1", "slice-a"),  # one 2-host slice
        node("b0", "slice-b"),
        node("solo"),                                  # its own slice
        {"metadata": {"name": "cpu-node"},
         "status": {"allocatable": {"cpu": "8"}}},     # not TPU: ignored
    ])
    assert inv.snapshot()[KEY]["capacity"] == 3


# --- live node-informer inventory (capacity changes without restart) ---------

def test_update_inventory_admits_queued_job_and_preserves_usage():
    s, wakes = sched(capacity=1)
    assert offer(s, "a")
    assert not offer(s, "b")
    # A node pool came up: capacity 1 → 2. The queued job admits and its
    # reconcile is woken — no operator restart, no release needed.
    s.update_inventory({KEY: 2})
    assert s.is_admitted("default/b")
    assert "default/b" in wakes
    # Reservations survived the swap: nothing fits a third gang.
    assert not offer(s, "c")
    # Shrink BELOW usage: honest over-commit (the gangs physically run);
    # drains as they release, and no new admission meanwhile.
    s.update_inventory({KEY: 1})
    assert s.summary()["inventory"][KEY] == {"capacity": 1, "used": 2}
    assert not offer(s, "d")
    s.release("default/a")
    assert not s.is_admitted("default/d")  # still over capacity
    s.release("default/b")
    # The drain frees the single modeled slot; FIFO hands it to the
    # earliest-queued waiter (c, parked since before the shrink).
    assert s.is_admitted("default/c")
    assert not s.is_admitted("default/d")


def test_update_inventory_unsidelines_impossible_demand():
    s, wakes = sched(capacity=1)
    # Demands 3 slices of a 1-slice shape: sidelined as unschedulable
    # (must not head-block the shape), with the reason exposed.
    assert not offer(s, "big", slices=3)
    assert s.unschedulable_reason("default/big")
    # A small same-shape job is NOT blocked by the sidelined head.
    assert offer(s, "small")
    # The node pool grew: the old verdict no longer holds — the job
    # un-sidelines, and admits once capacity actually frees.
    s.update_inventory({KEY: 4})
    assert s.unschedulable_reason("default/big") is None
    assert s.is_admitted("default/big")


def test_node_watch_updates_admission_live():
    """ROADMAP item 1 follow-on, end to end over the real informer loop:
    with --discover-slice-inventory the capacity model follows the node
    watch, so a node pool scaling up admits a queued gang — and
    rebalances the queue — with the operator NEVER restarting."""
    cs = FakeClientset()

    def node(name, sid):
        return {"metadata": {"name": name, "labels": {
            "cloud.google.com/gke-tpu-topology": "2x2x2",
            "tpuoperator.dev/slice-id": sid}},
            "status": {"allocatable": {V4: "4"}}}

    cs.nodes.create("", node("n1", "slice-a"))
    cs.tpujobs.create("default", tpu_job("first").to_dict())
    cs.tpujobs.create("default", tpu_job("second").to_dict())

    factory = SharedInformerFactory(cs, resync_period=0)
    config = t.ControllerConfig(discover_slice_inventory=True)
    controller = Controller(cs, factory, config, shards=2)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()
    try:
        # One discovered slice: exactly one of the two jobs admits.
        assert wait_for(lambda: sorted(
            phase_of(cs, n) for n in ("first", "second"))
            == ["Creating", "Queued"])
        queued = ("first" if phase_of(cs, "first") == "Queued"
                  else "second")
        # The pool scales up — the queued gang admits off the node event.
        cs.nodes.create("", node("n2", "slice-b"))
        assert wait_for(lambda: phase_of(cs, queued) == "Creating")
        assert wait_for(lambda: any(
            queued in p["metadata"]["name"]
            for p in cs.pods.list("default")))
    finally:
        stop.set()
        runner.join(timeout=5.0)


def test_node_not_ready_transition_shrinks_inventory_live():
    """Satellite of the kubelet layer: a node's Ready condition flipping
    False must flow node informer → discovery (which skips NotReady
    nodes) → FleetScheduler capacity, live; flipping back restores it.
    Debounce is disabled here — the flap-absorption behavior has its own
    regression in tests/test_fake_cluster.py."""
    cs = FakeClientset()

    def node(name, sid, ready=True):
        return {"metadata": {"name": name, "labels": {
            "cloud.google.com/gke-tpu-topology": "2x2x2",
            "tpuoperator.dev/slice-id": sid}},
            "status": {"allocatable": {V4: "4"},
                       "conditions": [{"type": "Ready",
                                       "status": "True" if ready
                                       else "False"}]}}

    cs.nodes.create("", node("n1", "slice-a"))
    cs.nodes.create("", node("n2", "slice-b"))

    factory = SharedInformerFactory(cs, resync_period=0)
    config = t.ControllerConfig(discover_slice_inventory=True,
                                node_debounce_seconds=0.0)
    controller = Controller(cs, factory, config, shards=1)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(1, stop),
                              daemon=True)
    runner.start()

    def capacity():
        return (controller.scheduler.summary()["inventory"]
                .get(KEY, {}).get("capacity"))

    try:
        assert wait_for(lambda: capacity() == 2)
        # Kubelet heartbeat lost: NotReady drops the slice from the model.
        cs.nodes.update_status("", node("n2", "slice-b", ready=False))
        assert wait_for(lambda: capacity() == 1)
        # Recovery is immediate (growth is never debounced).
        cs.nodes.update_status("", node("n2", "slice-b", ready=True))
        assert wait_for(lambda: capacity() == 2)
        # Node DELETED events shrink the same way (drain storms).
        cs.nodes.delete("", "n1")
        assert wait_for(lambda: capacity() == 1)
    finally:
        stop.set()
        runner.join(timeout=5.0)


# --- admission queue ordering ------------------------------------------------

def sched(capacity=1, metrics=None, clock=time.time):
    wakes = []
    s = FleetScheduler(SliceInventory({KEY: capacity}),
                       enqueue=wakes.append, metrics=metrics, clock=clock)
    return s, wakes


def offer(s, name, priority=0, queue="default", slices=1, uid=None):
    return s.ensure_admitted(f"default/{name}", uid=uid or f"uid-{name}",
                             demand=(KEY, slices), priority=priority,
                             queue=queue)


def test_admission_capacity_and_release_wakeup():
    s, wakes = sched(capacity=2)
    assert offer(s, "a") and offer(s, "b")
    assert not offer(s, "c")
    assert s.queue_position("default/c") == 0
    s.release("default/a")
    # c admitted off the freed slice, and its reconcile woken.
    assert "default/c" in wakes
    assert s.is_admitted("default/c")
    assert offer(s, "c")  # idempotent fast path


def test_queued_head_reexamination_under_mass_release():
    """Named scale-risk regression (ISSUE 17): when a storm releases many
    admitted gangs at once (mass preemption, churn teardown), EVERY freed
    slice must re-admit from the queue head in the same pass, and every
    newly admitted key must be woken through the enqueue callback — a
    fresh add, not a rate-limited requeue, so the admission is not parked
    behind the workqueue's 10 s per-item backoff tail."""
    s, wakes = sched(capacity=4)
    admitted = ["a", "b", "c", "d"]
    parked = ["e", "f", "g", "h"]
    for name in admitted:
        assert offer(s, name)
    for name in parked:
        assert not offer(s, name)
    assert s.summary()["pending"] == 4
    wakes.clear()
    for name in admitted:
        s.release(f"default/{name}")
    # One release at a time, but the whole parked head drained: nothing
    # waits for a resync or a second release to be re-examined.
    assert all(s.is_admitted(f"default/{n}") for n in parked), s.summary()
    assert s.summary()["pending"] == 0
    assert {f"default/{n}" for n in parked} <= set(wakes)


def test_priority_orders_admission():
    s, _ = sched(capacity=1)
    assert offer(s, "low", priority=0)
    assert not offer(s, "mid", priority=5)
    assert not offer(s, "high", priority=10)  # preempts low (marked)
    # Queue order is priority-desc: high ahead of mid.
    assert s.queue_position("default/high") == 0
    assert s.queue_position("default/mid") == 1


def test_fair_share_across_queues():
    s, wakes = sched(capacity=2)
    # Queue "a" holds both slices; pending: one from each queue, same
    # priority, "a"'s arrived first.
    assert offer(s, "a1", queue="a") and offer(s, "a2", queue="a")
    assert not offer(s, "a3", queue="a")
    assert not offer(s, "b1", queue="b")
    # Fair share: b (0 admitted slices) orders ahead of a (2) despite FIFO.
    assert s.queue_position("default/b1") == 0
    assert s.queue_position("default/a3") == 1
    s.release("default/a1")
    assert s.is_admitted("default/b1")
    assert not s.is_admitted("default/a3")


def test_preemption_victim_selection():
    s, wakes = sched(capacity=2)
    assert offer(s, "old-low", priority=1)
    assert offer(s, "new-low", priority=1)
    # Higher-priority arrival that cannot fit: the NEWEST of the
    # lowest-priority admitted jobs is marked, and its reconcile woken.
    assert not offer(s, "urgent", priority=10)
    assert "default/new-low" in wakes
    assert s.pop_eviction("default/old-low") is None  # not the victim
    reason = s.pop_eviction("default/new-low")
    assert reason and "default/urgent" in reason
    # The pop released the slice and admitted the urgent job.
    assert s.is_admitted("default/urgent")
    # No sufficient lower-priority set → no pointless eviction.
    assert not offer(s, "colossus", priority=99, slices=5)
    assert s.pop_eviction("default/old-low") is None
    assert s.pop_eviction("default/urgent") is None


def test_preemption_prefers_shrunk_victims_over_full_width():
    """Within a priority band, a gang running SHRUNK (admitted below its
    preferred size) is evicted before a full-width one — it is degraded
    already and its restart is billed to the infra budget either way —
    even when the full-width gang is the newer admission (the old
    newest-first rule would have picked it)."""
    s, _wakes = sched(capacity=3)
    # Elastic job granted 3 of its preferred 6: runs shrunk.
    assert s.ensure_admitted("default/sh", uid="uid-sh", demand=(KEY, 6),
                             min_slices=2)
    assert s.granted_slices("default/sh") == 3
    # Capacity returns (admitted sizes only change at attempt
    # boundaries, so sh stays shrunk) and a NEWER rigid full-width job
    # takes the freed slices.
    s.update_inventory({KEY: 6})
    assert offer(s, "full", slices=3)
    # Urgent arrival needing 3: the shrunk gang is the victim, not the
    # newest admission.
    assert not offer(s, "urgent", priority=10, slices=3)
    assert s.peek_eviction("default/full") is None
    reason = s.pop_eviction("default/sh")
    assert reason and "default/urgent" in reason
    assert s.is_admitted("default/urgent")
    assert s.is_admitted("default/full")


def test_preemption_spares_serve_fleet_at_min_replicas():
    """ISSUE 20's scheduler tail: a serving fleet already at its replica
    floor ranks as a WORSE victim than a training gang in the same
    priority band — even a SHRUNK training gang, and even though the
    at-min fleet itself reads as shrunk (scaled below its preferred
    maximum). Evicting the fleet takes live traffic capacity to zero;
    the training gang resumes from its checkpoint."""
    s, _wakes = sched(capacity=4)
    # A serve fleet scaled down to its minReplicas floor of 2 (preferred
    # maximum 4): shrunk by the old reading, at-min by the serve one.
    assert s.ensure_admitted("default/fleet", uid="uid-fleet",
                             demand=(KEY, 4), held_slices=2,
                             holds_hardware=True, serve=True,
                             serve_min_slices=2)
    assert s.granted_slices("default/fleet") == 2
    # A training gang running shrunk (granted 2 of preferred 6) — the
    # old shrunk-first rule alone would have ranked the fleet equal and
    # then evicted it as the NEWER admission.
    assert s.ensure_admitted("default/train", uid="uid-train",
                             demand=(KEY, 6), min_slices=2)
    assert s.granted_slices("default/train") == 2
    assert not offer(s, "urgent", priority=10, slices=2)
    assert s.peek_eviction("default/fleet") is None
    reason = s.pop_eviction("default/train")
    assert reason and "default/urgent" in reason
    assert s.is_admitted("default/fleet")
    assert s.is_admitted("default/urgent")


def test_preemption_serve_fleet_above_min_ranks_normally():
    """A serve fleet still ABOVE its floor has slack to give back, so it
    keeps the ordinary newest-first ranking — the at-min shield applies
    exactly when eviction would take the fleet dark."""
    s, _wakes = sched(capacity=4)
    assert s.ensure_admitted("default/train", uid="uid-train",
                             demand=(KEY, 2))
    # Fleet at 2 slices over a minReplicas floor of 1: not at-min, and
    # the newer admission — the ordinary victim.
    assert s.ensure_admitted("default/fleet", uid="uid-fleet",
                             demand=(KEY, 2), serve=True,
                             serve_min_slices=1)
    assert not offer(s, "urgent", priority=10, slices=2)
    assert s.peek_eviction("default/train") is None
    reason = s.pop_eviction("default/fleet")
    assert reason and "default/urgent" in reason


def test_serving_sched_kwargs_carries_serve_floor():
    """serving.sched_kwargs tags every serve job's scheduler entry with
    its minimum slice footprint: minReplicas for slice-per-replica
    fleets, the whole (fixed) footprint otherwise — the input the
    victim ranking's at-min shield reads."""
    from tpu_operator.trainer import serving as serving_mod

    job_spec = t.TPUJobSpec(replica_specs=[
        t.TPUReplicaSpec(replicas=4, template=make_template(),
                         tpu_port=t.DEFAULT_TPU_PORT,
                         tpu_replica_type=t.TPUReplicaType.WORKER)])
    job_spec.mode = t.JobMode.SERVE
    job_spec.num_slices = 4  # slice-per-replica: 4 workers, 4 slices
    job_spec.serving = t.ServingSpec(min_replicas=2, max_replicas=4)
    demand, kwargs = serving_mod.sched_kwargs(
        job_spec, {"replicas": 3}, (KEY, 4))
    assert demand == (KEY, 3)  # current scale, not the spec maximum
    assert kwargs == {"held_slices": 3, "serve": True,
                      "serve_min_slices": 2}
    # Fixed-footprint serve job (not slice-per-replica): always at its
    # floor — the whole demand is the minimum.
    job_spec.num_slices = 1
    demand, kwargs = serving_mod.sched_kwargs(
        job_spec, {"replicas": 3}, (KEY, 1))
    assert demand == (KEY, 1)
    assert kwargs == {"serve": True, "serve_min_slices": 1}
    # Non-serve jobs pass through untouched.
    job_spec.mode = t.JobMode.TRAIN
    assert serving_mod.sched_kwargs(job_spec, None, (KEY, 4)) \
        == ((KEY, 4), {})


def test_unfittable_head_blocks_only_its_own_shape():
    """A full v4 pool must not park v5e jobs whose own pool is free: the
    head-of-line block is per slice shape, not global."""
    other_key = slice_key(V4, "4x4x4")
    s = FleetScheduler(SliceInventory({KEY: 1, other_key: 1}))
    assert s.ensure_admitted("default/a", uid="u-a", demand=(KEY, 1))
    # Same-priority 1-slice job behind the held slice: queued (no victims
    # at equal priority), and it becomes the global order head.
    assert not s.ensure_admitted("default/blocked", uid="u-b",
                                 demand=(KEY, 1))
    # A job of the OTHER shape admits straight through.
    assert s.ensure_admitted("default/other", uid="u-o",
                             demand=(other_key, 1))
    # And a later same-shape arrival still queues BEHIND the head (the
    # anti-starvation property the per-shape block preserves).
    assert not s.ensure_admitted("default/later", uid="u-l",
                                 demand=(KEY, 1))
    s.release("default/a")
    assert s.is_admitted("default/blocked")
    assert not s.is_admitted("default/later")


def test_slice_inventory_config_rejects_nonpositive_counts():
    from tpu_operator.cmd.server import parse_slice_inventory

    assert parse_slice_inventory(f"{V4}:2x2x2=8") == {f"{V4}:2x2x2": 8}
    with pytest.raises(ValueError, match=">= 1"):
        parse_slice_inventory(f"{V4}:2x2x2=0")
    with pytest.raises(ValueError, match=">= 1"):
        t.ControllerConfig.from_dict({"sliceInventory": {KEY: -8}})
    # A colon-less key can never match any demand key: silent no-op entry.
    with pytest.raises(ValueError, match="topology"):
        parse_slice_inventory(f"{V4}=8")
    with pytest.raises(ValueError, match="topology"):
        t.ControllerConfig.from_dict({"sliceInventory": {V4: 8}})


def test_impossible_demand_sidelined_not_blocking():
    """numSlices past the shape's TOTAL capacity can never fit: it must
    not head-block every later same-shape job (silent cluster-wide
    starvation off one typo), and its status says 'unschedulable'."""
    s, _ = sched(capacity=2)
    assert not offer(s, "colossus", slices=5)
    reason = s.unschedulable_reason("default/colossus")
    assert reason and "exceeds" in reason
    # Later same-shape jobs flow right past it.
    assert offer(s, "small-a") and offer(s, "small-b")
    assert not offer(s, "small-c")  # genuinely waiting, not unschedulable
    assert s.unschedulable_reason("default/small-c") is None
    s.release("default/small-a")
    assert s.is_admitted("default/small-c")

    # TrainingJob surfaces the distinction in status.reason.
    cs, tj = fleet_training_job(tpu_job("huge", replicas=10, num_slices=10), s)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert "unschedulable" in tj.job.status.reason


def test_queue_wait_does_not_consume_deadline_before_first_start():
    """activeDeadlineSeconds measures runtime budget: a job that never
    ran must not be failed DeadlineExceeded off queue wait, and on first
    admission the lifecycle origin re-bases to the admission time."""
    import tpu_operator.trainer.training as training_mod

    s, _ = sched(capacity=1)
    assert offer(s, "holder")
    cs, tj = fleet_training_job(
        tpu_job("patient", active_deadline_seconds=60), s)
    t0 = "2026-08-04T00:00:00Z"
    late = "2026-08-04T02:00:00Z"  # 2h later — way past the 60s deadline
    old_now = training_mod._now
    try:
        training_mod._now = lambda: t0
        tj.reconcile()
        assert tj.job.status.phase == t.TPUJobPhase.QUEUED
        training_mod._now = lambda: late
        tj.reconcile()  # 2h queued: must NOT DeadlineExceeded
        assert tj.job.status.phase == t.TPUJobPhase.QUEUED
        s.release("default/holder")
        tj.reconcile()  # admitted now; deadline clock starts HERE
        assert tj.job.status.phase == t.TPUJobPhase.CREATING
        assert tj.job.status.phase_timeline[
            t.TPUJobPhase.CREATING] == late
    finally:
        training_mod._now = old_now


def test_stale_eviction_never_hits_same_name_successor():
    """An eviction directive is UID-scoped: aimed at a deleted job, it
    must not preempt (or bill) a re-created job of the same name."""
    s, _ = sched(capacity=1)
    assert offer(s, "phoenix", uid="uid-old")
    assert not offer(s, "urgent", priority=10)  # marks uid-old
    # The old job is deleted and re-created under the same name; its
    # release cleared nothing here (simulating the coalesced-watch path
    # where only ensure_admitted's new-UID branch runs).
    assert s.pop_eviction("default/phoenix", uid="uid-new") is None
    # The directive is consumed without touching the successor.
    assert s.pop_eviction("default/phoenix", uid="uid-new") is None


def test_preemption_not_doubled_while_in_flight():
    s, wakes = sched(capacity=1)
    assert offer(s, "low", priority=0)
    assert not offer(s, "high", priority=10)
    # Re-offering the blocked head must not mark a second victim (the
    # first eviction is still draining).
    assert not offer(s, "high", priority=10)
    assert wakes.count("default/low") == 1


def test_admission_metrics():
    m = Metrics()
    clock = [100.0]
    s = FleetScheduler(SliceInventory({KEY: 1}), metrics=m,
                       clock=lambda: clock[0])
    s.ensure_admitted("default/a", uid="u-a", demand=(KEY, 1))
    s.ensure_admitted("default/b", uid="u-b", demand=(KEY, 1))
    assert m.counter_value("tpujob_queue_depth",
                           {"queue": "default"}) == 1
    clock[0] += 30.0
    s.release("default/a")
    assert m.counter_value("tpujob_queue_depth",
                           {"queue": "default"}) == 0
    # Two observations: a's zero-wait admission (~0s) and b's 30s park.
    hist = m.histogram_snapshot("tpujob_admission_latency_seconds")
    assert hist["count"] == 2 and 29.0 < hist["sum"] < 31.0
    s.ensure_admitted("default/c", uid="u-c", demand=(KEY, 1), priority=9)
    s.pop_eviction("default/b")
    # tpujob_preemptions_total ticks at the TrainingJob's actual teardown
    # (a directive consumed by an already-succeeded gang is a no-op), so
    # the bare pop leaves it at zero — see the e2e preemption test for
    # the counted path.
    assert m.snapshot()["tpujob_preemptions_total"] == 0


# --- TrainingJob integration -------------------------------------------------

def fleet_training_job(job, scheduler, cs=None, writeback=None):
    cs = cs or FakeClientset()
    if cs.tpujobs.list("default") == []:
        pass
    try:
        cs.tpujobs.get(job.namespace, job.name)
    except Exception:
        cs.tpujobs.create(job.namespace, job.to_dict())
    tj = TrainingJob(cs, EventRecorder(cs), job, scheduler=scheduler,
                     writeback=writeback)
    return cs, tj


def mark_pods(cs, phase="Running", state=None):
    state = state if state is not None else {"running": {}}
    for pod in cs.pods.list("default"):
        pod["status"] = {"phase": phase, "containerStatuses": [
            {"name": "tpu", "state": state}]}
        cs.pods.update("default", pod)


def test_trainingjob_queues_then_admits():
    s, _ = sched(capacity=1)
    cs_a, tj_a = fleet_training_job(tpu_job("a"), s)
    tj_a.reconcile()
    assert tj_a.job.status.phase == t.TPUJobPhase.CREATING
    assert len(cs_a.pods.list("default")) == 1

    cs_b, tj_b = fleet_training_job(tpu_job("b"), s)
    tj_b.reconcile()
    assert tj_b.job.status.phase == t.TPUJobPhase.QUEUED
    assert cs_b.pods.list("default") == []  # no partial acquisition
    persisted = cs_b.tpujobs.get("default", "b")
    assert persisted["status"]["phase"] == "Queued"
    assert persisted["status"]["scheduling"]["position"] == 0
    events = [e["reason"] for e in cs_b.events.list("default")]
    assert "Queued" in events

    # a finishes → slice frees → b's next reconcile admits and gangs up.
    mark_pods(cs_a, "Succeeded", {"terminated": {"exitCode": 0}})
    tj_a.reconcile()
    assert tj_a.job.status.phase == t.TPUJobPhase.DONE
    tj_b.reconcile()
    assert tj_b.job.status.phase == t.TPUJobPhase.CREATING
    assert len(cs_b.pods.list("default")) == 1
    events = [e["reason"] for e in cs_b.events.list("default")]
    assert "Admitted" in events
    assert "position" not in (tj_b.job.status.scheduling or {})


def test_inventory_release_on_teardown_ttl_failure():
    # DONE releases (covered above); here: terminal failure, TTL reap,
    # suspension, and explicit delete.
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("f", max_restarts=0), s)
    tj.reconcile()
    assert s.is_admitted("default/f")
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 1}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.FAILED
    assert not s.is_admitted("default/f")
    assert s.summary()["inventory"][KEY]["used"] == 0

    cs2, tj2 = fleet_training_job(tpu_job("g"), s)
    tj2.reconcile()
    assert s.is_admitted("default/g")
    tj2.job.spec.suspend = True
    tj2.reconcile()
    assert tj2.job.status.phase == t.TPUJobPhase.SUSPENDED
    assert not s.is_admitted("default/g")  # suspension frees the slice
    tj2.job.spec.suspend = False
    tj2.reconcile()
    assert s.is_admitted("default/g")  # resume re-admits

    tj2.delete()
    assert not s.is_admitted("default/g")

    # TTL reap: a finished job with ttlSecondsAfterFinished=0 reaps on the
    # next pass and must release (belt to delete()'s braces).
    s3, _ = sched(capacity=1)
    cs3, tj3 = fleet_training_job(
        tpu_job("h", ttl_seconds_after_finished=0), s3)
    tj3.reconcile()
    mark_pods(cs3, "Succeeded", {"terminated": {"exitCode": 0}})
    tj3.reconcile()
    assert tj3.job.status.phase == t.TPUJobPhase.DONE
    tj3.reconcile()  # TTL pass
    assert tj3._reaped
    assert not s3.is_admitted("default/h")


def test_terminated_pods_do_not_count_as_held_hardware():
    """Resume-vs-retained-logs: terminated pods are kept for log
    inspection long after their slice freed, so a resumed (or rebuilt)
    job with only a finished pod in cache must go through the queue, not
    force-admit past a full inventory."""
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("a", replicas=2), s)
    tj.reconcile()
    # Worker 1 finishes (retained), worker 0 keeps running.
    pods = sorted(cs.pods.list("default"),
                  key=lambda p: p["metadata"]["name"])
    pods[1]["status"] = {"phase": "Succeeded", "containerStatuses": [
        {"name": "tpu", "state": {"terminated": {"exitCode": 0}}}]}
    cs.pods.update("default", pods[1])
    pods[0]["status"] = {"phase": "Running", "containerStatuses": [
        {"name": "tpu", "state": {"running": {}}}]}
    cs.pods.update("default", pods[0])
    tj.reconcile()

    tj.job.spec.suspend = True
    tj.reconcile()  # live pod deleted, Succeeded pod retained, slice freed
    assert not s.is_admitted("default/a")
    assert offer(s, "b")  # the freed slice goes to b

    tj.job.spec.suspend = False
    tj.reconcile()  # resume: only the retained terminated pod is in cache
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert s.summary()["inventory"][KEY]["used"] == 1  # never over-committed


def test_rebuild_from_cache_after_operator_restart():
    """No persisted scheduler state: a restarted operator re-learns the
    inventory from what the informer caches show already running."""
    s1, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("run"), s1)
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING

    # "Restart": fresh scheduler + fresh TrainingJob built from the
    # persisted object (the cache copy), same clientset state.
    s2, _ = sched(capacity=1)
    job2 = t.TPUJob.from_dict(cs.tpujobs.get("default", "run"))
    _, tj2 = fleet_training_job(job2, s2, cs=cs)
    tj2.reconcile()
    # Force-admitted (it holds hardware), capacity accounted...
    assert s2.is_admitted("default/run")
    assert s2.summary()["inventory"][KEY]["used"] == 1
    # ...so a new job correctly queues instead of over-admitting.
    _, tj3 = fleet_training_job(tpu_job("late"), s2)
    tj3.reconcile()
    assert tj3.job.status.phase == t.TPUJobPhase.QUEUED


def test_controller_restart_rebuilds_before_new_jobs_admit():
    """Operator restart with a fresh job racing in: the EAGER rebuild
    (Controller.run, post-cache-sync pre-workers) must account the old
    Running job's slice before any reconcile runs, or the newcomer is
    admitted into physically occupied capacity (caught by the kill -9
    e2e drive — the lazy per-reconcile force-admit alone loses the
    race)."""
    cs = FakeClientset()
    old = tpu_job("old")
    old.status.phase = t.TPUJobPhase.RUNNING
    old.status.state = t.State.RUNNING
    old.status.attempt = 0
    cs.tpujobs.create("default", old.to_dict())
    created = cs.tpujobs.get("default", "old")
    cs.pods.create("default", {
        "metadata": {"name": "old-worker-fl33-0", "labels": {
            "job_name": "old", "job_type": "worker", "task_index": "0",
            "attempt": "0"},
            "ownerReferences": [{"kind": "TPUJob", "controller": True,
                                 "uid": created["metadata"]["uid"],
                                 "name": "old"}]},
        "status": {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}})
    # The newcomer exists in the cache BEFORE the controller starts — the
    # worst ordering for a lazy rebuild.
    cs.tpujobs.create("default", tpu_job("newcomer").to_dict())

    factory = SharedInformerFactory(cs, resync_period=0)
    config = t.ControllerConfig(slice_inventory={KEY: 1})
    controller = Controller(cs, factory, config, shards=2)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()
    try:
        assert wait_for(lambda: phase_of(cs, "newcomer") == "Queued")
        assert controller.scheduler.is_admitted("default/old")
        assert not any("newcomer" in p["metadata"]["name"]
                       for p in cs.pods.list("default"))
    finally:
        stop.set()
        runner.join(timeout=5.0)


def test_trainingjob_preemption_requeue_budget():
    """An evicted job bills the preemption budget (4x maxRestarts), NOT
    the crash-loop budget, and re-queues with the reason in the ledger."""
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("victim", priority=0,
                                        max_restarts=3), s)
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING

    assert not offer(s, "urgent", priority=10)  # marks the victim
    tj.reconcile()  # pops the eviction
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert cs.pods.list("default") == []
    ledger = tj.job.status.failures
    assert ledger and ledger[-1].kind == t.FailureKind.PREEMPTION
    assert "urgent" in ledger[-1].reason
    assert tj.job.status.restart_counts == {t.FailureKind.PREEMPTION: 1}
    assert tj.job.status.attempt == 1
    events = [e["reason"] for e in cs.events.list("default")]
    assert "Preempted" in events
    # The victim re-entered the queue behind the preemptor.
    assert s.queue_position("default/victim") == 0
    assert s.is_admitted("default/urgent")


def test_eviction_skipped_for_already_succeeded_gang():
    """A victim whose chief already exited 0 is not torn down and re-run:
    the pop frees its reservation either way, and the reconcile rolls
    straight to Done instead of billing a pointless preemption."""
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("winner"), s)
    tj.reconcile()
    mark_pods(cs, "Succeeded", {"terminated": {"exitCode": 0}})
    # Eviction marked BEFORE the Done roll-up reconcile runs.
    assert not offer(s, "urgent", priority=10)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.DONE
    assert tj.job.status.failures == []  # no preemption billed
    assert s.is_admitted("default/urgent")


def test_eviction_cancelled_when_no_longer_justified():
    """If the preemptor goes away (or admits off independently freed
    capacity) before the victims drain, their eviction directives are
    rescinded at the next rebalance — a healthy running gang is never
    torn down for a preemption nobody needs any more."""
    s, _ = sched(capacity=2)
    cs, tj = fleet_training_job(tpu_job("keeper"), s)
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    assert offer(s, "x")  # second slice held
    assert not offer(s, "big", priority=10, slices=2)  # marks both
    s.release("default/big")  # preemptor deleted before victims drained
    assert s.pop_eviction("default/x") is None  # cancelled
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.failures == []
    assert s.is_admitted("default/keeper")


def test_preempt_to_queue_readmits_when_capacity_already_free():
    """The pop-raced-with-a-release safety net: if the re-offer inside
    the preemption teardown admits on the spot, the job goes straight
    back to Creating — never parked Queued while holding a slot whose
    wakeup was already consumed."""
    s, _ = sched(capacity=2)
    cs, tj = fleet_training_job(tpu_job("racer"), s)
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    tj._preempt_to_queue(0, "raced eviction")
    assert tj.job.status.phase == t.TPUJobPhase.CREATING
    assert "re-admitted" in tj.job.status.reason
    assert tj.job.status.failures[-1].kind == t.FailureKind.PREEMPTION


def test_eviction_lands_during_backoff():
    """A victim parked in Backoff (pods already torn down, reservation
    retained) must release the moment its eviction reconcile runs — the
    preemptor cannot wait out the victim's crash backoff."""
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(
        tpu_job("crashy", restart_backoff=t.RestartBackoffSpec(
            base_seconds=300)), s)
    tj.reconcile()
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}})
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.BACKOFF
    assert s.is_admitted("default/crashy")  # restarts retain their slot

    assert not offer(s, "urgent", priority=10)  # marks the backoff victim
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert s.is_admitted("default/urgent")
    # Both the kubelet preemption (exit 137) and the scheduler eviction
    # bill the preemption budget, never the crash-loop budget.
    assert tj.job.status.restart_counts == {t.FailureKind.PREEMPTION: 2}


# --- shard affinity ----------------------------------------------------------

def test_sharded_queue_routing_stable_and_exclusive():
    q = ShardedWorkQueue(4)
    keys = [f"default/job-{i}" for i in range(64)]
    routed = {k: q.shard_for(k) for k in keys}
    assert set(routed.values()) == {0, 1, 2, 3}  # spread
    assert all(q.shard_for(k) == s for k, s in routed.items())  # stable

    # Stress: 4 shard workers, many adds per key — no key is ever
    # processed by two workers at once (affinity + processing-set).
    in_flight = {k: 0 for k in keys}
    max_seen = {k: 0 for k in keys}
    guard = threading.Lock()
    stop = threading.Event()

    def worker(shard):
        while not stop.is_set():
            item = q.get(timeout=0.05, shard=shard)
            if item is None:
                continue
            with guard:
                in_flight[item] += 1
                max_seen[item] = max(max_seen[item], in_flight[item])
            time.sleep(0.0005)
            with guard:
                in_flight[item] -= 1
            q.done(item)

    workers = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for w in workers:
        w.start()
    for _ in range(30):
        for k in keys:
            q.add(k)
        time.sleep(0.002)
    time.sleep(0.3)
    stop.set()
    for w in workers:
        w.join(timeout=2.0)
    q.shutdown()
    assert max(max_seen.values()) == 1


# --- informer 410 re-anchor (the fleet-burst gap) ----------------------------

def test_pristine_store_list_rv_anchors_gap_free():
    """A pristine (empty) store's list RV must be a USABLE watch anchor:
    resourceVersion "0" is the K8s any-version sentinel with no replay
    guarantee, and the fake minting it for version-0 stores silently
    degraded anchored reflectors to from-now watches — at fleet burst
    rates that swallowed ~25% of submitted jobs until the next resync
    (caught by bench.py --fleet; latent since the PR-3 reflector)."""
    cs = FakeClientset()
    items, rv = cs.tpujobs.list_with_version("default")
    assert items == [] and rv not in ("", "0")
    # A create raced into the list→watch-open window MUST be replayed by
    # the anchored watch — that is the entire gap-free contract.
    cs.tpujobs.create("default", tpu_job("raced").to_dict())
    w = cs.tpujobs.watch("default", resource_version=rv)
    try:
        event_type, obj = next(iter(w))
    finally:
        w.stop()
    assert event_type == "ADDED" and obj["metadata"]["name"] == "raced"


def test_informer_falls_back_gap_free_when_list_rv_is_zero():
    """Defense in depth for servers that DO hand out RV "0": the informer
    must treat it as no-anchor and use the watch-before-list order, which
    is gap-free for unanchored streams — never anchor a watch on the
    any-version sentinel."""
    from tpu_operator.client.informer import Informer

    cs = FakeClientset()

    class ZeroRvClient:
        kind = "TPUJob"

        def __init__(self):
            self.watch_opens = []

        def list(self, ns, label_selector=""):
            return cs.tpujobs.list(ns, label_selector)

        def list_with_version(self, ns, label_selector=""):
            # Pathological server: always "0" — and a job races in right
            # after the snapshot is taken.
            items = cs.tpujobs.list(ns, label_selector)
            cs.tpujobs.create(
                "default",
                tpu_job(f"raced-{len(items)}").to_dict())
            return items, "0"

        def watch(self, ns, label_selector="", resource_version=None):
            self.watch_opens.append(resource_version)
            return cs.tpujobs.watch(ns, label_selector,
                                    resource_version=resource_version or "")

    client = ZeroRvClient()
    inf = Informer(client, "default", resync_period=0)
    stop = threading.Event()
    inf.start(stop)
    try:
        # The raced job lands despite the useless RV: watch opened before
        # the post-watch list that closes the gap.
        assert wait_for(lambda: inf.store.get("default", "raced-0")
                        is not None)
        assert all(rv in (None, "") for rv in client.watch_opens)
    finally:
        stop.set()


def test_informer_relists_on_expired_anchor_instead_of_gapping():
    """410 Gone on the anchored watch open must trigger a FRESH list +
    re-anchor, not a from-now watch: a job created between the stale
    snapshot and the new stream otherwise vanishes until the next resync
    (at fleet burst rates that was ~25% of submissions parked with phase
    None — caught by bench.py --fleet)."""
    from tpu_operator.client import errors as cerrors
    from tpu_operator.client.informer import Informer

    cs = FakeClientset()
    cs.tpujobs.create("default", tpu_job("early").to_dict())

    class Expired410Client:
        """First anchored open 410s; a job slips in during the failure
        window (after the list, before any stream exists)."""

        kind = "TPUJob"

        def __init__(self, real_cs):
            self._cs = real_cs
            self.lists = 0
            self.expired_once = False

        def list(self, ns, label_selector=""):
            return self._cs.tpujobs.list(ns, label_selector)

        def list_with_version(self, ns, label_selector=""):
            self.lists += 1
            return self._cs.tpujobs.list_with_version(ns, label_selector)

        def watch(self, ns, label_selector="", resource_version=None):
            if resource_version and not self.expired_once:
                self.expired_once = True
                self._cs.tpujobs.create("default",
                                        tpu_job("slipped-in").to_dict())
                raise cerrors.expired("TPUJob", "anchor compacted")
            return self._cs.tpujobs.watch(
                ns, label_selector, resource_version=resource_version)

    client = Expired410Client(cs)
    inf = Informer(client, "default", resync_period=0)  # no resync healing
    seen = []
    inf.add_event_handler(on_add=lambda o: seen.append(
        o["metadata"]["name"]))
    stop = threading.Event()
    inf.start(stop)
    try:
        assert wait_for(lambda: inf.store.get("default", "slipped-in")
                        is not None)
        assert client.lists >= 2  # the 410 forced a fresh list
        assert "slipped-in" in seen and "early" in seen
    finally:
        stop.set()


# --- writeback rate limiting -------------------------------------------------

def test_writeback_limiter_defers_noncritical_writes():
    clock = [0.0]
    limiter = WritebackLimiter(qps=1.0, burst=1, clock=lambda: clock[0])
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("w"), s, writeback=limiter)
    tj.reconcile()  # setup + gang: critical writes pass the limiter
    mark_pods(cs)
    tj.reconcile()  # phase → Running (critical)
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING

    # Drain the bucket, then change pure telemetry: the PUT defers.
    while limiter.allow():
        pass
    rv_before = cs.tpujobs.get("default", "w")["metadata"]["resourceVersion"]
    tj.job.status.last_heartbeat = {"step": 5, "time": "2026-08-04T00:00:00Z"}
    tj.update_crd_status()
    assert tj._writeback_deferred
    assert cs.tpujobs.get("default", "w")["metadata"]["resourceVersion"] \
        == rv_before
    # The retry obligation is armed so the deferred write always lands.
    assert tj.next_time_obligation() is not None

    # Tokens refill → the coalesced state lands in one PUT.
    clock[0] += 2.0
    tj.update_crd_status()
    assert not tj._writeback_deferred
    stored = cs.tpujobs.get("default", "w")
    assert stored["status"]["lastHeartbeat"]["step"] == 5

    # A critical transition never waits for tokens.
    while limiter.allow():
        pass
    tj.job.spec.suspend = True
    tj.reconcile()
    assert cs.tpujobs.get("default", "w")["status"]["phase"] == "Suspended"


def test_startup_oneshot_never_deferred_by_writeback_limiter():
    """status.startup is a one-shot (the payload drops it after the 200
    ACK — PR 5), so the limiter must treat its appearance as critical:
    a deferred copy parked in a dying operator would be lost forever."""
    clock = [0.0]
    limiter = WritebackLimiter(qps=1.0, burst=1, clock=lambda: clock[0])
    s, _ = sched(capacity=1)
    cs, tj = fleet_training_job(tpu_job("su"), s, writeback=limiter)
    tj.reconcile()
    while limiter.allow():
        pass
    tj.job.status.startup = {"compileSeconds": 12.5, "cacheHit": True,
                             "attempt": 0}
    tj.update_crd_status()
    assert not tj._writeback_deferred
    stored = cs.tpujobs.get("default", "su")
    assert stored["status"]["startup"]["compileSeconds"] == 12.5


def test_sharded_queue_shardless_get_sweeps_all_shards():
    """A harness driving the controller without a shard must see keys
    from EVERY shard, not silently drain shard 0 only."""
    q = ShardedWorkQueue(4)
    keys = [f"default/job-{i}" for i in range(16)]
    assert len({q.shard_for(k) for k in keys}) == 4
    for k in keys:
        q.add(k)
    got = []
    while True:
        item = q.get(timeout=0.2)
        if item is None:
            break
        got.append(item)
        q.done(item)
    assert sorted(got) == sorted(keys)


# --- e2e: preemption over the full (sharded) controller loop -----------------

@pytest.fixture
def fleet_harness():
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)
    config = t.ControllerConfig(slice_inventory={KEY: 1})
    controller = Controller(cs, factory, config, shards=2)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()
    yield cs, controller
    stop.set()
    runner.join(timeout=5.0)


def phase_of(cs, name):
    return (cs.tpujobs.get("default", name).get("status") or {}).get("phase")


def test_e2e_priority_preemption_victim_requeues_and_finishes(fleet_harness):
    cs, controller = fleet_harness
    assert controller.queue.num_shards == 2

    cs.tpujobs.create("default", tpu_job("batch-lo", priority=0).to_dict())
    assert wait_for(lambda: len(cs.pods.list("default")) == 1)
    mark_pods(cs)
    assert wait_for(lambda: phase_of(cs, "batch-lo") == "Running")

    # Higher-priority arrival: the running job is preempted, re-queues
    # on the preemption budget, and the urgent job takes the slice.
    cs.tpujobs.create("default", tpu_job("urgent-hi", priority=10).to_dict())
    assert wait_for(lambda: phase_of(cs, "batch-lo") == "Queued", timeout=10)
    assert wait_for(lambda: len(cs.pods.list("default")) == 1, timeout=10)
    urgent_pods = cs.pods.list("default")
    assert all("urgent-hi" in p["metadata"]["name"] for p in urgent_pods)
    lo = cs.tpujobs.get("default", "batch-lo")["status"]
    assert lo["failures"][-1]["kind"] == "preemption"
    assert lo["restartCounts"] == {"preemption": 1}

    # The urgent job finishes → victim re-admits, re-gangs, finishes.
    mark_pods(cs, "Succeeded", {"terminated": {"exitCode": 0}})
    assert wait_for(lambda: phase_of(cs, "urgent-hi") == "Done", timeout=10)
    assert wait_for(
        lambda: any("batch-lo" in p["metadata"]["name"]
                    and not (p.get("status") or {}).get("phase")
                    for p in cs.pods.list("default")), timeout=10)
    for pod in cs.pods.list("default"):
        if "batch-lo" in pod["metadata"]["name"] \
                and not (pod.get("status") or {}).get("phase"):
            pod["status"] = {"phase": "Succeeded", "containerStatuses": [
                {"name": "tpu",
                 "state": {"terminated": {"exitCode": 0}}}]}
            cs.pods.update("default", pod)
    assert wait_for(lambda: phase_of(cs, "batch-lo") == "Done", timeout=10)

    # One Event per decision, through the aggregating recorder.
    reasons = [e["reason"] for e in cs.events.list("default")]
    assert "Preempted" in reasons and "Admitted" in reasons \
        and "Queued" in reasons
    assert controller.metrics.snapshot()["tpujob_preemptions_total"] == 1


# --- tpujobctl surfacing -----------------------------------------------------

def test_describe_shows_scheduling_state(capsys):
    import io
    import contextlib
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.cmd import ctl
    from tpu_operator.testing.apiserver import ApiServerHarness

    with ApiServerHarness() as srv:
        cs = Clientset(RestConfig(host=srv.url, timeout=5.0))
        job = tpu_job("queuedjob", priority=4, queue="research")
        set_defaults(job.spec)
        job.status.phase = t.TPUJobPhase.QUEUED
        job.status.scheduling = {"queue": "research", "priority": 4,
                                 "position": 2}
        job.status.failures = [t.FailureRecord(
            attempt=0, kind=t.FailureKind.PREEMPTION,
            reason="preempted by higher-priority job default/urgent",
            time="2026-08-04T00:00:00Z")]
        cs.tpujobs.create("default", job.to_dict())

        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = ctl.main(["--master", srv.url, "describe", "queuedjob"])
        text = out.getvalue()
    assert rc == 0
    assert "queue 'research', priority 4" in text
    assert "queued at position 2" in text
    assert "Preempted:" in text and "default/urgent" in text
