"""Deterministic interleaving tests: the harness itself, then the four
known-hairy triples as permuted schedules instead of soak lottery —

1. fleet admission vs. teardown-release vs. eager restart rebuild,
2. writeback defer vs. critical-field bypass,
3. straggler fold vs. attempt reset (regression: a stale beat must never
   regress the detector to a dead generation),
4. write-behind enqueue vs. close()-drain (regression: an accepted
   enqueue is never stranded past close(flush=True)'s return).
"""

import threading

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.store.writebehind import WriteBehindUploader
from tpu_operator.testing import schedules
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.util import yieldpoints

from tests.test_fleet_scheduler import (
    KEY,
    fleet_training_job,
    mark_pods,
    sched,
    tpu_job,
)
from tests.test_steptrace import _beat, _controller_with_job

wait_for = make_wait_for(timeout=5.0, interval=0.02)


# --- harness self-tests -------------------------------------------------------

def test_merge_orders_enumerates_the_multinomial():
    orders = list(schedules.merge_orders(2, 2))
    assert len(orders) == 6  # C(4,2)
    assert len(set(orders)) == 6
    assert all(order.count(0) == 2 and order.count(1) == 2
               for order in orders)
    assert len(list(schedules.merge_orders(1, 1, 1))) == 6  # 3!


def test_run_order_executes_steps_in_merge_order():
    log = []
    threads = [[lambda: log.append("a1"), lambda: log.append("a2")],
               [lambda: log.append("b1")]]
    schedules.run_order(threads, (0, 1, 0))
    assert log == ["a1", "b1", "a2"]
    with pytest.raises(ValueError):
        schedules.run_order(threads, (0, 1))  # leaves a2 unexecuted


def test_exhaustive_rebuilds_state_per_schedule():
    seen = []

    def scenario():
        state = []
        return [[lambda: state.append(1)], [lambda: seen.append(len(state))]]

    count = schedules.exhaustive(scenario)
    assert count == 2  # two merges of 1+1
    assert sorted(seen) == [0, 1]  # fresh state each schedule


def test_scheduler_same_seed_same_schedule():
    def build(sched_):
        log = []
        sched_.add("a", lambda: log.append("a1"), lambda: log.append("a2"))
        sched_.add("b", lambda: log.append("b1"), lambda: log.append("b2"))
        sched_.log = log

    traces = []
    for _ in range(2):
        s = schedules.InterleavingScheduler(seed=7)
        build(s)
        s.run()
        traces.append((s.trace, s.log))
    assert traces[0] == traces[1]  # bit-identical schedule and effects
    # A different seed explores a different interleaving eventually.
    orders = set()
    for seed in range(8):
        s = schedules.InterleavingScheduler(seed=seed)
        build(s)
        s.run()
        orders.add(tuple(s.log))
    assert len(orders) > 1


def test_scheduler_reports_task_errors_with_schedule():
    s = schedules.InterleavingScheduler(seed=0)
    s.add("boom", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    with pytest.raises(AssertionError, match="seed 0"):
        s.run()
    assert not yieldpoints.installed()  # hook always uninstalls


def test_point_gate_holds_and_releases_threads():
    with schedules.PointGate() as gate:
        gate.hold("p")
        hits = []

        def worker():
            yieldpoints.pause("p")
            hits.append(1)

        th = threading.Thread(target=worker, daemon=True)
        th.start()
        assert gate.wait_blocked("p")
        assert hits == []  # parked at the point
        gate.release("p")
        th.join(timeout=5.0)
        assert hits == [1]
    assert not yieldpoints.installed()


# --- triple 1: admission vs teardown-release vs eager restart rebuild --------

def test_schedule_admission_release_rebuild_accounting():
    """All 6 serializations of: new job B admitting, old job A's teardown
    releasing, and the post-restart rebuild force-admitting A. In every
    schedule the inventory ledger must equal the sum of admitted grants
    (the invariant whose violation leaks or double-books slices), and B
    must never be lost — admitted or visibly queued."""
    state = {}

    def scenario():
        s, _ = sched(capacity=1)
        state["s"] = s
        return [
            [lambda: s.ensure_admitted("default/b", uid="u-b",
                                       demand=(KEY, 1))],
            [lambda: s.release("default/a")],
            [lambda: s.ensure_admitted("default/a", uid="u-a",
                                       demand=(KEY, 1),
                                       holds_hardware=True)],
        ]

    def check(order):
        s = state["s"]
        snap = s.summary()
        used = snap["inventory"][KEY]["used"]
        booked = sum(e.slices for e in s._admitted.values())
        assert used == booked, (order, snap)
        # B is never lost: admitted, or pending with a position.
        assert s.is_admitted("default/b") \
            or s.queue_position("default/b") is not None, order
        # The pool holds 1 slice; over-commit can only come from the
        # force-admit path (truth-on-the-ground), never from B.
        if used > 1:
            assert s.is_admitted("default/a"), order

    n = schedules.exhaustive(scenario, check)
    assert n == 6


def test_schedule_release_then_rebuild_heals_on_next_release():
    """The one schedule where teardown-release runs BEFORE the rebuild
    re-reserves (the release is a no-op, A's ghost reservation survives)
    is healed by the level-driven terminal path calling release again —
    the scheduler contract the controller relies on."""
    s, _ = sched(capacity=1)
    s.release("default/a")  # teardown raced ahead of the rebuild: no-op
    s.ensure_admitted("default/a", uid="u-a", demand=(KEY, 1),
                      holds_hardware=True)
    s.ensure_admitted("default/b", uid="u-b", demand=(KEY, 1))
    assert not s.is_admitted("default/b")  # ghost still holds the slice
    s.release("default/a")  # the terminal reconcile's idempotent release
    assert s.is_admitted("default/b")
    assert s.summary()["inventory"][KEY]["used"] == 1


# --- triple 2: writeback defer vs critical-field bypass ----------------------

def test_schedule_writeback_defer_vs_critical_bypass():
    """Both serializations of a telemetry-only write against a critical
    transition under a dry token bucket: whichever order runs, the
    critical field is persisted immediately and the telemetry either
    rides along coalesced or stays deferred WITH the retry obligation
    armed — never silently dropped."""
    from tpu_operator.scheduler.writeback import WritebackLimiter

    state = {}

    def scenario():
        clock = [0.0]
        limiter = WritebackLimiter(qps=1.0, burst=1,
                                   clock=lambda: clock[0])
        s, _ = sched(capacity=1)
        cs, tj = fleet_training_job(tpu_job("w"), s, writeback=limiter)
        tj.reconcile()
        mark_pods(cs)
        tj.reconcile()
        assert tj.job.status.phase == t.TPUJobPhase.RUNNING
        while limiter.allow():
            pass  # dry bucket: non-critical writes must defer
        state.update(cs=cs, tj=tj)

        def telemetry():
            tj.job.status.last_heartbeat = {
                "step": 7, "time": "2026-08-04T00:00:00Z"}
            tj.update_crd_status()

        def critical():
            tj.job.status.reason = "StallDetected"
            tj.update_crd_status()

        return [[telemetry], [critical]]

    def check(order):
        cs, tj = state["cs"], state["tj"]
        stored = cs.tpujobs.get("default", "w")["status"]
        # The critical field landed no matter the order.
        assert stored.get("reason") == "StallDetected", order
        if stored.get("lastHeartbeat", {}).get("step") == 7:
            # telemetry rode along on the critical write (coalesced)
            assert not tj._writeback_deferred, order
        else:
            # telemetry deferred: dirty in memory, retry armed
            assert tj._writeback_deferred, order
            assert tj.job.status.last_heartbeat["step"] == 7, order
            assert tj.next_time_obligation() is not None, order

    n = schedules.exhaustive(scenario, check)
    assert n == 2


# --- triple 3: straggler fold vs attempt reset --------------------------------

def test_schedule_straggler_fold_vs_attempt_reset():
    """Every serialization of: the old gang's last beats (one slow
    member), the reconcile's attempt bump, and the new gang's first
    beat. No schedule may leave a dead generation's straggler flag in
    status — and the detector must never regress to the old generation
    once it has seen the new one."""
    state = {}

    def scenario():
        cs, controller, tj = _controller_with_job(name="sj")
        state.update(controller=controller, tj=tj)

        def old_beat_healthy():
            controller.record_heartbeat("default", "sj",
                                        _beat(1, 0.1, attempt=0))

        def old_beat_slow():
            controller.record_heartbeat("default", "sj",
                                        _beat(2, 0.5, attempt=0))

        def attempt_bump():
            tj.job.status.attempt = 1

        def new_beat():
            controller.record_heartbeat("default", "sj",
                                        _beat(1, 0.1, attempt=1, step=0))

        return [[old_beat_healthy, old_beat_slow], [attempt_bump],
                [new_beat]]

    def check(order):
        tj = state["tj"]
        controller = state["controller"]
        # The dead generation's flag never survives the schedule.
        assert tj.job.status.stragglers == [], order
        # And the detector's memory never points at a generation older
        # than the newest beat it accepted.
        cadence = controller._gang_cadence.get("default/sj")
        assert cadence is not None and cadence["attempt"] == 1, order

    n = schedules.exhaustive(scenario, check)
    assert n == 12  # merges of 2+1+1


def test_stale_beat_does_not_regress_detector_generation():
    """Named regression for the defect the schedule above surfaced: a
    terminating pod's attempt-0 beat landing AFTER the new gang's first
    attempt-1 beat (but before the reconcile bumps status.attempt) used
    to reset the detector back to generation 0, wiping the live gang's
    cadence and force-persisting a spurious stragglers clear."""
    _cs, controller, tj = _controller_with_job(name="sj")
    assert controller.record_heartbeat("default", "sj",
                                       _beat(1, 0.1, attempt=1, step=0))
    cadence = controller._gang_cadence["default/sj"]
    assert cadence["attempt"] == 1 and 1 in cadence["procs"]
    # The stale beat: status.attempt is still 0, so the age gate in
    # record_heartbeat does NOT drop it — the detector itself must.
    assert controller.record_heartbeat("default", "sj",
                                       _beat(2, 0.5, attempt=0))
    cadence = controller._gang_cadence["default/sj"]
    assert cadence["attempt"] == 1, \
        "stale attempt-0 beat regressed the detector generation"
    assert 1 in cadence["procs"] and 2 not in cadence["procs"]


# --- triple 4: write-behind enqueue vs close()-drain --------------------------

class _RecordingStore:
    """WarmStartStore stand-in that records uploads in order."""

    def __init__(self):
        self.uploads = []
        self.artifacts = []

    def upload_checkpoint(self, step_dir, step):
        self.uploads.append(int(step))

    def mark_corrupt(self, step, reason=""):
        pass

    def upload_artifact(self, path, name):
        self.artifacts.append(name)

    def upload_cache(self, cache_dir):
        return 0


def test_schedule_writebehind_enqueue_vs_close_drain():
    """Named regression for the close-ordering defect the interleaving
    harness surfaced: close(flush=True) used to drain FIRST and mark
    closed after, so an enqueue landing in between was accepted and then
    stranded behind close's return (the process exit tears down the
    daemon worker mid-upload — a lost final checkpoint). The contract
    now: every enqueue that returns True is uploaded (or superseded by a
    later accepted step) by the time close(flush=True) returns; a racing
    enqueue that cannot be honored is REFUSED, never stranded."""
    store = _RecordingStore()
    with schedules.PointGate() as gate:
        gate.hold("writebehind.popped")
        up = WriteBehindUploader(store)
        assert up.enqueue(5, "/tmp/s5") is True
        # The worker pops step 5 and parks mid-window: queue empty,
        # upload not yet done — the exact state flush() misreads.
        assert gate.wait_blocked("writebehind.popped")
        assert up.enqueue(6, "/tmp/s6") is True  # accepted pre-close

        gate.hold("writebehind.close.marked")
        closer = threading.Thread(target=lambda: up.close(flush=True),
                                  daemon=True)
        closer.start()
        assert gate.wait_blocked("writebehind.close.marked")
        # The close mark has landed: the racing enqueue is refused
        # outright instead of being silently accepted-and-stranded.
        assert up.enqueue(7, "/tmp/s7") is False
        gate.release("writebehind.close.marked")
        gate.release("writebehind.popped")
        closer.join(timeout=10.0)
        assert not closer.is_alive()
    # Every accepted step landed before close returned; the refused one
    # never did.
    assert store.uploads == [5, 6]
    assert up.stats()["lastUploadedStep"] == 6
    assert up.idle()


def test_schedule_writebehind_seeded_no_lost_accepted_steps():
    """Seeded cooperative schedules over enqueue/close against a live
    worker: under every seed, close(flush=True) returns only after every
    ACCEPTED step is uploaded or superseded."""
    def build(sched_):
        store = _RecordingStore()
        up = WriteBehindUploader(store)
        accepted = []

        def enqueue(step):
            def op():
                if up.enqueue(step, f"/tmp/s{step}"):
                    accepted.append(step)
            return op

        def close_and_check():
            up.close(flush=True, timeout=10.0)
            outstanding = [s for s in accepted
                           if s not in store.uploads
                           and any(l > s for l in accepted)
                           is False]
            assert not [s for s in outstanding
                        if s == max(accepted, default=-1)], \
                (sched_.seed, accepted, store.uploads)

        sched_.add("producer", enqueue(1), enqueue(2))
        sched_.add("closer", close_and_check)

    schedules.run_seeds(build, seeds=range(8), step_timeout=0.75)


# --- triple 5: drain ACK vs real-failure restart ------------------------------

def test_schedule_drain_ack_vs_attempt_bump():
    """Every serialization of: process 0's drainAck beat racing a real
    gang failure (exit 137) and the two reconciles that restart and
    resolve. In no schedule may the restarted attempt inherit the
    predecessor's directive (the serve gate returns None), be billed
    planned off a hard death, or leave a non-terminal directive
    addressed to the live gang — an ACK from a restarted attempt is a
    pure no-op."""
    from tests.test_drain import drain_harness
    from tpu_operator.trainer import training as training_mod

    state = {}

    def scenario():
        cs, controller, tj = drain_harness(name="race")
        tj.request_drain(t.DrainReason.RESIZE, target_slices=8)
        rid = tj.job.status.drain["id"]
        state.update(controller=controller, tj=tj)

        def ack():
            controller.record_heartbeat("default", "race", {
                "time": training_mod._now(), "step": 100, "attempt": 0,
                "processId": 0, "drainAck": {"id": rid, "step": 100}})

        def fail():
            mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}})

        return [[ack], [fail, tj.reconcile, tj.reconcile]]

    def check(order):
        controller, tj = state["controller"], state["tj"]
        status = tj.job.status
        assert status.attempt == 1, order
        # Hard death is billed preemption — the raced directive must not
        # launder a 137 into a planned restart.
        assert status.restart_counts == {"preemption": 1}, order
        dr = status.drain
        assert not (dr and dr["state"] in (t.DrainState.REQUESTED,
                                           t.DrainState.ACKED)
                    and dr["attempt"] == status.attempt), order
        assert controller.pending_drain("default", "race") is None, order

    n = schedules.exhaustive(scenario, check)
    assert n == 4  # merges of 1+3


# --- triple 6: drain completion vs eviction cancel ----------------------------

def test_schedule_drain_completion_vs_eviction_cancel():
    """Every serialization of: the drained victim's planned exit (+ the
    reconcile that classifies it) racing the fleet's unjustified-
    eviction cancel (the preemptor released). Whichever wins, the
    restart is billed planned exactly once, the directive resolves
    terminally, no eviction mark is left behind, and the inventory
    ledger still equals the sum of admitted grants."""
    from tests.test_drain import beat, drain_harness

    state = {}

    def scenario():
        cs, controller, tj = drain_harness(name="dr", capacity=8)
        beat(controller, tj, step=100)
        assert not controller.scheduler.ensure_admitted(
            "default/vip", uid="uid-vip", demand=(KEY, 8), priority=10)
        tj.reconcile()
        assert tj.job.status.drain["reason"] == t.DrainReason.PREEMPTION
        state.update(controller=controller, tj=tj)

        def planned_exit():
            mark_pods(cs, "Failed", {"terminated": {"exitCode": 160}})

        def cancel():
            controller.scheduler.release("default/vip")

        return [[planned_exit, tj.reconcile], [cancel]]

    def check(order):
        controller, tj = state["controller"], state["tj"]
        s = controller.scheduler
        assert tj.job.status.restart_counts == {"planned": 1}, order
        assert tj.job.status.drain["state"] in (
            t.DrainState.COMPLETED, t.DrainState.EXPIRED), order
        assert s.peek_eviction("default/dr") is None, order
        snap = s.summary()
        used = snap["inventory"][KEY]["used"]
        booked = sum(e.slices for e in s._admitted.values())
        assert used == booked, (order, snap)

    n = schedules.exhaustive(scenario, check)
    assert n == 3  # merges of 2+1
