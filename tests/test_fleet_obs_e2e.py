"""Fleet observability e2e: the unified timeline over the real operator
binary, and the churn-soak residue gate for the TimelineStore.

The binary tier drives one elastic job through its whole observable life
— submit while the fleet is full (Queued), admit off a freed slice, run,
preempt into a capacity-grown pool (restart + resize up), finish — with
the operator running as a real process against the HTTP test apiserver,
then reads the timeline back over the operator's OWN status port and
asserts the span tree tells that story in order: queue/admit decision
spans, the phase ladder, the failure-ledger restart span, the
elastic:resize span, and a Chrome trace export perfetto would accept.
The fleet rollup endpoint and the fleet_* metric families (goodput,
queue waits, preemption cost) are scraped from the same port, so the
whole observability plane is proven over the wire, process boundary
included.

The in-process tier is the lifecycle gate: a create/delete churn storm
must leave ``TimelineStore.job_count() == 0`` — the conftest joblife
guard turns any per-job residue into a test failure.
"""

from __future__ import annotations

import contextlib
import io
import json
import signal
import socket
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import pytest

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.controller.controller import Controller
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for

wait_for = make_wait_for(timeout=60.0, interval=0.25)

V4 = "cloud-tpus.google.com/v4"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def status_get(port: int, path: str):
    """GET against the operator's status port; (code, parsed-or-text)."""
    url = f"http://127.0.0.1:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=5.0) as resp:
            body = resp.read().decode()
            ctype = resp.headers.get("Content-Type", "")
            if "json" in ctype:
                return resp.status, json.loads(body)
            return resp.status, body
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()
    except (urllib.error.URLError, OSError):
        return 0, ""


def node(name: str, sid: str) -> dict:
    return {"metadata": {"name": name, "labels": {
        "cloud.google.com/gke-tpu-topology": "2x2x2",
        "tpuoperator.dev/slice-id": sid}},
        "status": {"allocatable": {V4: "4"}}}


def make_template(chips=4):
    return {"spec": {"containers": [{"name": "tpu", "image": "x",
                                     "resources": {"requests": {
                                         V4: str(chips)}}}]}}


def rigid_job(name: str) -> dict:
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=1, template=make_template(),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="ob01", tpu_topology="2x2x2",
        restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    return t.TPUJob(metadata={"name": name, "namespace": "default",
                              "uid": f"uid-{name}"}, spec=spec).to_dict()


def elastic_job(name: str) -> dict:
    """A 2-process gang over [1, 2] v4 slices: small enough to admit on
    one freed slice, elastic enough to resize up on restart."""
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=2, template=make_template(),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="ob02", tpu_topology="2x2x2", num_slices=2,
        elastic=t.ElasticSpec(min_slices=1, max_slices=2),
        restart_backoff=t.RestartBackoffSpec(base_seconds=0))
    return t.TPUJob(metadata={"name": name, "namespace": "default",
                              "uid": f"uid-{name}"}, spec=spec).to_dict()


def set_pod_state(cs, pod, phase, container_state):
    pod["status"] = {
        "phase": phase,
        "containerStatuses": [{"name": "tpu", "state": container_state}],
    }
    cs.pods.update("default", pod)


def live_pods(cs, job="obs"):
    """The job's live gang (a deleted job's pods may linger until the GC
    sweep — scope by name so the hog's orphan doesn't count)."""
    return [p for p in cs.pods.list("default")
            if p["metadata"]["name"].startswith(f"{job}-")
            and (p.get("status") or {}).get("phase")
            not in ("Succeeded", "Failed")]


@pytest.fixture
def operator_env():
    """Real operator binary with fleet scheduling discovered from the
    node watch and the status server on a real port."""
    harness = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=harness.url, timeout=5.0))
    port = free_port()
    op = subprocess.Popen(
        [sys.executable, "-m", "tpu_operator.cmd.main", "--master",
         harness.url, "--namespace", "default", "--no-leader-elect",
         "--discover-slice-inventory", "--status-port", str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    yield cs, port
    op.send_signal(signal.SIGINT)
    try:
        op.wait(timeout=10)
    except subprocess.TimeoutExpired:
        op.kill()
    harness.stop()


def phase_of(cs, name):
    return (cs.tpujobs.get("default", name).get("status") or {}) \
        .get("phase")


@pytest.mark.slow
def test_timeline_over_operator_binary(operator_env):
    """Acceptance walk for the observability plane: queue → admit →
    run → preempt/resize → Done, then the timeline read back over the
    operator's status port tells the whole story in span order."""
    cs, port = operator_env

    # One discovered slice; a rigid hog takes it, so the elastic job
    # queues — the timeline's first chapters.
    cs.nodes.create("", node("n1", "slice-a"))
    cs.tpujobs.create("default", rigid_job("hog"))
    assert wait_for(lambda: phase_of(cs, "hog") == "Creating")
    cs.tpujobs.create("default", elastic_job("obs"))
    assert wait_for(lambda: phase_of(cs, "obs") == "Queued")

    # The hog finishes its tenancy: its slice frees, obs admits at the
    # elastic minimum (1 of 2 slices → a 1-process gang).
    cs.tpujobs.delete("default", "hog")
    assert wait_for(lambda: phase_of(cs, "obs") == "Creating")
    assert wait_for(lambda: len(live_pods(cs)) == 1)
    for p in live_pods(cs):
        set_pod_state(cs, p, "Running", {"running": {}})
    assert wait_for(lambda: phase_of(cs, "obs") == "Running")

    # A node pool scales up, then the gang is preempted (exit 137): the
    # restart regangs at 2 slices — a failure-ledger record AND an
    # elastic resize land on the same timeline.
    cs.nodes.create("", node("n2", "slice-b"))
    assert wait_for(lambda: (status_get(
        port, "/api/fleet")[1] or {}).get("jobs") is not None)
    for p in live_pods(cs):
        set_pod_state(cs, p, "Failed", {"terminated": {"exitCode": 137}})
    assert wait_for(lambda: len(live_pods(cs)) == 2, timeout=90.0)
    status = cs.tpujobs.get("default", "obs")["status"]
    assert status["elastic"]["slices"] == 2
    assert status["elastic"]["lastResizeDirection"] == "up"
    for p in live_pods(cs):
        set_pod_state(cs, p, "Succeeded", {"terminated": {"exitCode": 0}})
    assert wait_for(lambda: phase_of(cs, "obs") == "Done", timeout=90.0)

    # -- the timeline, over the wire ------------------------------------
    code, body = status_get(port, "/api/jobs/default/obs/timeline")
    assert code == 200, body
    spans = body["spans"]
    assert body["job"] == "default/obs"
    assert body["phase"] == "Done"

    kinds = {s["kind"] for s in spans}
    assert {"phase", "decision", "failure", "elastic"} <= kinds, kinds
    names = [s["name"] for s in spans]
    assert "phase:Queued" in names
    assert "phase:Running" in names
    assert "phase:Done" in names
    assert "elastic:resize" in names
    assert any(n.startswith("restart:") for n in names), names

    # Spans come back start-ordered — the assembled tree IS the story.
    starts = [s["start"] for s in spans]
    assert starts == sorted(starts)
    # The ledger span carries the restart's forensics inline.
    ledger = next(s for s in spans if s["kind"] == "failure")
    assert ledger["attrs"]["attempt"] == 0
    resize = next(s for s in spans if s["name"] == "elastic:resize")
    assert resize["attrs"]["direction"] == "up"
    # Queued happened strictly before the restart record.
    queued = next(s for s in spans if s["name"] == "phase:Queued")
    assert queued["start"] <= ledger["start"]
    # Decision spans carry reconcile trace ids that cross-reference the
    # trace buffer's ?job= filter.
    traced = [s for s in spans
              if s["kind"] == "decision" and s.get("traceId")]
    assert traced, [s["name"] for s in spans if s["kind"] == "decision"]
    code, traces = status_get(port, "/api/traces?job=default/obs")
    assert code == 200
    trace_ids = {s.get("traceId") for s in traces.get("spans", [])}
    assert trace_ids & {s["traceId"] for s in traced}

    # -- Chrome trace export: perfetto-loadable JSON --------------------
    code, chrome = status_get(
        port, "/api/jobs/default/obs/timeline?format=chrome")
    assert code == 200
    events = chrome if isinstance(chrome, list) else json.loads(chrome)
    phs = {ev.get("ph") for ev in events}
    assert "M" in phs            # process/thread name metadata
    assert phs & {"X", "i"}      # complete spans and/or instants
    assert all("ts" in ev for ev in events if ev.get("ph") != "M")

    # -- fleet rollup + metric families over the same port --------------
    code, fleet = status_get(port, "/api/fleet")
    assert code == 200
    rows = {r["name"]: r for r in fleet["jobs"]}
    assert rows["obs"]["phase"] == "Done"
    assert rows["obs"]["restarts"] == 1
    assert fleet["preemption"]["restarts"] >= 1

    code, metrics_text = status_get(port, "/metrics")
    assert code == 200
    assert "fleet_goodput_ratio" in metrics_text
    assert "fleet_preemption_lost_step_seconds" in metrics_text
    assert "fleet_straggler_count" in metrics_text
    assert "fleet_remediation_count" in metrics_text
    # obs waited in the queue before admitting, so the per-queue wait
    # quantile gauge has samples for its queue.
    assert "fleet_queue_wait_seconds" in metrics_text
    assert 'queue="default"' in metrics_text

    # 404 contract: an unknown job is a miss, not an empty timeline.
    code, _ = status_get(port, "/api/jobs/default/ghost/timeline")
    assert code == 404

    # -- tpujobctl against the live binary's status port ----------------
    from tpu_operator.cmd import ctl
    url = f"http://127.0.0.1:{port}"
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main(["--status-url", url, "timeline", "obs"])
    text = out.getvalue()
    assert rc == 0
    assert "default/obs" in text
    assert "phase:Queued" in text and "elastic:resize" in text
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main(["--status-url", url, "top"])
    assert rc == 0
    assert "obs" in out.getvalue()


# --- churn soak: zero joblife residue ---------------------------------------


def churn_job(name: str) -> dict:
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=1, template=make_template(),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="ob03")
    return t.TPUJob(metadata={"name": name, "namespace": "default",
                              "uid": f"uid-{name}"}, spec=spec).to_dict()


def test_timeline_store_survives_job_churn_with_zero_residue():
    """Create/delete N jobs through a live controller: every one of them
    feeds decision events into the TimelineStore, and every deletion
    must prune its slot — ``job_count() == 0`` at the end, and the
    conftest joblife guard fails the test on any witness residue."""
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)
    controller = Controller(cs, factory)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()
    soak_wait = make_wait_for(timeout=20.0, interval=0.05)
    try:
        names = [f"churn-{i}" for i in range(10)]
        for n in names:
            cs.tpujobs.create("default", churn_job(n))
        # Every job got far enough to emit events into its timeline.
        assert soak_wait(lambda: all(
            controller.timeline.events("default", n) for n in names))
        assert controller.timeline.job_count() == len(names)
        for n in names:
            cs.tpujobs.delete("default", n)
        assert soak_wait(
            lambda: not any(f"default/{n}" in controller.jobs
                            for n in names))
        # Deletion reconciles pruned each slot eagerly — no residue.
        assert soak_wait(lambda: controller.timeline.job_count() == 0), \
            controller.timeline.job_count()
    finally:
        stop.set()
        runner.join(timeout=5.0)
