"""Coordinated multi-process SIGTERM drain (VERDICT round-1 item 6).

A preemption SIGTERM lands on ONE pod of a multi-process job, but an orbax
save is a group collective — so train_loop reaches drain consensus via a
per-step allgather of the local drain latch, and every process saves the
same step. This test runs a real 2-process jax.distributed CPU group
through the operator's bootstrap path (tests/drain_worker.py), SIGTERMs
process 0 only, and asserts:

- both processes exit 143 (the retryable band → whole-group restart);
- both log the SAME drained step;
- the checkpoint directory holds exactly that step, readable.
"""

from __future__ import annotations

import os
import re
import signal
import socket
import subprocess
import sys
import time


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "drain_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_sigterm_to_one_process_checkpoints_one_consistent_step(tmp_path):
    ckpt_dir = tmp_path / "ckpt"
    sentinel_dir = tmp_path / "sentinels"
    sentinel_dir.mkdir()
    port = _free_port()

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers want 1 local CPU device each
    env["PALLAS_AXON_POOL_IPS"] = ""

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(port), str(pid), "2",
             str(ckpt_dir), str(sentinel_dir)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO)
        for pid in range(2)
    ]
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            if len(os.listdir(sentinel_dir)) >= 2:
                break
            for p in procs:
                assert p.poll() is None, (
                    f"worker died before stepping:\n{p.communicate()[0]}")
            time.sleep(0.3)
        else:
            raise AssertionError("workers never reached steady-state stepping")

        procs[0].send_signal(signal.SIGTERM)  # only process 0 is preempted

        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 143, f"exit {p.returncode}:\n{out}"

        drained = [re.search(r"drain: checkpointed step (\d+)", out)
                   for out in outs]
        assert all(drained), f"missing drain log:\n---\n" + "\n---\n".join(outs)
        steps = {int(m.group(1)) for m in drained}
        assert len(steps) == 1, f"processes drained at different steps: {steps}"
        step = steps.pop()
        assert step > 0

        from tpu_operator.payload import checkpoint as ckpt_mod

        reader = ckpt_mod.Checkpointer(str(ckpt_dir), save_every=10 ** 9)
        try:
            assert reader.latest_step() == step
        finally:
            reader.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
