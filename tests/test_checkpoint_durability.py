"""Checkpoint durability matrix (payload/checkpoint.py).

The hardening arc over the plain orbax wrapper: verified saves (commit
marker + manifest), quarantine-and-fall-back restore, save-failure
tolerance (skip/count/escalate), gang-consistent resume, and the
end-of-run save dedup — plus the operator-side plumbing: heartbeat fields,
``status.checkpoint`` delta accounting, ledger ``resumeStep``, and strict
schema round-trips.

These tests use raw pytrees (no model build) so the matrix stays fast; the
train-loop integration rides in tests/test_checkpoint.py and the full
kill -9 + corrupt-latest e2e in tests/test_checkpoint_chaos.py.
"""

import json
import os
import shutil

import pytest

import jax.numpy as jnp

from tpu_operator.payload import checkpoint
from tpu_operator.payload.bootstrap import EXIT_RETRYABLE


def tiny_state(step=0):
    return {"step": jnp.int32(step), "w": jnp.arange(64, dtype=jnp.float32)}


def make_ck(path, **kw):
    kw.setdefault("save_every", 2)
    return checkpoint.Checkpointer(str(path), **kw)


def corrupt_a_file(step_dir, keep_size=False):
    """Flip bytes in one data file of a step dir (not the manifest)."""
    victims = []
    for root, _dirs, files in os.walk(step_dir):
        for fn in files:
            if fn != checkpoint.MANIFEST_NAME:
                victims.append(os.path.join(root, fn))
    victim = sorted(victims)[-1]
    if keep_size:
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            f.write(b"\xde\xad\xbe\xef" * max(1, min(size, 16) // 4))
    else:
        with open(victim, "ab") as f:
            f.write(b"TORN")
    return victim


# --- verified saves ----------------------------------------------------------

def test_verified_save_writes_manifest_and_tracks_step(tmp_path):
    ck = make_ck(tmp_path / "ck")
    assert ck.maybe_save(1, tiny_state(1))
    assert ck.maybe_save(2, tiny_state(2))
    ck.close()
    assert ck.last_verified_step() == 2
    manifest = tmp_path / "ck" / "2" / checkpoint.MANIFEST_NAME
    assert manifest.exists()
    doc = json.loads(manifest.read_text())
    assert doc["step"] == 2
    assert doc["files"] and all(
        {"path", "size", "sha256"} <= set(e) for e in doc["files"])
    assert ck.stats() == {"saveFailures": 0, "restoreFallbacks": 0,
                          "lastCheckpointStep": 2}


def test_last_verified_lags_latest_until_commit_checked(tmp_path):
    ck = make_ck(tmp_path / "ck")
    assert ck.maybe_save(1, tiny_state(1))
    # The async save may already be on disk, but it has not been VERIFIED
    # yet — last_verified must not advertise it as durable.
    assert ck.last_verified_step() is None
    ck.close()  # flush + verify
    assert ck.last_verified_step() == 1


# --- end-of-run save dedup (satellite) ---------------------------------------

def test_save_dedups_in_flight_interval_save_of_same_step(tmp_path):
    """The old code compared only latest_step(), which misses an in-flight
    async interval save of the same step and issued a redundant force=True
    rewrite. save() must synchronize and skip."""
    ck = make_ck(tmp_path / "ck")
    assert ck.maybe_save(2, tiny_state(2))  # async interval save in flight
    calls = []
    real_save = ck.manager.save

    def spying_save(*a, **kw):
        calls.append(a)
        return real_save(*a, **kw)

    ck.manager.save = spying_save
    assert ck.save(2, tiny_state(2)) is False  # dedup: no manager.save call
    assert calls == []
    assert ck.last_verified_step() == 2  # the sync verified the pending one
    ck.close()


def test_save_still_writes_new_final_step(tmp_path):
    ck = make_ck(tmp_path / "ck")
    assert ck.maybe_save(2, tiny_state(2))
    assert ck.save(3, tiny_state(3)) is True  # genuinely new step
    ck.close()
    assert ck.latest_step() == 3
    assert ck.last_verified_step() == 3


# --- save-failure tolerance --------------------------------------------------

def test_interval_save_failure_is_skipped_and_counted(tmp_path):
    ck = make_ck(tmp_path / "ck", fail_after=3)

    def exploding(*_a, **_kw):
        raise OSError(28, "No space left on device")

    ck.manager.save = exploding
    assert ck.maybe_save(2, tiny_state(2)) is False  # skipped, not raised
    assert ck.save_failures == 1
    assert ck.consecutive_save_failures == 1
    assert ck.stats()["saveFailures"] == 1
    ck.manager = make_ck(tmp_path / "ck").manager  # healthy again
    assert ck.maybe_save(4, tiny_state(4)) is True
    ck._finalize_pending(block=True)
    # a verified commit resets the escalation streak, not the total
    assert ck.consecutive_save_failures == 0
    assert ck.save_failures == 1
    ck.close()


def test_consecutive_save_failures_escalate_retryable(tmp_path):
    ck = make_ck(tmp_path / "ck", fail_after=3)

    def exploding(*_a, **_kw):
        raise OSError("flaky volume")

    ck.manager.save = exploding
    assert ck.maybe_save(2, tiny_state(2)) is False
    assert ck.maybe_save(4, tiny_state(4)) is False
    with pytest.raises(SystemExit) as exc:
        ck.maybe_save(6, tiny_state(6))
    assert exc.value.code == EXIT_RETRYABLE


def test_drain_save_failure_still_exits_retryable(tmp_path):
    """Satellite: an I/O failure during the preemption drain save must not
    escape train_loop as a permanent exit — the drain still exits 143 and
    the restart resumes from the last verified save."""
    import jax
    import optax

    from tpu_operator.payload import bootstrap, data as data_mod, models, train

    mesh = train.make_mesh(1)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((8, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)

    ck = make_ck(tmp_path / "ck", save_every=1000)

    def exploding_save(_step, _state):
        raise RuntimeError("checkpoint volume vanished mid-drain")

    ck.save = exploding_save

    def drain_after_step_3(i, _metrics):
        if i == 3:
            bootstrap.request_drain()

    try:
        with pytest.raises(SystemExit) as exc:
            train.train_loop(mesh, step, state,
                             data_mod.synthetic_linear(0, 8, 8), 50,
                             checkpointer=ck, log_every=1,
                             log_fn=drain_after_step_3)
        assert exc.value.code == EXIT_RETRYABLE
    finally:
        bootstrap.reset_drain()
        ck.save = lambda *_a, **_kw: False
        ck.close()


def test_final_save_failure_exits_retryable_not_done(tmp_path):
    """A run must not report DONE with its end state silently unpersisted:
    when the end-of-run save fails (tolerance swallows the I/O error, so no
    escalation fires) and the final step never becomes durable, train_loop
    exits retryable — the restarted attempt resumes from the last verified
    step and re-earns a durable finish."""
    import jax
    import optax

    from tpu_operator.payload import data as data_mod, models, train

    mesh = train.make_mesh(1)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((8, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)

    ck = make_ck(tmp_path / "ck", save_every=1000, fail_after=100)

    def exploding(*_a, **_kw):
        raise OSError(28, "No space left on device")

    ck.manager.save = exploding
    try:
        with pytest.raises(SystemExit) as exc:
            train.train_loop(mesh, step, state,
                             data_mod.synthetic_linear(0, 8, 8), 5,
                             checkpointer=ck)
        assert exc.value.code == EXIT_RETRYABLE
        assert ck.save_failures >= 1
        assert ck.last_verified_step() is None
    finally:
        ck.manager.save = lambda *_a, **_kw: False
        ck.close()


def test_restore_failure_on_intact_bytes_raises_not_quarantines(tmp_path):
    """A restore that raises on a checkpoint whose bytes still verify
    against their manifest is NOT corruption (model-shape change, orbax
    drift): it must surface as a visible error, not quarantine healthy,
    resumable checkpoints one by one and silently restart from step 0."""
    save_steps(tmp_path / "ck", [2, 4])

    ck = make_ck(tmp_path / "ck")

    def incompatible(*_a, **_kw):
        raise ValueError("shape mismatch: restored (8,) vs abstract (16,)")

    ck.manager.restore = incompatible
    with pytest.raises(ValueError, match="shape mismatch"):
        ck.restore(tiny_state(0))
    ck.close()
    # Nothing was quarantined: both steps survive, resumable after rollback.
    assert (tmp_path / "ck" / "4").is_dir()
    assert (tmp_path / "ck" / "2").is_dir()
    assert ck.restore_fallbacks == 0


# --- restore fallback matrix -------------------------------------------------

def save_steps(path, steps):
    ck = make_ck(path, save_every=1)
    for s in steps:
        assert ck.maybe_save(s, tiny_state(s))
    ck.close()
    return ck


def test_restore_empty_dir_is_identity(tmp_path):
    ck = make_ck(tmp_path / "empty")
    state = tiny_state(0)
    same, start = ck.restore(state)
    ck.close()
    assert start == 0
    assert same is state
    assert ck.restore_fallbacks == 0


def test_corrupt_latest_falls_back_to_older_verified_step(tmp_path):
    save_steps(tmp_path / "ck", [1, 2, 3])
    corrupt_a_file(str(tmp_path / "ck" / "3"), keep_size=True)  # checksum

    ck = make_ck(tmp_path / "ck")
    restored, start = ck.restore(tiny_state(0))
    ck.close()
    assert start == 2
    assert int(restored["step"]) == 2
    assert ck.restore_fallbacks == 1
    assert ck.last_verified_step() == 2
    # the corrupt step was quarantined, not deleted
    quarantined = [d for d in os.listdir(tmp_path / "ck")
                   if d.startswith("3" + checkpoint.QUARANTINE_SUFFIX)]
    assert quarantined


def test_torn_latest_size_mismatch_falls_back(tmp_path):
    save_steps(tmp_path / "ck", [1, 2])
    corrupt_a_file(str(tmp_path / "ck" / "2"), keep_size=False)  # size

    ck = make_ck(tmp_path / "ck")
    _restored, start = ck.restore(tiny_state(0))
    ck.close()
    assert start == 1
    assert ck.restore_fallbacks == 1


def test_orphaned_tmp_dir_from_killed_save_is_swept(tmp_path):
    save_steps(tmp_path / "ck", [1, 2])
    # the litter a kill -9 mid-save leaves behind
    tmp_dir = tmp_path / "ck" / "4.orbax-checkpoint-tmp-123"
    (tmp_dir / "default").mkdir(parents=True)
    (tmp_dir / "default" / "data").write_bytes(b"half-written")

    ck = make_ck(tmp_path / "ck")
    _restored, start = ck.restore(tiny_state(0))
    ck.close()
    assert start == 2  # the tmp dir never shadows the real latest
    assert ck.restore_fallbacks == 0
    swept = [d for d in os.listdir(tmp_path / "ck")
             if d.endswith(checkpoint.ORPHAN_SUFFIX)]
    assert swept


def test_all_corrupt_reaches_step_zero(tmp_path):
    save_steps(tmp_path / "ck", [1, 2])
    for step in ("1", "2"):
        corrupt_a_file(str(tmp_path / "ck" / step), keep_size=True)

    ck = make_ck(tmp_path / "ck")
    state = tiny_state(0)
    same, start = ck.restore(state)
    ck.close()
    assert start == 0
    assert same is state
    assert ck.restore_fallbacks == 2
    assert ck.stats()["restoreFallbacks"] == 2


def test_unmanifested_corrupt_step_quarantined_on_restore_failure(tmp_path):
    """A legacy checkpoint (no manifest) passes static verification; when
    the actual restore then raises, it must still be quarantined and the
    walk continue."""
    save_steps(tmp_path / "ck", [1, 2])
    os.remove(tmp_path / "ck" / "2" / checkpoint.MANIFEST_NAME)
    # gut the payload data so orbax's restore itself fails
    default = tmp_path / "ck" / "2" / "default"
    shutil.rmtree(default)
    default.mkdir()

    ck = make_ck(tmp_path / "ck")
    _restored, start = ck.restore(tiny_state(0))
    ck.close()
    assert start == 1
    assert ck.restore_fallbacks == 1


def test_gang_disagreement_restores_min_step(tmp_path):
    """Injected per-process newest steps (this process saw 4, a lagging
    peer only 2): the group must restore the MIN so no member restores
    state another member does not hold."""
    save_steps(tmp_path / "ck", [2, 4])

    seen = []

    def lagging_peer_agree(candidate):
        seen.append(candidate)
        return min(candidate, 2) if candidate is not None else None

    ck = make_ck(tmp_path / "ck", agree_fn=lagging_peer_agree)
    restored, start = ck.restore(tiny_state(0))
    ck.close()
    # Agree round saw the local newest (4); the post-restore confirm round
    # saw the agreed step (2) — both collectives run on every process so
    # the gang's collective sequences stay paired.
    assert seen == [4, 2]
    assert start == 2    # group agreed on the lagging peer's 2
    assert int(restored["step"]) == 2
    assert ck.last_verified_step() == 2


def test_peer_restore_failure_retries_walk_collectively(tmp_path):
    """A peer whose restore of the agreed step failed reports None in the
    confirm round: this process must discard its own (successful) restore
    and re-agree, landing on the older step the whole group can hold —
    never proceeding alone into mismatched collectives."""
    save_steps(tmp_path / "ck", [2, 4])

    calls = []

    def peer_restore_fails_once(candidate):
        calls.append(candidate)
        if len(calls) == 1:
            return candidate        # agree: everyone's newest is 4
        if len(calls) == 2:
            return None             # confirm: a peer's restore of 4 failed
        if len(calls) == 3:
            return min(candidate, 2)  # re-agree: that peer fell back to 2
        return candidate            # confirm: everyone restored 2

    ck = make_ck(tmp_path / "ck", agree_fn=peer_restore_fails_once)
    restored, start = ck.restore(tiny_state(0))
    ck.close()
    assert calls == [4, 4, 4, 2]
    assert start == 2
    assert int(restored["step"]) == 2
    # The failure was the peer's, not ours: our step 4 stays unquarantined.
    assert ck.restore_fallbacks == 0
    assert (tmp_path / "ck" / "4").is_dir()


def test_gang_agree_single_process_is_identity():
    assert checkpoint.gang_agree_step(7) == 7
    assert checkpoint.gang_agree_step(None) is None


# --- reshard-restore matrix (elastic gangs resize between attempts) ----------

def _mesh_build(ndev):
    """(mesh, state, step_fn) of the tiny regression payload on an
    ndev-device data mesh — the reshard matrix's world-size knob."""
    import jax
    import optax

    from tpu_operator.payload import models, train

    mesh = train.make_mesh(ndev)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((8, 8), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)
    step = train.make_regression_train_step(model, tx, mesh, state)
    return mesh, state, step


def _run(ndev, steps, ckpt_dir, save_every=4, losses=None):
    """Drive train_loop on an ndev mesh to ``steps`` total steps (resume
    + fast-forward included), collecting (step, loss). The flight
    recorder is off: its one-step telemetry lag differs between a fresh
    and a resumed run, which would skew the trajectory comparison."""
    from tpu_operator.payload import data as data_mod, train

    mesh, state, step_fn = _mesh_build(ndev)
    ck = checkpoint.Checkpointer(str(ckpt_dir), save_every=save_every)
    try:
        train.train_loop(
            mesh, step_fn, state, data_mod.synthetic_linear(0, 8, 8),
            steps, checkpointer=ck, steptrace=None, log_every=1,
            log_fn=(lambda i, m: losses.append((i, float(m["loss"])))
                    if losses is not None else None))
    finally:
        ck.close()
    return ck


@pytest.mark.parametrize("save_dev,resume_dev", [(8, 4), (4, 8)],
                         ids=["shrink-8to4", "grow-4to8"])
def test_reshard_restore_matches_unresized_trajectory(tmp_path, save_dev,
                                                      resume_dev):
    """A checkpoint saved on mesh {data: save_dev} restores onto
    {data: resume_dev} inside the verified walk, and the resumed loss
    trajectory matches the unresized run after fast-forward — global
    batches and global math are mesh-layout-invariant, so the only
    acceptable difference is f32 reduction noise."""
    ckpt = tmp_path / "ck"
    _run(save_dev, 6, ckpt)

    resumed = []
    ck = _run(resume_dev, 10, ckpt, save_every=100, losses=resumed)
    assert ck.restore_fallbacks == 0  # resharding is NOT a fallback walk
    assert resumed and resumed[0][0] == 7  # fast-forwarded past step 6

    reference = []
    _run(save_dev, 10, tmp_path / "ref", save_every=100, losses=reference)
    ref = dict(reference)
    for i, loss in resumed:
        assert loss == pytest.approx(ref[i], abs=1e-4), (i, loss, ref[i])


def test_corrupt_latest_falls_back_across_size_boundary(tmp_path):
    """The quarantine walk composes with resharding: the newest step
    (saved by an 8-device mesh) is corrupt, so restore on a 4-device
    mesh quarantines it and reshard-restores the older verified step."""
    import jax

    _mesh8, state8, _step = _mesh_build(8)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(2, state8.replace(step=jnp.int32(2)))
    assert ck.maybe_save(4, state8.replace(step=jnp.int32(4)))
    ck.close()
    corrupt_a_file(str(tmp_path / "ck" / "4"), keep_size=True)

    _mesh4, state4, _step4 = _mesh_build(4)
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    restored, start = ck2.restore(state4)
    ck2.close()
    assert start == 2
    assert int(restored.step) == 2
    assert ck2.restore_fallbacks == 1
    leaf = restored.params["linear"]["kernel"]
    assert leaf.sharding.mesh.shape["data"] == 4
    assert [d.id for d in leaf.sharding.mesh.devices.flat] \
        == [d.id for d in jax.devices()[:4]]


def test_reshard_fallback_path_when_direct_restore_refuses(tmp_path):
    """Future-proofing the walk against orbax versions that REFUSE a
    mesh change on the direct sharded restore: with intact bytes, the
    host-roundtrip + device_put fallback re-lays the leaves out instead
    of the old behavior (re-raise as a permanent error)."""
    _mesh8, state8, _step = _mesh_build(8)
    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    assert ck.maybe_save(6, state8.replace(step=jnp.int32(6)))
    ck.close()

    _mesh4, state4, _step4 = _mesh_build(4)
    ck2 = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=1)
    real_restore = ck2.manager.restore
    calls = []

    def refuses_sharded_restore(step, *a, **kw):
        calls.append(step)
        if len(calls) == 1:
            raise ValueError("sharding mismatch: saved mesh shape (8, 1) "
                             "!= target mesh shape (4, 1)")
        return real_restore(step, *a, **kw)

    ck2.manager.restore = refuses_sharded_restore
    restored, start = ck2.restore(state4)
    ck2.close()
    assert calls == [6, 6]      # direct refused once, fallback restored
    assert start == 6
    assert int(restored.step) == 6
    assert ck2.reshard_restores == 1
    assert ck2.restore_fallbacks == 0   # nothing was quarantined
    assert (tmp_path / "ck" / "6").is_dir()
    leaf = restored.params["linear"]["kernel"]
    assert leaf.sharding.mesh.shape["data"] == 4


# --- heartbeat / operator plumbing -------------------------------------------

def test_heartbeat_carries_checkpoint_fields():
    from tpu_operator.payload import heartbeat as heartbeat_mod

    posts = []
    r = heartbeat_mod.HeartbeatReporter(
        "http://x:1", "job", poster=lambda _u, b: posts.append(b),
        clock=lambda: 0.0)
    assert r.report(5, {"loss": 1.0},
                    checkpoint={"lastCheckpointStep": 4, "saveFailures": 1,
                                "restoreFallbacks": 2})
    body = posts[0]
    assert body["lastCheckpointStep"] == 4
    assert body["checkpointSaveFailures"] == 1
    assert body["checkpointRestoreFallbacks"] == 2
    # stats without a verified step yet: the step field is simply absent
    assert r.report(6, None, checkpoint={"saveFailures": 0,
                                         "restoreFallbacks": 0})
    assert "lastCheckpointStep" not in posts[1]


def test_statusserver_accepts_and_gauges_checkpoint_fields():
    from tpu_operator.controller.statusserver import StatusServer

    server = StatusServer(0)
    try:
        ok, msg = server.record_heartbeat(
            {"name": "x", "lastCheckpointStep": -1})
        assert not ok and "negative" in msg
        ok, msg = server.record_heartbeat(
            {"name": "x", "checkpointSaveFailures": "nan"})
        assert not ok
    finally:
        server.server.server_close()


def test_controller_folds_checkpoint_into_status_and_metrics():
    from tpu_operator.apis.tpujob.v1alpha1.types import TPUJob
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.controller.controller import Controller
    from tpu_operator.trainer.training import TrainingJob

    def job_dict(name):
        return {
            "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"replicaSpecs": [{
                "replicas": 1, "tpuReplicaType": "WORKER", "tpuPort": 8476,
                "template": {"spec": {"containers": [{"name": "tpu"}]}}}]},
        }

    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=3600.0)
    job = TPUJob.from_dict(job_dict("ck"))
    tj = TrainingJob(cs, None, job)
    controller.jobs["default/ck"] = tj

    hb1 = {"time": "2026-08-03T00:00:00.000000Z", "step": 4, "attempt": 0,
           "lastCheckpointStep": 4, "checkpointSaveFailures": 1,
           "checkpointRestoreFallbacks": 0}
    assert controller.record_heartbeat("default", "ck", hb1)
    ck = tj.job.status.checkpoint
    assert ck["lastCheckpointStep"] == 4
    assert ck["saveFailures"] == 1
    assert ck["restoreFallbacks"] == 0

    # same attempt, counters advance: only the DELTA is added
    hb2 = {"time": "2026-08-03T00:00:10.000000Z", "step": 8, "attempt": 0,
           "lastCheckpointStep": 8, "checkpointSaveFailures": 3,
           "checkpointRestoreFallbacks": 1}
    assert controller.record_heartbeat("default", "ck", hb2)
    ck = tj.job.status.checkpoint
    assert ck["saveFailures"] == 3
    assert ck["restoreFallbacks"] == 1

    # new attempt: the payload's per-attempt counters reset; totals keep
    # accumulating instead of double-counting or going backwards
    hb3 = {"time": "2026-08-03T00:00:20.000000Z", "step": 8, "attempt": 1,
           "lastCheckpointStep": 8, "checkpointSaveFailures": 2,
           "checkpointRestoreFallbacks": 1}
    assert controller.record_heartbeat("default", "ck", hb3)
    ck = tj.job.status.checkpoint
    assert ck["saveFailures"] == 5       # 3 + 2 (fresh attempt baseline)
    assert ck["restoreFallbacks"] == 2   # 1 + 1
    assert ck["attempt"] == 1

    snap = controller.metrics.snapshot()
    assert snap["job_checkpoint_save_failures_total"] == 5
    assert snap["job_checkpoint_restore_fallbacks_total"] == 2

    # a liveness-only heartbeat must not erase the checkpoint fields from
    # lastHeartbeat (merge) nor disturb status.checkpoint
    hb4 = {"time": "2026-08-03T00:00:30.000000Z", "attempt": 1}
    assert controller.record_heartbeat("default", "ck", hb4)
    assert tj.job.status.last_heartbeat["lastCheckpointStep"] == 8
    assert tj.job.status.checkpoint["saveFailures"] == 5


def test_failure_ledger_records_resume_step():
    from tpu_operator.apis.tpujob.v1alpha1.types import FailureKind, TPUJob
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.trainer.training import TrainingJob

    job = TPUJob.from_dict({
        "metadata": {"name": "r", "namespace": "default"},
        "spec": {"replicaSpecs": []},
    })
    job.status.checkpoint = {"lastCheckpointStep": 42}
    tj = TrainingJob(FakeClientset(), None, job)
    tj._record_failure(0, FailureKind.PREEMPTION, "slice preempted")
    (rec,) = job.status.failures
    assert rec.resume_step == 42
    assert rec.to_dict()["resumeStep"] == 42

    # no checkpoint state known: the record says so (cold restart)
    job2 = TPUJob.from_dict({
        "metadata": {"name": "r2", "namespace": "default"},
        "spec": {"replicaSpecs": []},
    })
    tj2 = TrainingJob(FakeClientset(), None, job2)
    tj2._record_failure(0, FailureKind.APPLICATION, "crash")
    (rec2,) = job2.status.failures
    assert rec2.resume_step is None
    assert "resumeStep" not in rec2.to_dict()


def test_status_checkpoint_round_trips_strict_schema():
    from tpu_operator.apis.tpujob.v1alpha1 import schema
    from tpu_operator.apis.tpujob.v1alpha1.types import TPUJobStatus

    status = TPUJobStatus.from_dict({
        "phase": "Running", "state": "Running", "attempt": 1,
        "checkpoint": {"lastCheckpointStep": 8, "saveFailures": 2,
                       "restoreFallbacks": 1, "attempt": 1,
                       "attemptSaveFailures": 2,
                       "attemptRestoreFallbacks": 1,
                       "time": "2026-08-03T00:00:00.000000Z"},
        "lastHeartbeat": {"step": 9, "lastCheckpointStep": 8,
                          "checkpointSaveFailures": 2,
                          "checkpointRestoreFallbacks": 1,
                          "time": "2026-08-03T00:00:00.000000Z"},
        "failures": [{"attempt": 0, "kind": "preemption", "reason": "x",
                      "time": "2026-08-03T00:00:00.000000Z",
                      "resumeStep": 6}],
    })
    body = {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "s"},
        "spec": {"replicaSpecs": []},
        "status": status.to_dict(),
    }
    ok, msg = schema.validate_tpujob_strict(body)
    assert ok, msg
    back = TPUJobStatus.from_dict(status.to_dict())
    assert back.checkpoint == status.checkpoint
    assert back.failures[0].resume_step == 6


def test_from_env_or_args_passes_fail_after(tmp_path):
    ck = checkpoint.from_env_or_args(
        "", env={"TPU_CHECKPOINT_DIR": str(tmp_path / "ck")}, fail_after=7)
    assert ck is not None
    assert ck.fail_after == 7
    ck.close()
