"""DCN-aware hybrid mesh tests (8-device CPU mesh standing in for 2 slices).

Multi-slice jobs (MEGASCALE_NUM_SLICES in the operator env contract) must
get a mesh whose inner axis never crosses a slice boundary: inner-axis
collectives are per-op and must stay on ICI; only the once-per-step data
psum may ride DCN.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from tpu_operator.payload import bootstrap, train


def test_inner_axis_stays_within_slice():
    # 8 devices, 2 "slices" (first 4 / last 4 by order), model_parallel=2:
    # every model-axis pair must come from one slice.
    devices = jax.devices()[:8]
    mesh = train.make_mesh(8, model_parallel=2, devices=devices, num_slices=2)
    slice_of = {d: (0 if i < 4 else 1) for i, d in enumerate(devices)}
    for row in mesh.devices:  # rows = data axis, columns = model axis
        assert len({slice_of[d] for d in row}) == 1


def test_data_axis_spans_slices():
    devices = jax.devices()[:8]
    mesh = train.make_mesh(8, model_parallel=2, devices=devices, num_slices=2)
    col_slices = {0 if list(jax.devices()[:8]).index(d) < 4 else 1
                  for d in mesh.devices[:, 0]}
    assert col_slices == {0, 1}


def test_inner_axis_must_fit_in_one_slice():
    with pytest.raises(ValueError, match="ICI"):
        train.make_mesh(8, model_parallel=8, devices=jax.devices()[:8],
                        num_slices=2)
    with pytest.raises(ValueError, match="num_slices"):
        train.make_mesh(6, model_parallel=1, devices=jax.devices()[:6],
                        num_slices=4)


def test_single_slice_unchanged():
    a = train.make_mesh(8, model_parallel=2, devices=jax.devices()[:8])
    b = train.make_mesh(8, model_parallel=2, devices=jax.devices()[:8],
                        num_slices=1)
    assert (a.devices == b.devices).all()


def test_process_info_carries_slice_env():
    info = bootstrap.process_info_from_env({
        "MEGASCALE_NUM_SLICES": "4", "MEGASCALE_SLICE_ID": "2",
        "JAX_COORDINATOR_ADDRESS": "w0:1234",
    })
    assert info.num_slices == 4 and info.slice_id == 2


def test_multislice_train_step_executes():
    # End-to-end: a DP×TP cifar step on the hybrid (2-slice) mesh layout.
    from tpu_operator.payload import cifar, data as data_mod

    args = cifar.parse_args(["--batch", "16", "--blocks", "1",
                             "--widths", "8", "8", "8",
                             "--model-parallel", "2"])
    mesh = train.make_mesh(8, model_parallel=2, devices=jax.devices()[:8],
                           num_slices=2)
    mesh, _m, state, step, batches = cifar.build(args, mesh=mesh)
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    state, metrics = step(state, *arrays)
    assert np.isfinite(float(metrics["loss"]))
