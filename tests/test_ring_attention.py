"""Ring attention + sequence-parallel transformer tests (8-device CPU mesh).

The long-context capability checklist (SURVEY.md §2/§5 required inventory:
sequence/context parallelism): exact parity of ring attention against vanilla
attention — forward and gradients, causal and full — plus the transformer LM
payload training end-to-end with the sequence dimension sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_operator.payload import ring_attention as ring
from tpu_operator.payload import transformer


def qkv(seed: int, b=2, t=64, h=2, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.fixture(scope="module")
def mesh():
    return transformer.make_lm_mesh(8, seq_parallel=4)  # (data=2, seq=4)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_ring_matches_reference_forward(mesh, causal):
    q, k, v = qkv(0)
    want = ring.reference_attention(q, k, v, causal=causal)
    got = ring.ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_matches_reference_gradients(mesh):
    q, k, v = qkv(1)

    def loss_ring(q, k, v):
        out = ring.ring_attention(q, k, v, mesh, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        out = ring.reference_attention(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_ring_under_jit_with_uneven_ring_position(mesh):
    # Shifted/jitted path: inside jit, on bf16 inputs (MXU dtype), with a
    # sequence length that gives each shard multiple blocks of queries.
    q, k, v = qkv(2, t=32, dtype=jnp.bfloat16)
    want = ring.reference_attention(q, k, v, causal=True)
    got = jax.jit(
        lambda q, k, v: ring.ring_attention(q, k, v, mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=2e-2, rtol=2e-2)


def test_causal_first_position_attends_only_itself(mesh):
    # Position 0's output must be exactly v[0] under causal masking — a
    # direct probe that no future key leaks across ring steps.
    q, k, v = qkv(3)
    out = ring.ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, 0]), np.asarray(v[:, 0]),
                               atol=1e-5, rtol=1e-5)


def test_transformer_seq_parallel_matches_single_device_loss():
    # Same weights, same batch: loss computed on the (data=2, seq=4) mesh
    # must equal the unsharded single-device loss.
    args = transformer.parse_args([
        "--batch", "4", "--seq-len", "64", "--dim", "32", "--heads", "2",
        "--layers", "2", "--seq-parallel", "4",
    ])
    mesh_sp = transformer.make_lm_mesh(8, seq_parallel=4)
    mesh_1 = transformer.make_lm_mesh(1, seq_parallel=1)
    _, _, state_sp, step_sp, batches = transformer.build(args, mesh=mesh_sp)

    args1 = transformer.parse_args([
        "--batch", "4", "--seq-len", "64", "--dim", "32", "--heads", "2",
        "--layers", "2", "--seq-parallel", "1",
    ])
    _, _, state_1, step_1, _ = transformer.build(args1, mesh=mesh_1)

    from tpu_operator.payload import data as data_mod
    from jax.sharding import PartitionSpec as P

    (tokens,) = next(batches)
    (dev_sp,) = data_mod.put_global_batch(mesh_sp, tokens, spec=P("data", "seq"))
    (dev_1,) = data_mod.put_global_batch(mesh_1, tokens, spec=P())
    _, m_sp = step_sp(state_sp, dev_sp)
    _, m_1 = step_1(state_1, dev_1)
    assert abs(float(m_sp["loss"]) - float(m_1["loss"])) < 2e-2


def test_transformer_lm_loss_descends_seq_parallel():
    args = transformer.parse_args([
        "--steps", "30", "--batch", "8", "--seq-len", "64", "--dim", "64",
        "--heads", "2", "--layers", "2", "--seq-parallel", "4",
        "--log-every", "0", "--lr", "1e-2",
    ])
    mesh, _model, state, step, batches = transformer.build(
        args, mesh=transformer.make_lm_mesh(8, seq_parallel=4))

    from tpu_operator.payload import data as data_mod
    from jax.sharding import PartitionSpec as P

    losses = []
    for _ in range(args.steps):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", "seq"))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_stripe_permutation_layout():
    perm, inv = ring.stripe_permutation(16, 4)
    # shard r's contiguous slice holds global positions r, r+N, r+2N, ...
    assert list(perm[:4]) == [0, 4, 8, 12]
    assert list(perm[4:8]) == [1, 5, 9, 13]
    np.testing.assert_array_equal(perm[inv], np.arange(16))


def test_striped_ring_matches_reference(mesh):
    # Arrays permuted into the striped layout, ring told stripe=True,
    # output unpermuted: must equal dense attention on the true positions.
    q, k, v = qkv(4, t=64)
    perm, inv = ring.stripe_permutation(64, 4)
    qs, ks, vs = (x[:, perm] for x in (q, k, v))
    got_s = ring.ring_attention(qs, ks, vs, mesh, causal=True, stripe=True)
    got = np.asarray(got_s)[:, inv]
    want = ring.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-5, rtol=1e-5)


def test_striped_ring_gradients_match_reference(mesh):
    q, k, v = qkv(5, t=64)
    perm, inv = ring.stripe_permutation(64, 4)

    def loss_striped(q, k, v):
        out = ring.ring_attention(q[:, perm], k[:, perm], v[:, perm],
                                  mesh, causal=True, stripe=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        out = ring.reference_attention(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    got = jax.grad(loss_striped, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   atol=1e-4, rtol=1e-4)


def _causal_pairs_per_rank(t: int, shards: int, striped: bool):
    """Unmasked (q, k) element pairs each rank computes across the whole
    ring — the per-rank attention FLOP count, from the same position math
    the kernels mask with."""
    import numpy as np

    c = t // shards
    if striped:
        pos = [np.array([r + shards * i for i in range(c)])
               for r in range(shards)]
    else:
        pos = [np.arange(r * c, (r + 1) * c) for r in range(shards)]
    all_pos = np.arange(t)
    return [int((p[:, None] >= all_pos[None, :]).sum()) for p in pos]


def test_striped_layout_balances_causal_work():
    # The point of striping: per-rank causal work max/min ~1, while the
    # contiguous layout's last rank does ~2x the mean (and the first ~0).
    contig = _causal_pairs_per_rank(1024, 8, striped=False)
    strip = _causal_pairs_per_rank(1024, 8, striped=True)
    assert sum(contig) == sum(strip)  # same total work
    assert max(contig) / min(contig) > 10  # contiguous: wildly skewed
    # striped: rank r's extra work vs rank 0 is exactly C*r element pairs
    # (one slot-pair per slot) — max/min = 1 + (N-1)/(N(C+1)/2 + ...) ≈ 1.4%
    # at T=1024 N=8, shrinking as C grows.
    assert max(strip) / min(strip) < 1.02
    assert max(contig) / (sum(contig) / 8) > 1.7  # ring critical path ~2x


def test_transformer_striped_loss_matches_contiguous():
    base = ["--batch", "4", "--seq-len", "64", "--dim", "32", "--heads",
            "2", "--layers", "2", "--seq-parallel", "4"]
    mesh_sp = transformer.make_lm_mesh(8, seq_parallel=4)
    args_c = transformer.parse_args(base)
    args_s = transformer.parse_args(base + ["--sp-layout", "striped"])
    _, _, st_c, step_c, batches = transformer.build(args_c, mesh=mesh_sp)
    _, _, st_s, step_s, _ = transformer.build(args_s, mesh=mesh_sp)

    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod

    (tokens,) = next(batches)
    (dev,) = data_mod.put_global_batch(mesh_sp, tokens, spec=P("data", "seq"))
    _, m_c = step_c(st_c, dev)
    _, m_s = step_s(st_s, dev)
    # Same params (same seed), same batch, permuted enumeration of the
    # same (position, next-token) pairs: losses must agree.
    assert abs(float(m_c["loss"]) - float(m_s["loss"])) < 1e-4


def test_transformer_striped_loss_descends():
    args = transformer.parse_args([
        "--steps", "30", "--batch", "8", "--seq-len", "64", "--dim", "64",
        "--heads", "2", "--layers", "2", "--seq-parallel", "4",
        "--sp-layout", "striped", "--log-every", "0", "--lr", "1e-2",
    ])
    mesh, _model, state, step, batches = transformer.build(
        args, mesh=transformer.make_lm_mesh(8, seq_parallel=4))

    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod

    losses = []
    for _ in range(args.steps):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", "seq"))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_striped_requires_ring_and_shards():
    import pytest

    with pytest.raises(ValueError, match="ring"):
        transformer.build(transformer.parse_args([
            "--seq-parallel", "4", "--sp-mode", "ulysses",
            "--sp-layout", "striped", "--heads", "4",
        ]), mesh=transformer.make_lm_mesh(8, seq_parallel=4))
    with pytest.raises(ValueError, match="seq-parallel"):
        transformer.build(transformer.parse_args(
            ["--sp-layout", "striped"]),
            mesh=transformer.make_lm_mesh(1))


def test_synthetic_lm_is_deterministic_recurrence():
    from tpu_operator.payload import data as data_mod

    (seq,) = next(data_mod.synthetic_lm(0, batch=4, seq_len=16))
    assert seq.shape == (4, 16) and seq.dtype == np.int32
    np.testing.assert_array_equal(seq[:, 1:], (5 * seq[:, :-1] + 17) % 256)
