"""Prometheus text-format conformance + deterministic-histogram tests.

Every `/metrics` line is parsed by a real exposition-format parser (below)
and checked for the invariants a scraper depends on: HELP/TYPE declared
before samples, valid names, label escaping that round-trips, cumulative
``le`` buckets that are monotone and end at ``+Inf == _count``, and
``_sum``/``_count`` consistency. Histograms are driven by injected fake
clocks, so the asserted bucket contents are exact, not timing-dependent.
"""

import re
import threading
import urllib.request

import pytest

from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.workqueue import RateLimitingQueue
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import Metrics, StatusServer

NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


# --- a real exposition-format parser ----------------------------------------

def _parse_labels(text: str) -> dict:
    """Parse the inside of {...}, honoring \\" \\\\ \\n escapes."""
    labels = {}
    i = 0
    while i < len(text):
        m = re.match(r'([a-zA-Z_][a-zA-Z0-9_]*)="', text[i:])
        assert m, f"bad label segment at {text[i:]!r}"
        key = m.group(1)
        i += m.end()
        value, escaped = [], False
        while i < len(text):
            ch = text[i]
            i += 1
            if escaped:
                assert ch in ('"', "\\", "n"), f"bad escape \\{ch}"
                value.append("\n" if ch == "n" else ch)
                escaped = False
            elif ch == "\\":
                escaped = True
            elif ch == '"':
                break
            else:
                assert ch != "\n", "raw newline in label value"
                value.append(ch)
        labels[key] = "".join(value)
        if i < len(text) and text[i] == ",":
            i += 1
    return labels


def parse_exposition(body: str):
    """text → {family: {"type": t, "help": h, "samples": [(name, labels, value)]}}
    Asserts structural validity while parsing."""
    families = {}
    declared_help, declared_type = {}, {}
    assert body.endswith("\n"), "exposition must end with a newline"
    for line in body.splitlines():
        assert line.strip() == line, f"stray whitespace: {line!r}"
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            assert NAME_RE.match(name), name
            assert name not in declared_help, f"duplicate HELP for {name}"
            declared_help[name] = help_text
            families.setdefault(name, {"help": help_text, "samples": []})
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            assert NAME_RE.match(name), name
            assert mtype in ("counter", "gauge", "histogram", "summary",
                             "untyped"), mtype
            assert name in declared_help, f"TYPE before HELP for {name}"
            assert name not in declared_type, f"duplicate TYPE for {name}"
            declared_type[name] = mtype
            families[name]["type"] = mtype
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$", line)
        assert m, f"unparseable sample line: {line!r}"
        sample_name, label_blob, value_text = m.groups()
        labels = _parse_labels(label_blob[1:-1]) if label_blob else {}
        for k in labels:
            assert LABEL_RE.match(k), k
        value = float(value_text)
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            trimmed = sample_name[: -len(suffix)]
            if sample_name.endswith(suffix) and declared_type.get(trimmed) \
                    == "histogram":
                base = trimmed
                break
        assert base in declared_type, \
            f"sample {sample_name} before its TYPE declaration"
        families[base]["samples"].append((sample_name, labels, value))
    return families


def assert_conformant(body: str):
    families = parse_exposition(body)
    seen_series = set()
    for name, fam in families.items():
        mtype = fam.get("type")
        assert mtype, f"{name} has HELP but no TYPE"
        if mtype == "histogram":
            _assert_histogram(name, fam["samples"])
        else:
            for sample_name, labels, value in fam["samples"]:
                assert sample_name == name
                if mtype == "counter":
                    assert value >= 0, f"negative counter {name}"
                key = (sample_name, tuple(sorted(labels.items())))
                assert key not in seen_series, f"duplicate series {key}"
                seen_series.add(key)
    return families


def _assert_histogram(name, samples):
    # group by non-le labels
    series = {}
    for sample_name, labels, value in samples:
        rest = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        series.setdefault(rest, {"buckets": [], "sum": None, "count": None})
        s = series[rest]
        if sample_name == f"{name}_bucket":
            s["buckets"].append((labels["le"], value))
        elif sample_name == f"{name}_sum":
            assert s["sum"] is None, f"duplicate {name}_sum"
            s["sum"] = value
        elif sample_name == f"{name}_count":
            assert s["count"] is None, f"duplicate {name}_count"
            s["count"] = value
        else:
            raise AssertionError(f"stray histogram sample {sample_name}")
    for key, s in series.items():
        assert s["buckets"], f"{name}{dict(key)}: no buckets"
        bounds = [float("inf") if le == "+Inf" else float(le)
                  for le, _ in s["buckets"]]
        counts = [c for _, c in s["buckets"]]
        assert bounds == sorted(bounds), f"{name}: le bounds out of order"
        assert bounds[-1] == float("inf"), f"{name}: missing +Inf bucket"
        assert counts == sorted(counts), \
            f"{name}: bucket counts not monotone: {counts}"
        assert s["count"] is not None and s["sum"] is not None, \
            f"{name}: missing _sum/_count"
        assert counts[-1] == s["count"], \
            f"{name}: +Inf bucket {counts[-1]} != _count {s['count']}"
        if s["count"] == 0:
            assert s["sum"] == 0


# --- full /metrics surface over HTTP -----------------------------------------

def scrape(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        assert "text/plain" in r.headers.get("Content-Type", "")
        return r.read().decode()


def test_full_metrics_surface_is_conformant():
    """Drive the whole pipeline deterministically — queue latency through
    fake-clock backoff, reconcile durations, heartbeat gauges, weird label
    values — then validate every line of the real scrape."""
    clock = FakeClock()
    cs = FakeClientset()
    metrics = Metrics()
    queue = RateLimitingQueue(base_delay=10.0, max_delay=360.0,
                              clock=clock, metrics=metrics)
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            queue=queue, metrics=metrics, clock=clock)
    server = StatusServer(0, metrics=metrics)
    server.start()
    try:
        server.set_controller(controller)
        stop = threading.Event()
        th = threading.Thread(target=controller.run, args=(1, stop),
                              daemon=True)
        th.start()
        try:
            cs.tpujobs.create("default", {
                "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
                "metadata": {"name": "conf", "namespace": "default"},
                "spec": {"replicaSpecs": [{
                    "replicas": 1, "tpuReplicaType": "WORKER",
                    "tpuPort": 8476,
                    "template": {"spec": {"containers": [
                        {"name": "tpu", "image": "x"}]}}}]},
            })
            deadline = threading.Event()
            for _ in range(100):
                if cs.pods.list("default"):
                    break
                deadline.wait(0.05)
            assert cs.pods.list("default"), "reconcile never created the pod"
        finally:
            stop.set()
            th.join(timeout=5)

        # weird-but-legal label values must round-trip the escaper
        metrics.set_gauge("escape_check", 1,
                          labels={"path": 'a\\b"c\nd'})
        # heartbeat → per-job gauges
        ok, _ = server.record_heartbeat({
            "namespace": "default", "name": "conf", "step": 7,
            "stepTimeSeconds": 0.25, "tokensPerSec": 1024.5, "loss": 2.5})
        assert ok

        body = scrape(server.port)
        families = assert_conformant(body)

        p = "tpu_operator_"
        for required in (f"{p}reconcile_duration_seconds",
                         f"{p}workqueue_queue_duration_seconds",
                         f"{p}workqueue_work_duration_seconds",
                         f"{p}job_time_to_running_seconds",
                         f"{p}job_time_to_scheduled_seconds",
                         f"{p}job_runtime_seconds",
                         f"{p}reconcile_total",
                         f"{p}reconcile_errors_total",
                         f"{p}gc_deleted_total",
                         f"{p}api_requests_total",
                         f"{p}leader_elections_won_total",
                         f"{p}workqueue_adds_total",
                         f"{p}workqueue_depth",
                         f"{p}workqueue_unfinished_work_seconds",
                         f"{p}workqueue_longest_running_processor_seconds",
                         f"{p}jobs"):
            assert required in families, f"missing family {required}"
            assert families[required]["samples"], f"empty family {required}"
        # the heartbeat posted above carries step time / throughput / loss —
        # each must surface as its per-job gauge, job-labeled
        for gauge, value in ((f"{p}job_step_time_seconds", 0.25),
                             (f"{p}job_tokens_per_second", 1024.5),
                             (f"{p}job_loss", 2.5)):
            assert families[gauge]["samples"] == [
                (gauge, {"namespace": "default", "name": "conf"}, value)
            ], f"heartbeat gauge {gauge} missing or wrong"
        # set_controller above won the (fake) election; the controller's
        # clientset ledger ticked real API requests during the reconcile
        won = [v for _n, _l, v
               in families[f"{p}leader_elections_won_total"]["samples"]]
        assert won and won[0] >= 1
        api = sum(v for _n, _l, v
                  in families[f"{p}api_requests_total"]["samples"])
        assert api >= 1
        for fam, expected_type in (
                (f"{p}reconcile_duration_seconds", "histogram"),
                (f"{p}workqueue_queue_duration_seconds", "histogram"),
                (f"{p}workqueue_work_duration_seconds", "histogram"),
                (f"{p}reconcile_total", "counter"),
                (f"{p}workqueue_depth", "gauge")):
            assert families[fam]["type"] == expected_type

        # the reconcile actually ran and was observed
        total = [v for n, _l, v in families[f"{p}reconcile_total"]["samples"]]
        assert total and total[0] >= 1
        count = [v for n, _l, v
                 in families[f"{p}reconcile_duration_seconds"]["samples"]
                 if n.endswith("_count")]
        assert count and count[0] >= 1

        # heartbeat gauges carry the job labels
        hb = families[f"{p}job_last_step"]["samples"]
        assert hb == [(f"{p}job_last_step",
                       {"namespace": "default", "name": "conf"}, 7.0)]
        # escaped label round-tripped
        esc = families[f"{p}escape_check"]["samples"]
        assert esc[0][1] == {"path": 'a\\b"c\nd'}
    finally:
        server.stop()


# --- deterministic histograms via injected clocks ----------------------------

def test_queue_latency_histogram_under_backoff():
    """Queue latency measures add→get through the injected clock, including
    rate-limit backoff — exact bucket placement, no real time involved."""
    clock = FakeClock()
    metrics = Metrics()
    q = RateLimitingQueue(base_delay=10.0, max_delay=360.0,
                          clock=clock, metrics=metrics)

    # plain add, 0.5s queued
    q.add("a")
    clock.advance(0.5)
    assert q.get(timeout=0) == "a"
    # work for 0.05s
    clock.advance(0.05)
    q.done("a")

    # first backoff: 10s base delay + 2s until the worker picks it up
    q.add_rate_limited("a")
    clock.advance(12.0)
    assert q.get(timeout=0) == "a"
    q.done("a")

    # second backoff: 20s
    q.add_rate_limited("a")
    clock.advance(9.9)
    assert q.get(timeout=0) is None  # 20s backoff: not due at 9.9
    clock.advance(10.2)
    assert q.get(timeout=0) == "a"
    q.done("a")

    snap = metrics.histogram_snapshot("workqueue_queue_duration_seconds")
    assert snap["count"] == 3
    # 0.5 → le=1; 12.0 → le=30; 20.1 → le=30
    assert snap["buckets"]["1"] == 1
    assert snap["buckets"]["10"] == 1
    assert snap["buckets"]["30"] == 3
    assert snap["sum"] == pytest.approx(0.5 + 12.0 + 20.1)

    work = metrics.histogram_snapshot("workqueue_work_duration_seconds")
    assert work["count"] == 3
    # two zero-duration cycles plus one ~0.05s one (float add puts it a hair
    # above the 0.05 bound, so it cumulates at le=0.1)
    assert work["buckets"]["0.001"] == 2
    assert work["buckets"]["0.1"] == 3

    assert metrics.snapshot()["workqueue_adds_total"] == 1
    assert metrics.snapshot()["workqueue_retries_total"] == 2


def test_unfinished_and_longest_running_gauges():
    clock = FakeClock()
    q = RateLimitingQueue(clock=clock, metrics=Metrics())
    q.add("a")
    q.add("b")
    assert q.get(timeout=0) == "a"
    clock.advance(3.0)
    assert q.get(timeout=0) == "b"
    clock.advance(2.0)
    assert q.unfinished_work_seconds() == pytest.approx(5.0 + 2.0)
    assert q.longest_running_processor_seconds() == pytest.approx(5.0)
    q.done("a")
    assert q.longest_running_processor_seconds() == pytest.approx(2.0)
    q.done("b")
    assert q.unfinished_work_seconds() == 0.0
    assert q.longest_running_processor_seconds() == 0.0


def test_queue_is_shutdown_property():
    q = RateLimitingQueue()
    assert not q.is_shutdown
    q.shutdown()
    assert q.is_shutdown


def test_histogram_out_of_range_lands_in_inf():
    m = Metrics()
    m.observe("reconcile_duration_seconds", 99.0)  # beyond last bound (10)
    snap = m.histogram_snapshot("reconcile_duration_seconds")
    assert snap["count"] == 1
    assert snap["buckets"]["10"] == 0
    assert snap["buckets"]["+Inf"] == 1
    assert snap["sum"] == pytest.approx(99.0)


def test_labeled_counter_series():
    m = Metrics()
    m.inc("requests_total", labels={"code": "200"})
    m.inc("requests_total", 2, labels={"code": "500"})
    body = "\n".join(m.render_lines()) + "\n"
    families = assert_conformant(body)
    samples = families["tpu_operator_requests_total"]["samples"]
    by_code = {l.get("code", ""): v for _n, l, v in samples}
    assert by_code["200"] == 1 and by_code["500"] == 2


def test_fresh_registry_renders_conformant_zero_state():
    """All pre-registered families render valid zero series before any
    activity — a scraper pointed at a just-started operator sees a full,
    parseable catalog."""
    body = "\n".join(Metrics().render_lines()) + "\n"
    families = assert_conformant(body)
    assert "tpu_operator_reconcile_duration_seconds" in families
    zero = families["tpu_operator_reconcile_duration_seconds"]["samples"]
    assert any(n.endswith("_count") and v == 0 for n, _l, v in zero)
