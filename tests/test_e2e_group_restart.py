"""HTTP-tier e2e: whole-group restart through the real operator binary.

The TPU semantic the reference lacked, exercised over the wire: a worker
preempted with exit 137 (retryable band) triggers deletion of the whole
attempt's pods and a fresh gang at attempt 1; a clean exit 0 on the next
attempt completes the job. The fake-clientset tier covers the same flow
in-process (test_informer_controller); this tier proves the operator
*binary* does it against an HTTP apiserver — process boundary included.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import time

import pytest

from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=60.0, interval=0.25)


@pytest.fixture
def operator_env():
    harness = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=harness.url, timeout=5.0))
    op = subprocess.Popen(
        [sys.executable, "-m", "tpu_operator.cmd.main", "--master",
         harness.url, "--namespace", "default"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    yield cs
    op.send_signal(signal.SIGINT)
    try:
        op.wait(timeout=10)
    except subprocess.TimeoutExpired:
        op.kill()
    harness.stop()


def _set_pod_state(cs, pod, phase, container_state):
    pod["status"] = {
        "phase": phase,
        "containerStatuses": [{"name": "tpu", "state": container_state}],
    }
    cs.pods.update("default", pod)


def test_preemption_triggers_group_restart_then_success(operator_env):
    cs = operator_env
    cs.tpujobs.create("default", {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "restarts", "namespace": "default"},
        "spec": {"replicaSpecs": [{
            "replicas": 2, "tpuReplicaType": "WORKER", "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu"}]}}}]},
    })

    def pods_at(attempt, n=2):
        return [p for p in cs.pods.list("default")
                if p["metadata"]["labels"].get("attempt") == str(attempt)]

    assert wait_for(lambda: len(pods_at(0)) == 2), cs.pods.list("default")
    for p in pods_at(0):
        _set_pod_state(cs, p, "Running", {"running": {}})
    assert wait_for(lambda: cs.tpujobs.get("default", "restarts")
                    .get("status", {}).get("phase") == "Running")

    # preempt one worker: retryable band (143/137-style) ⇒ the WHOLE group
    # restarts, not just the dead pod
    victim = pods_at(0)[0]
    _set_pod_state(cs, victim, "Failed",
                   {"terminated": {"exitCode": 137}})

    assert wait_for(lambda: len(pods_at(1)) == 2, timeout=90.0), [
        (p["metadata"]["name"], p["metadata"]["labels"].get("attempt"))
        for p in cs.pods.list("default")]
    assert cs.tpujobs.get("default", "restarts")["status"].get("attempt") == 1
    # no attempt-0 stragglers — the old gang is gone
    assert wait_for(lambda: len(pods_at(0)) == 0, timeout=30.0)

    for p in pods_at(1):
        _set_pod_state(cs, p, "Succeeded",
                       {"terminated": {"exitCode": 0}})
    assert wait_for(
        lambda: cs.tpujobs.get("default", "restarts")
        .get("status", {}).get("phase") == "Done", timeout=90.0)
    assert (cs.tpujobs.get("default", "restarts")["status"].get("state")
            == "Succeeded")


def test_suspend_resume_through_operator_binary(operator_env):
    """User PATCHes spec.suspend over the wire; the operator tears down the
    gang (slice freed), parks the job Suspended, and re-gangs the SAME
    attempt on resume — then the job runs to completion."""
    cs = operator_env
    cs.tpujobs.create("default", {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "parkable", "namespace": "default"},
        "spec": {"replicaSpecs": [{
            "replicas": 2, "tpuReplicaType": "WORKER", "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu"}]}}}]},
    })

    assert wait_for(lambda: len(cs.pods.list("default")) == 2)
    for p in cs.pods.list("default"):
        _set_pod_state(cs, p, "Running", {"running": {}})
    assert wait_for(lambda: cs.tpujobs.get("default", "parkable")
                    .get("status", {}).get("phase") == "Running")

    job = cs.tpujobs.get("default", "parkable")
    job["spec"]["suspend"] = True
    cs.tpujobs.update("default", job)
    assert wait_for(lambda: cs.tpujobs.get("default", "parkable")
                    .get("status", {}).get("phase") == "Suspended")
    assert wait_for(lambda: cs.pods.list("default") == [])

    job = cs.tpujobs.get("default", "parkable")
    job["spec"]["suspend"] = False
    cs.tpujobs.update("default", job)
    assert wait_for(lambda: len(cs.pods.list("default")) == 2, timeout=90.0)
    pods = cs.pods.list("default")
    assert all(p["metadata"]["labels"]["attempt"] == "0" for p in pods)
    assert cs.tpujobs.get("default", "parkable")["status"].get("attempt") == 0

    for p in pods:
        _set_pod_state(cs, p, "Succeeded", {"terminated": {"exitCode": 0}})
    assert wait_for(lambda: cs.tpujobs.get("default", "parkable")
                    .get("status", {}).get("phase") == "Done", timeout=90.0)
