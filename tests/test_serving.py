"""Serving mode: long-lived inference gangs under the TPUJob CRD.

``spec.mode: serve`` — each WORKER replica is an independent batched
decode server: per-replica Services exist only while the replica's
payload posts ``ready`` serving beats; weights hot-reload from the
remote store (newer VERIFIED snapshot → rolling reload, loadedStep
advances, NO attempt bump); the replica count follows the requests/sec
signal within ``spec.serving`` through the fleet scheduler's queue.

The e2es at the bottom are the acceptance flows over the in-process
apiserver: a serve gang reaches ``replicasReady == replicas`` with real
decode loops posting through the real status server, hot-reloads a
newer snapshot with ``status.serving.loadedStep`` advancing while
``status.attempt`` and ``job_elastic_resizes_total`` stay untouched,
and scales up then down on a traffic change through the admission
queue. The strict-schema apiserver validates every status write.
"""

import contextlib
import io
import os
import threading
import time

import pytest

from tpu_operator.apis.tpujob import validation
from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.payload import serve as serve_mod
from tpu_operator.scheduler.inventory import slice_key
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.trainer import replicas as replicas_mod
from tpu_operator.trainer.training import TrainingJob

V4 = "cloud-tpus.google.com/v4"
KEY = slice_key(V4, "2x2x2")

wait_for = make_wait_for(timeout=20.0, interval=0.05)


def make_template(tpu_chips=0):
    c = {"name": "tpu", "image": "x"}
    if tpu_chips:
        c["resources"] = {"requests": {V4: str(tpu_chips)}}
    return {"spec": {"containers": [c]}}


def serve_job(name="sv", replicas=3, min_replicas=1, max_replicas=0,
              target=2.0, num_slices=1, tpu_chips=0, uid=None,
              policy=t.StragglerPolicy.NONE, **spec_kw):
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=replicas, template=make_template(tpu_chips),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="sv01",
        mode=t.JobMode.SERVE,
        num_slices=num_slices,
        serving=t.ServingSpec(
            min_replicas=min_replicas, max_replicas=max_replicas,
            target_requests_per_second_per_replica=target,
            straggler_policy=policy),
        **spec_kw,
    )
    if tpu_chips:
        spec.tpu_topology = "2x2x2"
    return t.TPUJob(metadata={"name": name, "namespace": "default",
                              "uid": uid or f"uid-{name}"}, spec=spec)


def pod_env(pod):
    return {e["name"]: e["value"]
            for e in pod["spec"]["containers"][0]["env"]}


def live_pods(cs):
    return [p for p in cs.pods.list("default")
            if (p.get("status") or {}).get("phase") not in ("Succeeded",
                                                            "Failed")]


def service_names(cs):
    return {s["metadata"]["name"] for s in cs.services.list("default")}


# --- spec plumbing -----------------------------------------------------------


def test_serving_spec_roundtrip():
    job = serve_job(min_replicas=2, max_replicas=6, target=50.0)
    wire = job.to_dict()
    assert wire["spec"]["mode"] == "serve"
    assert wire["spec"]["serving"] == {
        "minReplicas": 2, "maxReplicas": 6,
        "targetRequestsPerSecondPerReplica": 50.0,
        "reloadPollSeconds": t.DEFAULT_SERVE_RELOAD_POLL,
        "stragglerPolicy": "none",
        "stragglerPatienceSeconds": t.DEFAULT_STRAGGLER_PATIENCE}
    back = t.TPUJob.from_dict(wire)
    assert back.spec.mode == t.JobMode.SERVE
    assert back.spec.serving.max_replicas == 6
    assert back.spec.serving.target_requests_per_second_per_replica == 50.0
    # Absent mode/serving stay absent (train specs round-trip unchanged).
    bare = t.TPUJobSpec.from_dict({"replicaSpecs": []})
    assert bare.mode == "" and bare.serving is None
    assert "mode" not in bare.to_dict() and "serving" not in bare.to_dict()


def test_store_keep_snapshots_roundtrip():
    spec = t.TPUJobSpec.from_dict({
        "replicaSpecs": [],
        "store": {"backend": "fake", "uri": "fake://t",
                  "keepSnapshots": 3}})
    assert spec.store.keep_snapshots == 3
    assert spec.to_dict()["store"]["keepSnapshots"] == 3
    # Default 0 = keep everything, kept off the wire.
    spec2 = t.TPUJobSpec.from_dict({
        "replicaSpecs": [], "store": {"backend": "fake",
                                      "uri": "fake://t"}})
    assert spec2.store.keep_snapshots == 0
    assert "keepSnapshots" not in spec2.to_dict()["store"]


def test_serving_strict_schema():
    job = serve_job()
    set_defaults(job.spec)
    ok, msg = schema_mod.validate_tpujob_strict(job.to_dict())
    assert ok, msg
    # Unknown serving field rejected (the typo-catching contract).
    wire = job.to_dict()
    wire["spec"]["serving"]["replicasMax"] = 5
    ok, msg = schema_mod.validate_tpujob_strict(wire)
    assert not ok and "replicasMax" in msg
    # status.serving round-trips the controller's roll-up shape.
    wire = job.to_dict()
    wire["status"] = {"phase": "Running", "reason": "", "state": "Running",
                      "replicaStatuses": [], "attempt": 0,
                      "serving": {"replicas": 3, "desiredReplicas": 2,
                                  "replicasReady": 3,
                                  "requestsPerSecond": 5.5,
                                  "p50LatencySeconds": 0.01,
                                  "p95LatencySeconds": 0.02,
                                  "loadedStep": 40, "reloads": 2,
                                  "attemptReloads": {"0": 1, "1": 1},
                                  "attempt": 0,
                                  "time": "2026-08-04T00:00:00Z"}}
    ok, msg = schema_mod.validate_tpujob_strict(wire)
    assert ok, msg


def test_serve_defaults():
    job = serve_job(replicas=4)
    set_defaults(job.spec)
    # maxReplicas fills from the WORKER count; the restart policy is
    # PerPod — independent servers, never whole-fleet restarts.
    assert job.spec.serving.max_replicas == 4
    assert job.spec.restart_policy == t.RestartPolicy.PER_POD
    # Mode case-normalizes.
    job2 = serve_job()
    job2.spec.mode = "Serve"
    set_defaults(job2.spec)
    assert job2.spec.mode == "serve"


def test_serve_validation():
    def invalid(mutate, fragment):
        job = serve_job(replicas=2, max_replicas=4)
        mutate(job.spec)
        set_defaults(job.spec)
        with pytest.raises(validation.ValidationError) as e:
            validation.validate_tpujob_spec(job.spec)
        assert fragment in str(e.value), str(e.value)

    def valid(mutate=lambda s: None):
        job = serve_job(replicas=2, max_replicas=4)
        mutate(job.spec)
        set_defaults(job.spec)
        validation.validate_tpujob_spec(job.spec)

    valid()
    invalid(lambda s: setattr(s, "mode", "inference"), "mode")
    invalid(lambda s: setattr(s, "mode", ""), "only meaningful under")
    invalid(lambda s: setattr(s, "restart_policy",
                              t.RestartPolicy.WHOLE_GROUP),
            "requires restartPolicy PerPod")
    invalid(lambda s: setattr(s, "elastic", t.ElasticSpec()),
            "excludes spec.elastic")
    invalid(lambda s: setattr(s.serving, "min_replicas", 0),
            "minReplicas")
    invalid(lambda s: setattr(s.serving, "max_replicas", 1),
            "must lie within")
    invalid(lambda s: setattr(
        s.serving, "target_requests_per_second_per_replica", 0.0),
        "targetRequestsPerSecondPerReplica")
    invalid(lambda s: setattr(s.serving, "reload_poll_seconds", 0),
            "reloadPollSeconds")
    invalid(lambda s: setattr(s.serving, "straggler_policy", "shed"),
            "stragglerPolicy")
    # Slice-per-replica: numSlices > 1 requires replicas == numSlices.
    job = serve_job(replicas=4, num_slices=2, tpu_chips=4)
    set_defaults(job.spec)
    with pytest.raises(validation.ValidationError) as e:
        validation.validate_tpujob_spec(job.spec)
    assert "numSlices" in str(e.value)
    # keepSnapshots must be >= 0.
    job = serve_job()
    job.spec.store = t.StoreSpec(backend="fake", uri="fake://t",
                                 keep_snapshots=-1)
    set_defaults(job.spec)
    with pytest.raises(validation.ValidationError) as e:
        validation.validate_tpujob_spec(job.spec)
    assert "keepSnapshots" in str(e.value)


# --- env contract ------------------------------------------------------------


def test_serve_env_injection():
    job = serve_job(replicas=3)
    job.spec.store = t.StoreSpec(backend="fake", uri="fake://sv",
                                 keep_snapshots=2)
    set_defaults(job.spec)
    env = replicas_mod.build_replica_env(
        "sv", "sv01", job.spec, t.TPUReplicaType.WORKER, 1)
    assert env["TPUJOB_SERVE"] == "1"
    assert env["TPUJOB_SERVE_RELOAD_POLL"] == \
        str(t.DEFAULT_SERVE_RELOAD_POLL)
    # HTTP ingress rides the SAME port the replica Service targets.
    assert env["TPUJOB_SERVE_PORT"] == str(t.DEFAULT_TPU_PORT)
    assert env["TPUJOB_STORE_KEEP"] == "2"
    # Independent servers: no cross-replica process group, identity kept.
    assert env["JAX_NUM_PROCESSES"] == "1"
    assert env["JAX_PROCESS_ID"] == "1"
    assert env["TPU_WORKER_ID"] == "0"
    assert "," not in env["TPU_WORKER_HOSTNAMES"]
    assert not any(k.startswith("MEGASCALE_") for k in env)


def test_train_mode_env_byte_inert():
    """A spec without mode injects NO serving env and the worker contract
    is byte-identical to the pre-serving build."""
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(
            replicas=4, template=make_template(),
            tpu_replica_type=t.TPUReplicaType.WORKER)],
        runtime_id="tr01")
    set_defaults(spec)
    env = replicas_mod.build_replica_env(
        "tr", "tr01", spec, t.TPUReplicaType.WORKER, 1)
    assert not any(k.startswith("TPUJOB_SERVE") for k in env)
    assert "TPUJOB_STORE_KEEP" not in env
    assert env["JAX_NUM_PROCESSES"] == "4"
    assert env["TPU_WORKER_HOSTNAMES"].count(",") == 3


# --- statusserver door -------------------------------------------------------


def serving_body(**kw):
    body = {"ready": True, "requestsPerSecond": 2.5,
            "p50LatencySeconds": 0.01, "p95LatencySeconds": 0.02,
            "loadedStep": 10, "reloads": 1}
    body.update(kw)
    return body


def test_statusserver_serving_door():
    from tpu_operator.controller.statusserver import _sanitize_serving

    clean, err = _sanitize_serving(serving_body())
    assert err == "" and clean["ready"] is True
    assert clean["loadedStep"] == 10
    # The paged-decode signals ride the same strict door.
    clean, err = _sanitize_serving(serving_body(
        tokensPerSecond=120.5, queueDepth=3, kvCacheUtilization=0.75))
    assert err == ""
    assert clean["tokensPerSecond"] == pytest.approx(120.5)
    assert clean["queueDepth"] == 3
    assert clean["kvCacheUtilization"] == pytest.approx(0.75)
    for bad in (serving_body(ready="false"),      # bool("false") is True
                serving_body(ready=1),
                serving_body(requestsPerSecond=-1.0),
                serving_body(p95LatencySeconds=float("nan")),
                serving_body(loadedStep=True),
                serving_body(reloads=-2),
                serving_body(tokensPerSecond=-5.0),
                serving_body(tokensPerSecond=float("inf")),
                serving_body(queueDepth=-1),
                serving_body(queueDepth="deep"),
                serving_body(kvCacheUtilization=float("nan")),
                "not-an-object"):
        clean, err = _sanitize_serving(bad)
        assert clean is None and err, bad
    # Unknown keys drop silently (forward compat), known ones survive.
    clean, err = _sanitize_serving(serving_body(futureKnob=7))
    assert err == "" and "futureKnob" not in clean


def test_statusserver_rejects_bad_serving_beat():
    srv = StatusServer(0)
    try:
        cs = FakeClientset()
        controller = Controller(cs,
                                SharedInformerFactory(cs, resync_period=0),
                                heartbeat_persist_interval=0.0)
        srv.set_controller(controller)
        ok, msg = srv.record_heartbeat({
            "name": "sv", "namespace": "default", "step": 1,
            "serving": serving_body(ready="yes")})
        assert not ok and "serving.ready" in msg
    finally:
        # Never start()ed: close the socket directly (shutdown() would
        # wait on a serve_forever loop that never ran).
        srv.server.server_close()


# --- controller fold ---------------------------------------------------------


def serving_harness(replicas=3, min_replicas=1, max_replicas=0, target=2.0,
                    num_slices=1, tpu_chips=0, capacity=0, **spec_kw):
    now = [1000.0]
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=0.0,
                            wall_clock=lambda: now[0])
    if capacity:
        controller.scheduler.update_inventory({KEY: capacity})
    job = serve_job(replicas=replicas, min_replicas=min_replicas,
                    max_replicas=max_replicas, target=target,
                    num_slices=num_slices, tpu_chips=tpu_chips, **spec_kw)
    cs.tpujobs.create("default", job.to_dict())
    tj = TrainingJob(cs, controller.recorder, job,
                     metrics=controller.metrics,
                     scheduler=controller.scheduler if capacity else None)
    controller.jobs["default/sv"] = tj
    tj.reconcile()

    def beat(pid, sv=None, attempt=None, step=50):
        body = {"time": "2026-08-04T00:00:00.000000Z", "step": step,
                "attempt": (attempt if attempt is not None
                            else tj.job.status.attempt),
                "processId": pid}
        if sv is not None:
            body["serving"] = sv
        return controller.record_heartbeat("default", "sv", body)

    return cs, controller, tj, now, beat


def test_serving_fold_aggregates():
    cs, controller, tj, now, beat = serving_harness(replicas=3)
    beat(0, serving_body(requestsPerSecond=1.0, p95LatencySeconds=0.02,
                         loadedStep=10))
    beat(1, serving_body(requestsPerSecond=2.0, p95LatencySeconds=0.05,
                         loadedStep=12))
    beat(2, serving_body(ready=False, requestsPerSecond=0.5,
                         loadedStep=8))
    sv = tj.job.status.serving
    assert sv["replicasReady"] == 2
    assert sv["requestsPerSecond"] == pytest.approx(3.5)
    # Tail = the WORST ready replica; the not-ready one is excluded.
    assert sv["p95LatencySeconds"] == pytest.approx(0.05)
    # loadedStep = the MINIMUM over ready replicas (the fleet floor).
    assert sv["loadedStep"] == 10
    # desired = ceil(3.5 / 2.0) = 2, within [1, 3].
    assert sv["desiredReplicas"] == 2
    m = controller.metrics
    labels = {"namespace": "default", "name": "sv"}
    assert m.counter_value("job_serving_replicas_ready", labels) == 2
    assert m.counter_value("job_serving_requests_per_second",
                           labels) == pytest.approx(3.5)
    assert m.counter_value("job_serving_latency_seconds",
                           {**labels, "quantile": "0.95"}) \
        == pytest.approx(0.05)


def test_serving_fold_paged_decode_signals():
    """tokensPerSecond and queueDepth are fleet SUMS (every replica's
    queue is real demand, ready or mid-reload); kvCacheUtilization is
    the WORST replica's pool pressure — and all three land on their
    job_serving_* gauges."""
    cs, controller, tj, now, beat = serving_harness(replicas=3)
    beat(0, serving_body(tokensPerSecond=100.0, queueDepth=2,
                         kvCacheUtilization=0.5))
    beat(1, serving_body(tokensPerSecond=50.5, queueDepth=0,
                         kvCacheUtilization=0.9))
    beat(2, serving_body(ready=False, tokensPerSecond=10.0, queueDepth=7,
                         kvCacheUtilization=0.1))
    sv = tj.job.status.serving
    assert sv["tokensPerSecond"] == pytest.approx(160.5)
    assert sv["queueDepth"] == 9
    assert sv["kvCacheUtilization"] == pytest.approx(0.9)
    m = controller.metrics
    labels = {"namespace": "default", "name": "sv"}
    assert m.counter_value("job_serving_tokens_per_second",
                           labels) == pytest.approx(160.5)
    assert m.counter_value("job_serving_queue_depth", labels) == 9
    assert m.counter_value("job_serving_kv_cache_utilization",
                           labels) == pytest.approx(0.9)


def test_serving_reload_delta_accounting():
    cs, controller, tj, now, beat = serving_harness(replicas=2)
    beat(0, serving_body(reloads=1))
    beat(1, serving_body(reloads=1))
    assert tj.job.status.serving["reloads"] == 2
    labels = {"namespace": "default", "name": "sv"}
    assert controller.metrics.counter_value("job_weight_reloads_total",
                                            labels) == 2
    # Re-reporting the same counters adds nothing (baselines held).
    beat(0, serving_body(reloads=1))
    assert tj.job.status.serving["reloads"] == 2
    # A replica restart resets ITS counter; the lifetime total survives.
    beat(0, serving_body(reloads=0))
    beat(0, serving_body(reloads=1))
    assert tj.job.status.serving["reloads"] == 3
    assert controller.metrics.counter_value("job_weight_reloads_total",
                                            labels) == 3


def test_partial_fleet_report_never_scales_down():
    """The real-binary drive regression: the FIRST replica to post after
    a deploy must not shrink the fleet under the still-silent peers —
    a partial fleet report under-counts the aggregate traffic, so a
    scale-DOWN decision waits until every current replica reports
    (scale-UP still acts on partial data: over-provisioning is the safe
    direction for serving)."""
    cs, controller, tj, now, beat = serving_harness(replicas=3, target=2.0)
    # One replica of three posts 1.5 req/s → naive desired would be 1.
    beat(0, serving_body(requestsPerSecond=1.5))
    assert tj.job.status.serving["desiredReplicas"] == 3  # held
    # Partial data may still scale UP.
    beat(0, serving_body(requestsPerSecond=9.0))
    assert tj.job.status.serving["desiredReplicas"] == 3  # ceil(9/2)=5→max 3
    # Every replica reporting: the scale-down is now evidence, not silence.
    beat(0, serving_body(requestsPerSecond=0.5))
    beat(1, serving_body(requestsPerSecond=0.5))
    beat(2, serving_body(requestsPerSecond=0.5))
    assert tj.job.status.serving["desiredReplicas"] == 1


def test_serving_readiness_expiry():
    """A replica that stops posting drops from the ready set after the
    expiry window — a wedged replica must leave routing without posting
    anything."""
    from tpu_operator.controller.controller import SERVING_EXPIRY_SECONDS

    cs, controller, tj, now, beat = serving_harness(replicas=2)
    beat(0, serving_body())
    beat(1, serving_body())
    assert tj.job.status.serving["replicasReady"] == 2
    now[0] += SERVING_EXPIRY_SECONDS + 1
    beat(0, serving_body())
    assert tj.job.status.serving["replicasReady"] == 1


def test_serving_series_pruned_on_deletion():
    cs, controller, tj, now, beat = serving_harness(replicas=2)
    beat(0, serving_body(reloads=1))
    cs.tpujobs.delete("default", "sv")
    # The informer cache is empty in this harness (no informer started),
    # so the sync sees a deleted job and prunes.
    assert controller.sync_tpujob("default/sv") is True
    labels = {"namespace": "default", "name": "sv"}
    m = controller.metrics
    assert m.counter_value("job_serving_replicas_ready", labels) == 0
    assert m.counter_value("job_serving_requests_per_second", labels) == 0
    assert m.counter_value("job_weight_reloads_total", labels) == 0
    assert m.counter_value("job_serving_latency_seconds",
                           {**labels, "quantile": "0.95"}) == 0
    assert "default/sv" not in controller._serving


# --- readiness-gated services ------------------------------------------------


def test_service_gated_on_ready_beat():
    """A per-replica Service must not exist before the replica's ready
    beat; a replica that loses readiness (reload in flight) has its
    Service REMOVED and restored on the next ready beat."""
    cs, controller, tj, now, beat = serving_harness(replicas=2)
    # Pods exist, but no serving beats yet: only the headless Service.
    assert len(live_pods(cs)) == 2
    headless = service_names(cs)
    assert len(headless) == 1  # the job-scoped headless backbone
    svc0, svc1 = (replicas_mod.gen_general_name("sv", "WORKER", "sv01", i)
                  for i in (0, 1))

    beat(0, serving_body())
    tj.reconcile()
    assert svc0 in service_names(cs) and svc1 not in service_names(cs)
    beat(1, serving_body())
    tj.reconcile()
    assert {svc0, svc1} <= service_names(cs)

    # Reload in flight: readiness drops → the Service goes with it.
    beat(0, serving_body(ready=False))
    tj.reconcile()
    assert svc0 not in service_names(cs)
    assert svc1 in service_names(cs)

    # Reload done: readiness returns → the Service is restored.
    beat(0, serving_body())
    tj.reconcile()
    assert svc0 in service_names(cs)


def test_readiness_gating_over_strict_apiserver():
    """The same protocol against the strict-schema apiserver: every
    status write validates, and the Service set follows readiness."""
    with ApiServerHarness() as api:
        cs = Clientset(RestConfig(host=api.url, timeout=5.0))
        controller = Controller(cs,
                                SharedInformerFactory(cs, resync_period=0),
                                heartbeat_persist_interval=0.0)
        job = serve_job(replicas=2)
        cs.tpujobs.create("default", job.to_dict())
        tj = TrainingJob(cs, controller.recorder, job,
                         metrics=controller.metrics)
        controller.jobs["default/sv"] = tj
        tj.reconcile()
        assert len(cs.pods.list("default")) == 2
        svc0 = replicas_mod.gen_general_name("sv", "WORKER", "sv01", 0)
        names = {s["metadata"]["name"]
                 for s in cs.services.list("default")}
        assert svc0 not in names  # no endpoints before the ready beat
        controller.record_heartbeat("default", "sv", {
            "time": "2026-08-04T00:00:00.000000Z", "step": 1,
            "attempt": 0, "processId": 0, "serving": serving_body()})
        tj.reconcile()
        names = {s["metadata"]["name"]
                 for s in cs.services.list("default")}
        assert svc0 in names
        status = cs.tpujobs.get("default", "sv")["status"]
        assert status["serving"]["replicasReady"] == 1


# --- traffic-driven scaling --------------------------------------------------


def test_scale_up_then_down_through_scheduler():
    """Traffic above target grows the fleet (delta admitted through the
    scheduler's resize — slice-per-replica accounting); traffic falling
    away shrinks it back, trimming pods and services past the target.
    The attempt counter never moves."""
    cs, controller, tj, now, beat = serving_harness(
        replicas=2, min_replicas=1, max_replicas=4, target=2.0,
        num_slices=2, tpu_chips=4, capacity=4)
    assert len(live_pods(cs)) == 2
    assert controller.scheduler.granted_slices("default/sv") == 2

    # 7 req/s against target 2/replica → desired ceil(3.5) = 4.
    beat(0, serving_body(requestsPerSecond=3.0))
    beat(1, serving_body(requestsPerSecond=4.0))
    assert tj.job.status.serving["desiredReplicas"] == 4
    tj.reconcile()
    assert tj.job.status.serving["replicas"] == 4
    assert controller.scheduler.granted_slices("default/sv") == 4
    tj.reconcile()  # the scaled replica sets create the new pods
    assert len(live_pods(cs)) == 4
    env = pod_env(live_pods(cs)[-1])
    assert env["TPUJOB_SERVE"] == "1"

    # Traffic falls to ~1 req/s → desired 1; pods+services trim.
    beat(0, serving_body(requestsPerSecond=0.5))
    beat(1, serving_body(requestsPerSecond=0.5))
    beat(2, serving_body(requestsPerSecond=0.0))
    beat(3, serving_body(requestsPerSecond=0.0))
    assert tj.job.status.serving["desiredReplicas"] == 1
    tj.reconcile()
    assert tj.job.status.serving["replicas"] == 1
    assert controller.scheduler.granted_slices("default/sv") == 1
    assert len(live_pods(cs)) == 1
    assert tj.job.status.attempt == 0
    assert tj.job.status.restart_counts == {}


def test_scale_up_capped_by_inventory():
    """The delta goes through the admission queue: a full inventory
    grants LESS than desired instead of over-committing."""
    cs, controller, tj, now, beat = serving_harness(
        replicas=2, min_replicas=1, max_replicas=4, target=1.0,
        num_slices=2, tpu_chips=4, capacity=3)
    beat(0, serving_body(requestsPerSecond=5.0))
    beat(1, serving_body(requestsPerSecond=5.0))
    assert tj.job.status.serving["desiredReplicas"] == 4
    tj.reconcile()
    # Only 3 slices exist: the grant stops there.
    assert tj.job.status.serving["replicas"] == 3
    assert controller.scheduler.granted_slices("default/sv") == 3


# --- serve payload (decode loop, load generator, hot reload) -----------------


def serve_args(**kw):
    argv = []
    defaults = {"load": "50:1", "batch": 2, "decode_tokens": 2,
                "window": 16, "vocab": 32, "dim": 16, "heads": 2,
                "kv_heads": 1, "layers": 1, "reload_poll": 0.1,
                "reload_stagger": 0.0}
    defaults.update(kw)
    for key, value in defaults.items():
        argv.extend([f"--{key.replace('_', '-')}", str(value)])
    return serve_mod.parse_args(argv)


def make_info(pid=0, replica_index=0):
    from tpu_operator.payload import bootstrap

    return bootstrap.ProcessInfo(
        coordinator_address="", process_id=pid, num_processes=1,
        worker_id=0, worker_hostnames=(), job_name="sv",
        replica_index=replica_index)


def test_load_schedule_and_generator():
    sched = serve_mod.LoadSchedule.parse("10:2,0:1,4:0")
    assert sched.rate_at(0.5) == 10.0
    assert sched.rate_at(2.5) == 0.0
    assert sched.rate_at(100.0) == 4.0  # zero-duration tail holds
    assert sched.duration() is None
    finite = serve_mod.LoadSchedule.parse("5:2")
    assert finite.duration() == 2.0
    assert finite.rate_at(3.0) is None
    gen = serve_mod.LoadGenerator(finite)
    assert gen.due(0.0) == 0
    assert gen.due(1.0) == 5
    assert gen.due(2.1) is None  # schedule over
    with pytest.raises(ValueError):
        serve_mod.LoadSchedule.parse("-1:5")


def test_decode_loop_serves_requests():
    loop = serve_mod.ServeLoop(serve_args(load="40:1.5"), make_info(),
                               heartbeat=None, store=None, recorder=None)
    summary = loop.run()
    assert summary["failedSteps"] == 0
    assert summary["completed"] > 0
    assert summary["completed"] == summary["arrivals"]


def test_ready_beats_and_serving_wire():
    posts = []

    class FakeReporter:
        cadence_only = False

        def due(self, _step):
            return False  # only forced beats land

        def report(self, step, metrics=None, serving=None, **kw):
            posts.append(dict(serving))
            return True

    loop = serve_mod.ServeLoop(serve_args(load="20:0.5"), make_info(),
                               heartbeat=FakeReporter(), store=None,
                               recorder=None)
    loop.run()
    # First forced beat = ready (post-compile); last = the teardown
    # not-ready beat.
    assert posts[0]["ready"] is True
    assert posts[-1]["ready"] is False
    assert all("loadedStep" in p and "requestsPerSecond" in p
               for p in posts)


def _commit_snapshot(store, args, step, tmpdir):
    """Trainer-side: save a verified checkpoint at ``step`` and upload it
    as a committed remote snapshot (manifest last)."""
    from tpu_operator.payload import checkpoint as checkpoint_mod

    trainer_dir = os.path.join(tmpdir, f"trainer-{step}")
    _mesh, _model, state, _fn, _spec = serve_mod.build_decode(args)
    state = state.replace(step=state.step + step)
    ck = checkpoint_mod.Checkpointer(trainer_dir, save_every=1)
    try:
        assert ck.save(step, state)
        ck.flush()
        assert ck.last_verified_step() == step
    finally:
        ck.close()
    store.upload_checkpoint(os.path.join(trainer_dir, str(step)), step)


def test_hot_reload_under_load(tmp_path):
    """The payload half of the acceptance: a serving loop under sustained
    load observes a newer VERIFIED snapshot, drops readiness, reloads,
    and returns — zero failed decode steps, loadedStep advanced, and the
    requests in flight during the reload still complete."""
    from tpu_operator.store.blob import from_uri

    backend = from_uri("fake://serve-reload-test")
    from tpu_operator.store import WarmStartStore

    store = WarmStartStore(backend, prefix="default/sv")
    args = serve_args(load="30:4", checkpoint_dir=str(tmp_path / "sv"))
    _commit_snapshot(store, args, 10, str(tmp_path))
    # The production path prefetches during bootstrap (TPUJOB_STORE_*);
    # mirror it so the INITIAL load is step 10, not a counted reload.
    store.prefetch_checkpoint(str(tmp_path / "sv"))

    posts = []

    class FakeReporter:
        cadence_only = False

        def due(self, _step):
            return False

        def report(self, step, metrics=None, serving=None, **kw):
            posts.append(dict(serving))
            return True

    loop = serve_mod.ServeLoop(args, make_info(), heartbeat=FakeReporter(),
                               store=store, recorder=None)

    committed = threading.Event()

    def trainer():
        time.sleep(1.0)
        _commit_snapshot(store, args, 20, str(tmp_path))
        committed.set()

    th = threading.Thread(target=trainer, daemon=True)
    th.start()
    summary = loop.run()
    th.join()
    assert committed.is_set()
    assert summary["failedSteps"] == 0
    assert summary["reloads"] == 1
    assert summary["loadedStep"] == 20
    assert summary["completed"] > 0
    # The reload dropped readiness then restored it: ready=False posted
    # mid-run, ready=True after.
    readies = [p["ready"] for p in posts]
    assert False in readies[1:-1]
    assert readies[0] is True
    loaded = [p["loadedStep"] for p in posts]
    assert loaded[0] == 10 and 20 in loaded


# --- acceptance e2e over the in-process apiserver ----------------------------


@pytest.fixture()
def harness():
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop),
                          daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


@pytest.mark.slow
def test_e2e_serve_gang_ready_and_hot_reload(harness, tmp_path):
    """Acceptance: a ``mode: serve`` gang reaches ``replicasReady ==
    replicas`` with REAL decode loops posting through the real status
    server; a newer verified snapshot hot-reloads with
    ``status.serving.loadedStep`` advancing while ``status.attempt`` and
    ``job_elastic_resizes_total`` stay unchanged (no restart)."""
    from tpu_operator.store import WarmStartStore
    from tpu_operator.payload import heartbeat as heartbeat_mod
    from tpu_operator.store.blob import from_uri

    api, cs, controller, server = harness
    replicas = 2
    job = serve_job(replicas=replicas, min_replicas=1, max_replicas=2,
                    target=1000.0)
    cs.tpujobs.create("default", job.to_dict())
    assert wait_for(
        lambda: len(api.clientset.pods.list("default")) == replicas)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: (cs.tpujobs.get("default", "sv")["status"]
                             .get("phase")) == "Running")

    backend = from_uri("fake://serve-e2e")
    store = WarmStartStore(backend, prefix="default/sv")
    args = serve_args(load="20:0", checkpoint_dir="")  # per-replica dirs
    _commit_snapshot(store, serve_args(
        load="20:0", checkpoint_dir=str(tmp_path / "seed")), 10,
        str(tmp_path))

    loops, threads = [], []
    for pid in range(replicas):
        rargs = serve_args(load="20:0",
                           checkpoint_dir=str(tmp_path / f"replica-{pid}"))
        # Bootstrap-path prefetch: the initial load is step 10, so the
        # reload counter counts exactly the HOT reloads below.
        store.prefetch_checkpoint(str(tmp_path / f"replica-{pid}"))
        reporter = heartbeat_mod.HeartbeatReporter(
            f"http://127.0.0.1:{server.port}", "sv", namespace="default",
            process_id=pid, attempt=0, interval=0.2,
            cadence_only=pid != 0)
        loop = serve_mod.ServeLoop(rargs, make_info(pid, pid),
                                   heartbeat=reporter, store=store,
                                   recorder=None)
        loops.append(loop)
        th = threading.Thread(target=loop.run, daemon=True)
        threads.append(th)
        th.start()
    try:
        def serving_status():
            return (cs.tpujobs.get("default", "sv")["status"]
                    .get("serving") or {})

        assert wait_for(lambda: serving_status()
                        .get("replicasReady") == replicas,
                        describe=serving_status)
        assert serving_status().get("loadedStep") == 10

        resizes_before = sum(
            controller.metrics.counter_value(
                "job_elastic_resizes_total", labels={"direction": d})
            for d in ("up", "down"))

        # Training commits a newer verified snapshot → rolling reload.
        _commit_snapshot(store, serve_args(
            load="20:0", checkpoint_dir=str(tmp_path / "seed2")), 30,
            str(tmp_path))
        assert wait_for(lambda: serving_status().get("loadedStep") == 30,
                        describe=serving_status)
        assert wait_for(lambda: serving_status()
                        .get("replicasReady") == replicas)
        status = cs.tpujobs.get("default", "sv")["status"]
        assert status["attempt"] == 0
        assert status["serving"]["reloads"] == replicas
        resizes_after = sum(
            controller.metrics.counter_value(
                "job_elastic_resizes_total", labels={"direction": d})
            for d in ("up", "down"))
        assert resizes_after == resizes_before
        for loop in loops:
            assert loop.failed_steps == 0
    finally:
        for loop in loops:
            loop.stop()
        for th in threads:
            th.join(timeout=10)


def test_e2e_scale_on_traffic_through_queue(harness):
    """Acceptance sibling: a serve gang scales up then down on a traffic
    change, the delta admitted through the fleet scheduler (synthetic
    serving beats through the real status server)."""
    api, cs, controller, server = harness
    controller.scheduler.update_inventory({KEY: 4})
    job = serve_job(replicas=2, min_replicas=1, max_replicas=4,
                    target=2.0, num_slices=2, tpu_chips=4)
    cs.tpujobs.create("default", job.to_dict())
    assert wait_for(
        lambda: len(api.clientset.pods.list("default")) == 2)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: (cs.tpujobs.get("default", "sv")["status"]
                             .get("phase")) == "Running")

    def post(pid, rps):
        ok, msg = server.record_heartbeat({
            "name": "sv", "namespace": "default", "step": 10,
            "attempt": 0, "processId": pid,
            "serving": serving_body(requestsPerSecond=rps)})
        assert ok, msg

    def live():
        return [p for p in api.clientset.pods.list("default")
                if (p.get("status") or {}).get("phase")
                not in ("Succeeded", "Failed")]

    # Traffic 8 req/s, target 2 → desired 4: scale up through the queue.
    post(0, 4.0)
    post(1, 4.0)
    assert wait_for(lambda: len(live()) == 4,
                    describe=lambda: (cs.tpujobs.get("default", "sv")
                                      ["status"].get("serving")))
    assert controller.scheduler.granted_slices("default/sv") == 4
    status = cs.tpujobs.get("default", "sv")["status"]
    assert status["serving"]["replicas"] == 4
    assert status["attempt"] == 0

    # Traffic collapses → desired 1: scale down, slices released.
    for pid in range(4):
        post(pid, 0.25)
    assert wait_for(lambda: len(live()) == 1,
                    describe=lambda: (cs.tpujobs.get("default", "sv")
                                      ["status"].get("serving")))
    assert controller.scheduler.granted_slices("default/sv") == 1
    status = cs.tpujobs.get("default", "sv")["status"]
    assert status["attempt"] == 0


# --- describe ----------------------------------------------------------------


def test_describe_shows_serving_section():
    with ApiServerHarness() as srv:
        cs = Clientset(RestConfig(host=srv.url, timeout=5.0))
        job = serve_job(replicas=3, min_replicas=1, max_replicas=4)
        set_defaults(job.spec)
        job.status.phase = t.TPUJobPhase.RUNNING
        job.status.serving = {
            "replicas": 3, "desiredReplicas": 2, "replicasReady": 3,
            "requestsPerSecond": 5.5, "tokensPerSecond": 480.0,
            "queueDepth": 12, "kvCacheUtilization": 0.62,
            "p50LatencySeconds": 0.01,
            "p95LatencySeconds": 0.025, "loadedStep": 40, "reloads": 2,
            "attempt": 0, "time": "2026-08-04T00:00:00Z"}
        cs.tpujobs.create("default", job.to_dict())
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = ctl.main(["--master", srv.url, "describe", "sv"])
        assert rc == 0
        text = out.getvalue()
        assert "Serving:    3/3 ready" in text
        assert "desired 2" in text and "range 1-4" in text
        assert "5.5 req/s" in text
        assert "480 tok/s" in text
        assert "p95 25.0 ms" in text
        assert "Backlog:    queue depth 12, KV cache 62% held" in text
        assert "loaded step 40" in text and "2 reload(s)" in text


# --- code-review regressions -------------------------------------------------


def test_serve_rejects_non_worker_roles():
    """Readiness gating maps process ids onto WORKER indices 1:1 and
    gates every per-index Service — a compat SCHEDULER/SERVER role would
    shift the mapping and lose its own Service, so serve specs are
    WORKER-only by validation."""
    job = serve_job(replicas=2, max_replicas=2)
    job.spec.replica_specs.append(t.TPUReplicaSpec(
        replicas=1, template=make_template(),
        tpu_replica_type=t.TPUReplicaType.SCHEDULER))
    set_defaults(job.spec)
    with pytest.raises(validation.ValidationError) as e:
        validation.validate_tpujob_spec(job.spec)
    assert "WORKER-only" in str(e.value)


def test_scaled_spec_single_slice_never_mints_slice_demand():
    """A numSlices=1 job's scaling never touches slice accounting
    (slice_per_replica is False), so the scaled view must keep
    numSlices at 1 — bumping it would mint slice demand admission never
    granted."""
    from tpu_operator.trainer import serving as serving_lib

    job = serve_job(replicas=1, max_replicas=4, num_slices=1)
    set_defaults(job.spec)
    eff = serving_lib.scaled_spec(job.spec, 3)
    assert eff.replica_specs[0].replicas == 3
    assert eff.num_slices == 1
    # Slice-per-replica DOES follow (replica delta == slice delta).
    job2 = serve_job(replicas=2, max_replicas=4, num_slices=2, tpu_chips=4)
    set_defaults(job2.spec)
    eff2 = serving_lib.scaled_spec(job2.spec, 4)
    assert eff2.num_slices == 4


def test_burst_backlog_drains_after_arrivals_stop():
    """Requests queued past the slot count during a burst must drain as
    slots free — even after the arrival stream pauses (the old loop only
    pulled the backlog on NEW arrivals, so a burst + silence starved the
    queue forever)."""
    # 2 slots, 2-token requests; a 1s burst at 60 rps queues far past
    # the slots, then a silent window (0 rps) before the schedule ends —
    # the backlog must drain during the silence, and the end-of-schedule
    # exit must wait for the queue, not just the in-flight slots.
    loop = serve_mod.ServeLoop(serve_args(load="60:1,0:3"), make_info(),
                               heartbeat=None, store=None, recorder=None)
    summary = loop.run()
    assert summary["failedSteps"] == 0
    # Every burst arrival completed — none stranded in the backlog.
    assert summary["completed"] == summary["arrivals"]
    assert summary["arrivals"] >= 50


def test_serving_wire_carries_paged_decode_signals():
    """The beat body grows tokensPerSecond / queueDepth /
    kvCacheUtilization — exactly the fields the statusserver door admits
    and the fold aggregates."""
    loop = serve_mod.ServeLoop(serve_args(load="30:0.5"), make_info(),
                               heartbeat=None, store=None, recorder=None)
    summary = loop.run()
    assert summary["completed"] == summary["arrivals"] > 0
    assert summary["tokensGenerated"] \
        == summary["completed"] * loop.args.decode_tokens
    assert summary["tokensPerSecond"] > 0
    assert summary["shed"] == 0
    assert summary["p99LatencySeconds"] >= summary["p50LatencySeconds"]
    wire = loop.serving_wire()
    assert set(wire) >= {"ready", "requestsPerSecond", "tokensPerSecond",
                         "queueDepth", "kvCacheUtilization", "loadedStep",
                         "reloads"}
    assert wire["queueDepth"] == 0
    assert wire["kvCacheUtilization"] == 0.0  # all requests completed
    # The wire body passes the statusserver's strict door verbatim.
    from tpu_operator.controller.statusserver import _sanitize_serving

    clean, err = _sanitize_serving(wire)
    assert err == "" and clean is not None


def test_http_ingress_decode_and_healthz():
    """The per-replica HTTP endpoint: POST /v1/decode queues through the
    continuous-batching loop and answers with the generated tokens —
    and they equal the synthetic path's greedy decode for the same
    prompt. /healthz tracks readiness."""
    import json as json_mod
    import urllib.error
    import urllib.request

    import numpy as np

    port = _free_port()
    args = serve_args(load="0:0", http_port=port)
    loop = serve_mod.ServeLoop(args, make_info(), heartbeat=None,
                               store=None, recorder=None)
    runner = threading.Thread(target=loop.run, daemon=True)
    runner.start()
    try:
        deadline = time.monotonic() + 60
        while not loop.ready and time.monotonic() < deadline:
            time.sleep(0.01)
        assert loop.ready
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert r.status == 200
        prompt = [int(x) for x in (np.arange(args.window) + 1)
                  % args.vocab]
        body = json_mod.dumps({"prompt": prompt, "maxTokens": 2}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/decode", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200
            tokens = json_mod.loads(r.read())["tokens"]
        assert len(tokens) == 2
        # A malformed prompt is a 400, not a crash.
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/decode", data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(bad, timeout=10)
        assert e.value.code == 400
    finally:
        loop.stop()
        runner.join(timeout=10)
    assert loop.completed >= 1


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def test_backpressure_depth_bound_and_deadline_shed():
    """Depth-bounded admission (submit past --max-queue sheds, returns
    None) and age-bounded queues (_shed_expired drops oldest-first past
    --queue-deadline, setting the request's shed flag) — both counted,
    both visible on the wire as queueDepth."""
    clock = [0.0]
    args = serve_args(load="0:0", max_queue=2, queue_deadline=5.0)
    loop = serve_mod.ServeLoop(args, make_info(), heartbeat=None,
                               store=None, recorder=None,
                               clock=lambda: clock[0])
    a = loop.submit([1, 2, 3], 2)
    b = loop.submit([4, 5, 6], 2)
    assert a is not None and b is not None
    assert loop.queue_depth() == 2
    # Queue full: the third arrival sheds at admission.
    c = loop.submit([7, 8, 9], 2)
    assert c is None
    assert loop.shed == 1
    assert loop.serving_wire()["queueDepth"] == 2
    # Offered load counted the shed arrival too (demand visibility) —
    # the wire drained all 3 arrivals above.
    clock[0] = 6.0
    loop._shed_expired(clock[0])
    assert loop.shed == 3
    assert a.done.is_set() and a.shed
    assert b.done.is_set() and b.shed
    assert loop.queue_depth() == 0


def test_failed_warmup_never_goes_ready():
    """A replica whose warm-up decode failed must not post ready — and a
    persistent failure streak exits instead of blackholing requests."""
    posts = []

    class FakeReporter:
        cadence_only = False

        def due(self, _step):
            return False

        def report(self, step, metrics=None, serving=None, **kw):
            posts.append(dict(serving))
            return True

    loop = serve_mod.ServeLoop(serve_args(load="50:5"), make_info(),
                               heartbeat=FakeReporter(), store=None,
                               recorder=None)

    def boom(*_a, **_k):
        raise RuntimeError("poisoned device")

    loop.engine.warmup = boom
    loop.engine.step = boom
    with pytest.raises(RuntimeError):
        loop.run()
    assert not any(p.get("ready") for p in posts)


def test_wedged_replica_swept_without_beats():
    """The reconcile-time sweep: a sole replica posts ready then goes
    fully silent — the expiry obligation wakes a reconcile, the sweep
    drops it from the ready set, and its Service is removed, all without
    a single further beat."""
    from tpu_operator.controller.controller import SERVING_EXPIRY_SECONDS

    cs, controller, tj, now, beat = serving_harness(replicas=1)
    beat(0, serving_body())
    tj.reconcile()
    svc0 = replicas_mod.gen_general_name("sv", "WORKER", "sv01", 0)
    assert svc0 in service_names(cs)
    # The expiry wakeup is armed for exactly the beat's staleness epoch.
    obligation = tj.next_time_obligation()
    assert obligation is not None
    assert obligation <= now[0] + SERVING_EXPIRY_SECONDS + 1
    # The replica wedges: NO further beats. Time passes; the woken
    # reconcile sweeps and ungates.
    now[0] += SERVING_EXPIRY_SECONDS + 1
    with controller._jobs_lock:
        controller._sweep_serving_locked("default/sv", tj)
    tj.reconcile()
    assert svc0 not in service_names(cs)
    assert tj.job.status.serving["replicasReady"] == 0


def test_trim_removes_all_stale_services_wide_scale_down():
    """Scale-down service cleanup walks the SNAPSHOT, not a probed index
    range — a 70→2 trim must remove every stale per-index Service (the
    old probe cap leaked everything past keep+64)."""
    cs, controller, tj, now, beat = serving_harness(replicas=70,
                                                    min_replicas=1)
    rs = tj.replica_sets[0]
    for index in range(70):
        rs.create_service_with_index(index, emit_event=False)
    assert len(service_names(cs)) >= 70
    tj.gang.trim_replicas(2, tj.build_snapshot())
    names = service_names(cs)
    assert rs.gen_name(0) in names and rs.gen_name(1) in names
    assert not any(rs.gen_name(i) in names for i in range(2, 70))


def test_operator_restart_keeps_services_until_evidence():
    """Restart-blackout regression: a freshly restarted operator has an
    EMPTY in-memory serving map while every replica may be healthy — the
    reconcile must leave the Service set untouched until the first beat
    (or sweep) provides evidence, never ungate on absence."""
    cs, controller, tj, now, beat = serving_harness(replicas=2)
    beat(0, serving_body())
    beat(1, serving_body())
    tj.reconcile()
    svc0, svc1 = (replicas_mod.gen_general_name("sv", "WORKER", "sv01", i)
                  for i in (0, 1))
    assert {svc0, svc1} <= service_names(cs)

    # Operator restart: a fresh controller + TrainingJob, no beats yet.
    controller2 = Controller(cs, SharedInformerFactory(cs,
                                                       resync_period=0),
                             heartbeat_persist_interval=0.0)
    job2 = t.TPUJob.from_dict(cs.tpujobs.get("default", "sv"))
    tj2 = TrainingJob(cs, controller2.recorder, job2,
                      metrics=controller2.metrics)
    controller2.jobs["default/sv"] = tj2
    tj2.reconcile()
    # No serving evidence: both Services survive the reconcile.
    assert {svc0, svc1} <= service_names(cs)
    # First beat arrives: gating resumes with real evidence.
    controller2.record_heartbeat("default", "sv", {
        "time": "2026-08-04T00:00:00.000000Z", "step": 60, "attempt": 0,
        "processId": 0, "serving": serving_body(ready=False)})
    tj2.reconcile()
    assert svc0 not in service_names(cs)
    assert svc1 in service_names(cs)


def test_late_appearing_pod_trimmed_on_next_pass():
    """Stale-snapshot trim regression: a pod created during a scale-up
    that the watch cache echoes only AFTER the scale-down pass must
    still be deleted — the trim is level-triggered on every serve
    reconcile, not a one-shot against one snapshot."""
    cs, controller, tj, now, beat = serving_harness(replicas=2)
    tj.reconcile()
    assert len(live_pods(cs)) == 2
    # A pod of a wider world appears late (as if the cache lagged its
    # create past the scale-down that should have removed it). Built by
    # hand: the CURRENT (narrow) world's env table can't describe it.
    rs = tj.replica_sets[0]
    stray = {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "sv-worker-sv01-5-zzzzz",
                          "labels": rs.index_labels(5, 0)},
             "spec": {"containers": [{"name": "tpu", "image": "x"}]},
             "status": {"phase": "Running", "containerStatuses": [
                 {"name": "tpu", "state": {"running": {}}}]}}
    cs.pods.create("default", stray)
    assert len(live_pods(cs)) == 3
    tj.reconcile()
    assert len(live_pods(cs)) == 2
    assert not any(
        (p["metadata"]["labels"] or {}).get("task_index") == "5"
        for p in live_pods(cs))


def test_serve_slice_mismatch_rejected_without_serving_block():
    """The replicas==numSlices consistency check guards the MODE, not
    only the serving block: a serve job without one still runs
    independent slice servers."""
    job = serve_job(replicas=3, num_slices=2, tpu_chips=4)
    job.spec.serving = None
    set_defaults(job.spec)
    with pytest.raises(validation.ValidationError) as e:
        validation.validate_tpujob_spec(job.spec)
    assert "numSlices" in str(e.value)
