"""FSDP/ZeRO parameter-sharding tests (8-device CPU mesh).

train.fsdp_shardings must actually shard large params over the data axis
(memory O(1/N)), be semantics-preserving (same loss as replicated), and
train end-to-end; sharding is layout, never math.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from tpu_operator.payload import transformer
from tpu_operator.payload import data as data_mod


def _argv(extra=()):
    return ["--batch", "8", "--seq-len", "64", "--dim", "64", "--heads", "2",
            "--layers", "2", *extra]


@pytest.fixture(scope="module")
def mesh():
    return transformer.make_lm_mesh(8, seq_parallel=1)  # (data=8, seq=1)


def test_fsdp_shards_large_params_over_data(mesh):
    args = transformer.parse_args(_argv(["--fsdp"]))
    _, _, state, _step, _batches = transformer.build(args, mesh=mesh)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    sharded = [(path, leaf) for path, leaf in flat
               if leaf.sharding.spec and leaf.sharding.spec[0] == "data"]
    # vocab=256 embeddings and 3*dim qkv kernels divide 8 and exceed the
    # size floor — they must be sharded; every sharded leaf is 1/8 per chip.
    assert sharded, "no param was FSDP-sharded"
    for _path, leaf in sharded:
        local = leaf.addressable_shards[0].data.shape
        assert local[0] == leaf.shape[0] // 8
    # adam moments mirror the param shardings
    mu = state.opt_state[0].mu
    mu_flat = jax.tree_util.tree_flatten_with_path(mu)[0]
    specs = {jax.tree_util.keystr(p): l.sharding.spec for p, l in mu_flat}
    for path, leaf in sharded:
        assert specs[jax.tree_util.keystr(path)] == leaf.sharding.spec


def test_fsdp_loss_matches_replicated(mesh):
    losses = {}
    for fsdp in (False, True):
        args = transformer.parse_args(_argv(["--fsdp"] if fsdp else []))
        _, _, state, step, batches = transformer.build(args, mesh=mesh)
        (tokens,) = next(batches)
        from jax.sharding import PartitionSpec as P

        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", None))
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[fsdp] = float(metrics["loss"])
    assert abs(losses[False] - losses[True]) < 5e-3, losses


def test_fsdp_loss_descends(mesh):
    args = transformer.parse_args(_argv(["--fsdp", "--lr", "1e-2"]))
    _, _, state, step, batches = transformer.build(args, mesh=mesh)
    from jax.sharding import PartitionSpec as P

    losses = []
    for _ in range(30):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", None))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_small_or_indivisible_leaves_replicate(mesh):
    args = transformer.parse_args(_argv(["--fsdp"]))
    _, _, state, _step, _batches = transformer.build(args, mesh=mesh)
    flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
    for _path, leaf in flat:
        if leaf.size < 1024 or leaf.shape[0] % 8:
            assert leaf.sharding.spec == (), (_path, leaf.shape)


def test_grad_accum_matches_single_shot(mesh):
    # K=4 accumulation must match the K=1 step to bf16 precision: gradients
    # are averaged before the single adam update.
    losses = {}
    for accum in (1, 4):
        args = transformer.parse_args(_argv(["--grad-accum", str(accum)]))
        _, _, state, step, batches = transformer.build(args, mesh=mesh)
        from jax.sharding import PartitionSpec as P

        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", None))
        state, _ = step(state, dev)
        _, metrics = step(state, dev)
        losses[accum] = float(metrics["loss"])
    assert abs(losses[1] - losses[4]) < 5e-3, losses


def test_grad_accum_composes_with_fsdp_and_descends(mesh):
    args = transformer.parse_args(
        _argv(["--grad-accum", "2", "--fsdp", "--remat", "--lr", "1e-2"]))
    _, _, state, step, batches = transformer.build(args, mesh=mesh)
    from jax.sharding import PartitionSpec as P

    losses = []
    for _ in range(30):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", None))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]


def test_fsdp_composes_with_seq_parallel():
    # (data=2, seq=4) mesh with FSDP over data: ring attention + ZeRO
    # params in one jit.
    mesh_sp = transformer.make_lm_mesh(8, seq_parallel=4)
    args = transformer.parse_args(
        ["--batch", "4", "--seq-len", "64", "--dim", "64", "--heads", "4",
         "--layers", "2", "--seq-parallel", "4", "--fsdp", "--lr", "1e-2"])
    _, _, state, step, batches = transformer.build(args, mesh=mesh_sp)
    from jax.sharding import PartitionSpec as P

    losses = []
    for _ in range(20):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh_sp, tokens,
                                           spec=P("data", "seq"))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::4]
