"""Cooperative drain protocol: planned restarts, in-attempt live resize,
and graceful preemption.

The controller stamps a drain directive into ``status.drain``; it rides
process 0's heartbeat ACK (the profile-directive delivery path) until
the payload's drainAck folds it Acked; the payload's verified save +
EXIT_PLANNED (160) completes it — classified ``planned``, billed to the
4x preemption-factor budget, never the crash-loop budget, with restart
backoff skipped. A directive that never ACKs or never exits hard-kills
at ``spec.drain.deadlineSeconds``, exactly the pre-drain teardown.

Three call sites are covered here: the in-attempt live resize (a
Running shrunk elastic gang grows WITHIN the job once inventory
headroom holds through the debounce), drain-first graceful preemption
(the fleet eviction keeps the gang running until the save lands), and
node-maintenance drains off the cordon watch.

Observability contract: ``job_planned_restarts_total{reason}`` and
``job_drain_seconds`` are asserted against the registry by name, and
pruned with the job (the PR-15 lifecycle discipline). The e2e at the
bottom runs the full HTTP path — strict status-subresource schema,
StatusServer directive delivery to process 0 only, drainAck fold, and
``tpujobctl describe``'s Drain line.
"""

import contextlib
import io
import threading
from types import SimpleNamespace

import pytest

from tpu_operator.apis.tpujob import validation
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.payload import bootstrap
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.trainer import policy, training
from tpu_operator.trainer.training import TrainingJob
from tpu_operator.util.util import parse_rfc3339
from tests.test_elastic import KEY, elastic_job, live_pods, mark_pods, pod_env
from tests.test_time_recovery import T0, FakeNow

wait_for = make_wait_for(timeout=20.0, interval=0.05)

LABELS = {"namespace": "default", "name": "dr"}


@pytest.fixture
def clock(monkeypatch):
    fake = FakeNow()
    monkeypatch.setattr(training, "_now", fake)
    return fake


def drain_harness(name="dr", capacity=4, replicas=8, num_slices=8,
                  min_slices=2, drain=None, **spec_kw):
    """A Running elastic gang under an in-process Controller whose fleet
    scheduler models ``capacity`` v4 2x2x2 slices (the gang shrinks to
    fit), with the heartbeat/drain fold path live."""
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=0.0)
    controller.scheduler.update_inventory({KEY: capacity})
    job = elastic_job(name, replicas=replicas, num_slices=num_slices,
                      min_slices=min_slices, **spec_kw)
    if drain is not None:
        job.spec.drain = drain
    cs.tpujobs.create("default", job.to_dict())
    tj = TrainingJob(cs, controller.recorder, job,
                     metrics=controller.metrics,
                     scheduler=controller.scheduler)
    controller.jobs[f"default/{name}"] = tj
    tj.reconcile()
    mark_pods(cs)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    return cs, controller, tj


def beat(controller, tj, step=100, pid=0, **extra):
    hb = {"time": training._now(), "step": step,
          "attempt": tj.job.status.attempt, "processId": pid}
    hb.update(extra)
    return controller.record_heartbeat(tj.namespace, tj.name, hb)


def event_reasons(cs):
    return [e["reason"] for e in cs.events.list("default")]


# --- spec, classification, billing -------------------------------------------


def test_drain_spec_roundtrip_defaults_and_validation():
    spec = t.DrainSpec(deadline_seconds=60, resize_debounce_seconds=5)
    assert t.DrainSpec.from_dict(spec.to_dict()) == spec
    assert t.DrainSpec.from_dict(None) is None
    assert t.DrainSpec.from_dict({}) == t.DrainSpec(
        deadline_seconds=t.DEFAULT_DRAIN_DEADLINE_SECONDS,
        resize_debounce_seconds=t.DEFAULT_RESIZE_DEBOUNCE_SECONDS)

    bad = elastic_job(drain=t.DrainSpec(deadline_seconds=0))
    set_defaults(bad.spec)
    with pytest.raises(validation.ValidationError, match="deadlineSeconds"):
        validation.validate_tpujob_spec(bad.spec)

    bad = elastic_job(drain=t.DrainSpec(resize_debounce_seconds=-1))
    set_defaults(bad.spec)
    with pytest.raises(validation.ValidationError,
                       match="resizeDebounceSeconds"):
        validation.validate_tpujob_spec(bad.spec)


def test_planned_exit_code_classifies_planned():
    assert bootstrap.EXIT_PLANNED == 160
    assert bootstrap.EXIT_PLANNED in policy.PLANNED_EXIT_CODES
    pod = {"metadata": {"name": "p"}, "status": {
        "phase": "Failed", "containerStatuses": [
            {"name": "tpu",
             "state": {"terminated": {"exitCode": 160}}}]}}
    kind, _reason = policy.classify_pod_failure(pod)
    assert kind == t.FailureKind.PLANNED
    # A planned exit is retryable — it must group-restart, not fail.
    assert policy.is_retryable_termination_state({"exitCode": 160})


def test_bootstrap_planned_drain_latch():
    bootstrap.reset_drain()
    assert not bootstrap.planned_drain()
    assert bootstrap.drain_exit_code() == bootstrap.EXIT_RETRYABLE
    bootstrap.request_planned_drain()
    assert bootstrap.planned_drain()
    assert bootstrap.drain_exit_code() == bootstrap.EXIT_PLANNED
    bootstrap.reset_drain()
    assert not bootstrap.planned_drain()


def test_planned_restarts_bill_preemption_pool_not_crash_loop(clock):
    _cs, _controller, tj = drain_harness(max_restarts=1)
    # Shared pool: planned + preemption together draw maxRestarts * 4.
    tj.job.status.restart_counts = {"planned": 3, "preemption": 1}
    used, budget, desc = tj._restart_budget_usage(t.FailureKind.PLANNED)
    assert (used, budget) == (4, 4)
    assert "preemption" in desc
    assert tj._within_restart_budget(t.FailureKind.PLANNED, "x")
    tj.job.status.restart_counts["planned"] = 4
    assert not tj._within_restart_budget(t.FailureKind.PLANNED, "x")
    assert tj.job.status.phase == t.TPUJobPhase.FAILED


def test_planned_failure_never_ticks_consecutive_streak(clock):
    _cs, _controller, tj = drain_harness(name="dr2")
    tj._record_failure(0, t.FailureKind.PLANNED, "planned exit")
    assert tj.job.status.consecutive_failures == 0
    assert tj.job.status.failures[-1].kind == t.FailureKind.PLANNED
    tj._record_failure(0, t.FailureKind.APPLICATION, "crash")
    assert tj.job.status.consecutive_failures == 1


# --- directive lifecycle -----------------------------------------------------


def test_request_drain_stamps_directive_once(clock):
    cs, _controller, tj = drain_harness()
    tj.request_drain(t.DrainReason.RESIZE, "headroom", target_slices=8)
    dr = tj.job.status.drain
    assert dr["state"] == t.DrainState.REQUESTED
    assert dr["reason"] == t.DrainReason.RESIZE
    assert dr["attempt"] == 0 and dr["targetSlices"] == 8
    assert len(dr["id"]) == 5
    assert parse_rfc3339(dr["deadline"]) == pytest.approx(
        T0 + t.DEFAULT_DRAIN_DEADLINE_SECONDS)
    assert "DrainRequested" in event_reasons(cs)
    # Idempotent while in flight: the level-triggered call sites must not
    # reset the directive's identity or push the deadline out forever.
    clock.advance(10)
    tj.request_drain(t.DrainReason.PREEMPTION, "other")
    assert tj.job.status.drain["id"] == dr["id"]
    assert tj.job.status.drain["reason"] == t.DrainReason.RESIZE
    assert tj.job.status.drain["deadline"] == dr["deadline"]


def test_heartbeat_ack_folds_requested_to_acked(clock):
    cs, controller, tj = drain_harness()
    tj.request_drain(t.DrainReason.RESIZE, target_slices=8)
    dr = dict(tj.job.status.drain)
    # Served to process 0 while Requested...
    assert controller.pending_drain("default", "dr") == {
        "id": dr["id"], "reason": "resize", "targetSlices": 8}
    clock.advance(5)
    assert beat(controller, tj, step=100,
                drainAck={"id": dr["id"], "step": 120})
    folded = tj.job.status.drain
    assert folded["state"] == t.DrainState.ACKED
    assert folded["drainedStep"] == 120
    # job_drain_seconds measures request -> planned exit: the ACK must
    # not reset the request stamp.
    assert folded["time"] == dr["time"]
    assert "DrainAcked" in event_reasons(cs)
    # ...and stops riding ACKs once Acked.
    assert controller.pending_drain("default", "dr") is None
    # A duplicate ACK (the payload resends until 200'd) is a no-op.
    assert beat(controller, tj, step=101,
                drainAck={"id": dr["id"], "step": 130})
    assert tj.job.status.drain["drainedStep"] == 120


def test_stale_attempt_directive_expires_and_ack_is_refused(clock):
    cs, controller, tj = drain_harness()
    tj.request_drain(t.DrainReason.RESIZE, target_slices=8)
    rid = tj.job.status.drain["id"]
    # A real failure wins the race: the gang the directive addressed dies.
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 137}})
    tj.reconcile()
    assert tj.job.status.attempt == 1
    # The successor's payload must never adopt the predecessor's drain:
    # the serve gate refuses it immediately, and the next reconcile
    # resolves the stranded record to Expired.
    assert controller.pending_drain("default", "dr") is None
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.EXPIRED
    # An ACK posted by the dying attempt is dropped by the attempt-age
    # gate (None = stale); the directive stays resolved.
    assert controller.record_heartbeat("default", "dr", {
        "time": training._now(), "step": 99, "attempt": 0, "processId": 0,
        "drainAck": {"id": rid, "step": 99}}) is None
    assert tj.job.status.drain["state"] == t.DrainState.EXPIRED


def test_suspend_mid_drain_expires_directive(clock):
    cs, _controller, tj = drain_harness()
    tj.request_drain(t.DrainReason.MAINTENANCE, "node cordoned")
    tj.job.spec.suspend = True
    job = cs.tpujobs.get("default", "dr")
    job["spec"]["suspend"] = True
    cs.tpujobs.update("default", job)
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.EXPIRED
    assert tj.job.status.phase == t.TPUJobPhase.SUSPENDED


# --- in-attempt live resize (grow) -------------------------------------------


def test_grow_waits_out_debounce_and_resets_on_flap(clock):
    _cs, controller, tj = drain_harness(
        drain=t.DrainSpec(deadline_seconds=120, resize_debounce_seconds=30))
    beat(controller, tj, step=50)
    controller.scheduler.update_inventory({KEY: 8})
    tj.reconcile()
    assert tj.job.status.drain is None  # window just opened
    assert tj._grow_ready_epoch() == pytest.approx(T0 + 30)
    assert tj.next_time_obligation() <= T0 + 30
    clock.advance(29)
    tj.reconcile()
    assert tj.job.status.drain is None
    # Headroom flaps away: the window must restart from scratch.
    controller.scheduler.update_inventory({KEY: 4})
    tj.reconcile()
    assert tj._grow_ready_epoch() is None
    clock.advance(60)
    controller.scheduler.update_inventory({KEY: 8})
    tj.reconcile()
    assert tj.job.status.drain is None
    clock.advance(30)
    tj.reconcile()
    dr = tj.job.status.drain
    assert dr["state"] == t.DrainState.REQUESTED
    assert dr["reason"] == t.DrainReason.RESIZE
    assert dr["targetSlices"] == 8


def test_planned_resize_grows_within_the_job(clock):
    cs, controller, tj = drain_harness(
        drain=t.DrainSpec(deadline_seconds=120, resize_debounce_seconds=0),
        restart_backoff=t.RestartBackoffSpec(base_seconds=300))
    assert tj.job.status.elastic["slices"] == 4
    beat(controller, tj, step=100)
    controller.scheduler.update_inventory({KEY: 8})
    tj.reconcile()
    dr = tj.job.status.drain
    assert dr["state"] == t.DrainState.REQUESTED and dr["targetSlices"] == 8
    # The gang keeps running while the directive is in flight.
    assert len(live_pods(cs)) == 4
    clock.advance(5)
    beat(controller, tj, step=110, drainAck={"id": dr["id"], "step": 120})
    assert tj.job.status.drain["state"] == t.DrainState.ACKED
    clock.advance(40)
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 160}})
    tj.reconcile()   # planned restart: teardown, attempt bump, no backoff
    done = tj.job.status.drain
    assert done["state"] == t.DrainState.COMPLETED
    assert done["drainedStep"] == 120
    assert tj.job.status.attempt == 1
    # Billed planned: zero crash-loop budget, no consecutive-failure
    # streak, and the 300 s restart backoff is skipped outright.
    assert tj.job.status.restart_counts == {"planned": 1}
    assert tj.job.status.consecutive_failures == 0
    assert not tj.job.status.backoff_until
    rec = tj.job.status.failures[-1]
    assert rec.kind == t.FailureKind.PLANNED
    assert rec.world_slices == 4
    tj.reconcile()   # re-gang at the renegotiated size
    el = tj.job.status.elastic
    assert el["slices"] == 8 and el["lastResizeDirection"] == "up"
    assert len(live_pods(cs)) == 8
    envs = pod_env(live_pods(cs)[0])
    assert envs["JAX_NUM_PROCESSES"] == "8"
    assert envs["MEGASCALE_NUM_SLICES"] == "8"
    mark_pods(cs, only_live=True)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    # Observability contract: per-reason planned-restart counter and the
    # request->exit drain latency histogram.
    assert controller.metrics.counter_value(
        "job_planned_restarts_total",
        labels={**LABELS, "reason": "resize"}) == 1
    hist = controller.metrics.histogram_snapshot("job_drain_seconds",
                                                 labels=LABELS)
    assert hist["count"] == 1
    assert hist["sum"] == pytest.approx(45.0)


def test_drain_deadline_expiry_falls_back_to_hard_teardown(clock):
    cs, controller, tj = drain_harness(
        drain=t.DrainSpec(deadline_seconds=60, resize_debounce_seconds=0))
    controller.scheduler.update_inventory({KEY: 8})
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.REQUESTED
    # The deadline is an exact-time obligation, not a polling hope.
    assert tj._drain_deadline_epoch() == pytest.approx(T0 + 60)
    assert tj.next_time_obligation() <= T0 + 60
    # Payload never ACKs, never exits. Past the deadline: hard teardown,
    # billed preemption (operator-initiated infra churn).
    clock.advance(61)
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.EXPIRED
    assert "DrainDeadlineExpired" in event_reasons(cs)
    assert tj.job.status.attempt == 1
    assert tj.job.status.restart_counts == {"preemption": 1}
    assert controller.metrics.counter_value(
        "job_planned_restarts_total",
        labels={**LABELS, "reason": "resize"}) == 0.0
    # The job still converges: the restart re-gangs at the wider size.
    tj.reconcile()
    assert len(live_pods(cs)) == 8
    mark_pods(cs, only_live=True)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING


# --- graceful preemption (drain-first eviction) ------------------------------


def evict_harness(clock, **kw):
    """A Running 8-slice victim plus a pending priority-10 preemptor
    whose admission marked the victim for eviction."""
    cs, controller, tj = drain_harness(capacity=8, **kw)
    beat(controller, tj, step=100)
    assert not controller.scheduler.ensure_admitted(
        "default/vip", uid="uid-vip", demand=(KEY, 8), priority=10)
    assert controller.scheduler.peek_eviction("default/dr") is not None
    return cs, controller, tj


def test_eviction_drains_first_then_requeues_planned(clock):
    cs, controller, tj = evict_harness(clock)
    tj.reconcile()
    dr = tj.job.status.drain
    assert dr["state"] == t.DrainState.REQUESTED
    assert dr["reason"] == t.DrainReason.PREEMPTION
    # Drain-first: the gang keeps running (and its reservation holds)
    # until the verified save lands; the directive is NOT consumed yet.
    assert len(live_pods(cs)) == 8
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert controller.scheduler.peek_eviction("default/dr") is not None
    clock.advance(5)
    beat(controller, tj, step=110, drainAck={"id": dr["id"], "step": 115})
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 160}})
    tj.reconcile()
    # Planned exit pops the eviction: reservation released, preemptor
    # admitted, victim requeued — billed planned, not preemption-hard.
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert controller.scheduler.granted_slices("default/vip") == 8
    assert tj.job.status.drain["state"] == t.DrainState.COMPLETED
    assert tj.job.status.failures[-1].kind == t.FailureKind.PLANNED
    assert controller.metrics.counter_value(
        "job_planned_restarts_total",
        labels={**LABELS, "reason": "preemption"}) == 1


def test_eviction_skips_drain_when_checkpoint_already_fresh(clock):
    cs, controller, tj = drain_harness(capacity=8)
    beat(controller, tj, step=100)
    # Satellite: nothing new to save — the last uploaded step matches the
    # last reported step, so a drain round-trip would only delay the
    # preemptor. Hard-preempt immediately, zero drain operations.
    tj.job.status.store = {"lastUploadedStep": 100}
    assert not controller.scheduler.ensure_admitted(
        "default/vip", uid="uid-vip", demand=(KEY, 8), priority=10)
    tj.reconcile()
    assert tj.job.status.phase == t.TPUJobPhase.QUEUED
    assert tj.job.status.drain is None
    assert "DrainRequested" not in event_reasons(cs)
    assert controller.scheduler.granted_slices("default/vip") == 8
    assert tj.job.status.failures[-1].kind == t.FailureKind.PREEMPTION


def test_cancelled_eviction_withdraws_requested_drain(clock):
    cs, controller, tj = evict_harness(clock)
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.REQUESTED
    # The preemptor goes away; the fleet's unjustified-eviction sweep
    # rescinds the mark, and the withdrawal must reach the directive
    # before the payload adopts it: the gang keeps running undisturbed.
    controller.scheduler.release("default/vip")
    assert controller.scheduler.peek_eviction("default/dr") is None
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.EXPIRED
    assert "DrainCancelled" in event_reasons(cs)
    assert tj.job.status.phase == t.TPUJobPhase.RUNNING
    assert tj.job.status.attempt == 0
    assert len(live_pods(cs)) == 8


def test_acked_drain_survives_cancel_and_restarts_in_place(clock):
    cs, controller, tj = evict_harness(clock)
    tj.reconcile()
    dr = tj.job.status.drain
    beat(controller, tj, step=110, drainAck={"id": dr["id"], "step": 115})
    assert tj.job.status.drain["state"] == t.DrainState.ACKED
    # Past withdrawal: the payload's latch is armed, the gang WILL exit
    # planned. The cancel must leave the directive alone...
    controller.scheduler.release("default/vip")
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.ACKED
    # ...and the planned exit then restarts in place (the eviction pop
    # no-ops), keeping the slot — the cheapest remaining outcome.
    mark_pods(cs, "Failed", {"terminated": {"exitCode": 160}})
    tj.reconcile()
    assert tj.job.status.drain["state"] == t.DrainState.COMPLETED
    assert tj.job.status.attempt == 1
    assert tj.job.status.phase != t.TPUJobPhase.QUEUED
    assert controller.scheduler.granted_slices("default/dr") == 8
    assert tj.job.status.failures[-1].kind == t.FailureKind.PLANNED


# --- node-maintenance drain --------------------------------------------------


def test_cordon_edge_triggers_maintenance_drain(clock):
    cs, controller, tj = drain_harness()
    for pod in cs.pods.list("default"):
        pod["spec"]["nodeName"] = "node-0"
        cs.pods.update("default", pod)
    controller.listers = SimpleNamespace(pods=SimpleNamespace(
        list=lambda: cs.pods.list("default")))
    node = {"metadata": {"name": "node-0"}, "spec": {"unschedulable": True}}
    controller._maybe_drain_cordoned({"metadata": {"name": "node-0"},
                                      "spec": {}}, node)
    assert tj._pending_maintenance == ("node-0", 0)
    tj.reconcile()
    dr = tj.job.status.drain
    assert dr["state"] == t.DrainState.REQUESTED
    assert dr["reason"] == t.DrainReason.MAINTENANCE
    # Edge-triggered: a node that STAYS cordoned must not re-drain every
    # successor forever.
    controller._maybe_drain_cordoned(node, node)
    assert tj._pending_maintenance is None


def test_stale_maintenance_handoff_is_dropped(clock):
    _cs, _controller, tj = drain_harness(name="dr2")
    # The cordon was observed against a gang that no longer exists.
    tj.request_maintenance_drain("node-0", attempt=7)
    tj.reconcile()
    assert tj.job.status.drain is None


# --- lifecycle residue -------------------------------------------------------


def test_drain_metrics_pruned_with_the_job(clock):
    cs, controller, _tj = drain_harness()
    for reason in t.DrainReason.ALL:
        controller.metrics.inc("job_planned_restarts_total",
                               labels={**LABELS, "reason": reason})
    controller.metrics.observe("job_drain_seconds", 12.0, labels=LABELS)
    cs.tpujobs.delete("default", "dr")
    controller.sync_tpujob("default/dr")
    for reason in t.DrainReason.ALL:
        assert controller.metrics.counter_value(
            "job_planned_restarts_total",
            labels={**LABELS, "reason": reason}) == 0.0
    assert controller.metrics.histogram_snapshot(
        "job_drain_seconds", labels=LABELS) is None


# --- e2e: HTTP directive delivery, strict schema, describe -------------------


@pytest.fixture()
def harness():
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop), daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


def _reporter(server, pid):
    return heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "drjob", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": str(pid), "TPUJOB_ATTEMPT": "0",
    }, tokens_per_batch=64)


def test_e2e_drain_directive_http_round_trip(harness):
    api, cs, controller, server = harness
    job = elastic_job("drjob", replicas=2, num_slices=2, min_slices=1)
    cs.tpujobs.create("default", job.to_dict())
    assert wait_for(lambda: len(api.clientset.pods.list("default")) >= 2)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: cs.tpujobs.get("default", "drjob")
                    .get("status", {}).get("phase") == "Running")

    # Controller stamps the directive (the cordon handoff path) and the
    # strict status-subresource schema admits status.drain.
    tj = controller.jobs["default/drjob"]
    tj.request_maintenance_drain("node-0", tj.job.status.attempt)
    controller.queue.add("default/drjob")
    assert wait_for(lambda: (cs.tpujobs.get("default", "drjob")
                             .get("status", {}).get("drain")
                             or {}).get("state") == "Requested")
    rid = cs.tpujobs.get("default", "drjob")["status"]["drain"]["id"]

    # The directive rides process 0's heartbeat ACK...
    reporter = _reporter(server, 0)
    assert reporter.report(5, {"loss": 2.0})
    directive = reporter.take_drain_directive()
    assert directive is not None
    assert directive["id"] == rid
    assert directive["reason"] == "maintenance"
    # ...one-shot per id...
    assert reporter.take_drain_directive() is None
    # ...and never to a non-zero process.
    cadence = _reporter(server, 1)
    assert cadence.report(5, None)
    assert cadence.take_drain_directive() is None

    # The payload's adoption ACK folds Requested -> Acked with the
    # gang-agreed boundary step.
    reporter.attach_drain_ack({"id": directive["id"], "step": 42})
    assert reporter.report(6, {"loss": 1.9})
    assert wait_for(lambda: (cs.tpujobs.get("default", "drjob")
                             .get("status", {}).get("drain")
                             or {}).get("state") == "Acked")
    assert cs.tpujobs.get("default", "drjob")["status"]["drain"][
        "drainedStep"] == 42

    # Verified save done: every process exits EXIT_PLANNED (160).
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Failed", "containerStatuses": [
            {"name": "tpu", "state": {"terminated": {"exitCode": 160}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: (cs.tpujobs.get("default", "drjob")
                             .get("status", {})).get("attempt") == 1)
    status = cs.tpujobs.get("default", "drjob")["status"]
    assert status["drain"]["state"] == "Completed"
    assert status["restartCounts"] == {"planned": 1}
    assert not status.get("consecutiveFailures")

    # The re-ganged attempt converges back to Running.
    assert wait_for(lambda: len([
        p for p in api.clientset.pods.list("default")
        if (p.get("status") or {}).get("phase") not in
        ("Failed", "Succeeded")]) >= 2)
    for pod in api.clientset.pods.list("default"):
        if (pod.get("status") or {}).get("phase") in ("Failed", "Succeeded"):
            continue
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: cs.tpujobs.get("default", "drjob")
                    .get("status", {}).get("phase") == "Running")

    # tpujobctl describe surfaces the resolved directive.
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        assert ctl.main(["--master", api.url, "describe", "drjob"]) == 0
    text = out.getvalue()
    assert "Drain:" in text
    assert "Completed — maintenance" in text
    assert "drained at step 42" in text

    # The planned restart landed in the registry under its reason label.
    assert controller.metrics.counter_value(
        "job_planned_restarts_total",
        labels={"namespace": "default", "name": "drjob",
                "reason": "maintenance"}) == 1
