"""Per-job state lifecycle contract tests: the ``lifecycle`` analyzer
rule against fixture trees with seeded violations (exact file:line
findings), the joblife runtime witness (registry, sweeps, epochs), the
deletion-sweep integration over a live controller, and regression tests
for the leaks this PR's first witness run surfaced (the status server's
heartbeat stash outliving deleted jobs; the serving/elastic/autotune
metric prune list)."""

import textwrap
import threading
import time
from pathlib import Path

import pytest

from tpu_operator.analysis import lifecycle
from tpu_operator.analysis.driver import run_analysis
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.util import joblife
from tests.test_types import make_template

REPO = Path(__file__).resolve().parent.parent

wait_for = make_wait_for(timeout=5.0, interval=0.02)


def write(root: Path, relpath: str, body: str) -> Path:
    path = root / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return path


def keyed(findings):
    return {f.key: f for f in findings}


# --- rule fixtures: container annotations ------------------------------------

def test_unannotated_per_job_container_is_found(tmp_path):
    write(tmp_path, "tpu_operator/controller/leaky.py", """\
        class Tracker:
            def __init__(self):
                self._by_job = {}

            def add(self, key, value):
                self._by_job[key] = value
        """)
    found = keyed(lifecycle.run(tmp_path))
    f = found["per-job:tpu_operator/controller/leaky.py:Tracker._by_job"]
    assert (f.path, f.line) == ("tpu_operator/controller/leaky.py", 3)
    assert "no `# per-job:` annotation" in f.message


def test_tuple_keyed_and_set_containers_are_per_job_shaped(tmp_path):
    write(tmp_path, "tpu_operator/controller/shapes.py", """\
        class Beats:
            def __init__(self):
                self._beats = {}
                self._marks = set()

            def put(self, namespace, name, hb):
                self._beats[(namespace, name)] = hb

            def mark(self, uid):
                self._marks.add(uid)
        """)
    found = keyed(lifecycle.run(tmp_path))
    assert "per-job:tpu_operator/controller/shapes.py:Beats._beats" in found
    assert "per-job:tpu_operator/controller/shapes.py:Beats._marks" in found


def test_non_job_keys_do_not_trip_the_heuristic(tmp_path):
    write(tmp_path, "tpu_operator/controller/clean.py", """\
        class Depths:
            def __init__(self):
                self._by_queue = {}

            def bump(self, queue):
                self._by_queue[queue] = self._by_queue.get(queue, 0) + 1
        """)
    assert lifecycle.run(tmp_path) == []


def test_missing_and_removal_free_removers_are_found(tmp_path):
    write(tmp_path, "tpu_operator/controller/removers.py", """\
        from tpu_operator.util import joblife


        class Ghost:
            def __init__(self):
                self._m = joblife.track("Ghost._m")  # per-job: forget

            def get(self, key):
                return self._m.get(key)


        class Hollow:
            def __init__(self):
                self._m = joblife.track("Hollow._m")  # per-job: forget

            def get(self, key):
                return self._m.get(key)

            def forget(self, key):
                return key  # touches nothing
        """)
    found = keyed(lifecycle.run(tmp_path))
    ghost = found["per-job-remover:tpu_operator/controller/removers.py:"
                  "Ghost._m:forget"]
    assert "does not exist" in ghost.message
    hollow = found["per-job-remover:tpu_operator/controller/removers.py:"
                   "Hollow._m:forget"]
    assert "performs no removal" in hollow.message


def test_unreferenced_remover_is_found_and_call_site_clears_it(tmp_path):
    body = """\
        from tpu_operator.util import joblife


        class Orphan:
            def __init__(self):
                self._m = joblife.track("Orphan._m")  # per-job: forget

            def get(self, key):
                return self._m.get(key)

            def forget(self, key):
                self._m.pop(key, None)
        """
    write(tmp_path, "tpu_operator/controller/orphan.py", body)
    found = keyed(lifecycle.run(tmp_path))
    assert ("per-job-unreached:tpu_operator/controller/orphan.py:"
            "Orphan._m:forget") in found
    # A call site anywhere in the scanned tree (here: another module)
    # makes the remover reachable.
    write(tmp_path, "tpu_operator/controller/caller.py", """\
        def on_delete(tracker, key):
            tracker.forget(key)
        """)
    assert lifecycle.run(tmp_path) == []


def test_untracked_annotated_container_is_found_and_no_track_opts_out(
        tmp_path):
    write(tmp_path, "tpu_operator/controller/untracked.py", """\
        class Raw:
            def __init__(self):
                self._m = {}  # per-job: forget

            def get(self, key):
                return self._m.get(key)

            def forget(self, key):
                self._m.pop(key, None)


        def caller(r, key):
            r.forget(key)
        """)
    found = keyed(lifecycle.run(tmp_path))
    f = found["per-job-untracked:tpu_operator/controller/untracked.py:Raw._m"]
    assert "joblife.track" in f.message
    write(tmp_path, "tpu_operator/controller/untracked.py", """\
        class Raw:
            def __init__(self):
                self._m = {}  # per-job: forget no-track

            def get(self, key):
                return self._m.get(key)

            def forget(self, key):
                self._m.pop(key, None)


        def caller(r, key):
            r.forget(key)
        """)
    assert lifecycle.run(tmp_path) == []


def test_track_name_must_match_class_and_attr(tmp_path):
    write(tmp_path, "tpu_operator/controller/misnamed.py", """\
        from tpu_operator.util import joblife


        class Off:
            def __init__(self):
                self._m = joblife.track("Other._x")  # per-job: forget

            def get(self, key):
                return self._m.get(key)

            def forget(self, key):
                self._m.pop(key, None)


        def caller(o, key):
            o.forget(key)
        """)
    found = keyed(lifecycle.run(tmp_path))
    assert ("per-job-untracked:tpu_operator/controller/misnamed.py:Off._m"
            in found)


# --- rule fixtures: metric families ------------------------------------------

def test_job_identity_metric_without_remove_series_is_found(tmp_path):
    write(tmp_path, "tpu_operator/controller/metrics_leak.py", """\
        class C:
            def tick(self, ns, name):
                self.metrics.inc("job_thing_total",
                                 labels={"namespace": ns, "name": name})
        """)
    found = keyed(lifecycle.run(tmp_path))
    f = found["per-job-metric:job_thing_total"]
    assert (f.path, f.line) == ("tpu_operator/controller/metrics_leak.py", 3)
    # A remove_series call site anywhere in the tree clears it.
    write(tmp_path, "tpu_operator/controller/pruner.py", """\
        class P:
            def on_delete(self, ns, name):
                self.metrics.remove_series(
                    "job_thing_total", labels={"namespace": ns, "name": name})
        """)
    assert lifecycle.run(tmp_path) == []


def test_metric_names_written_through_variables_resolve(tmp_path):
    """The tuple-driven fold loops (checkpoint counters, the deletion
    prune loop) pass family names through variables; resolution goes via
    the enclosing function's literals ∩ registered families."""
    write(tmp_path, "tpu_operator/controller/varmetrics.py", """\
        class M:
            def __init__(self):
                self.register("job_var_total", "counter", "h")

            def tick(self, ns, name):
                for metric in ("job_var_total",):
                    self.metrics.inc(metric, 1,
                                     labels={"namespace": ns, "name": name})
        """)
    found = keyed(lifecycle.run(tmp_path))
    assert "per-job-metric:job_var_total" in found
    write(tmp_path, "tpu_operator/controller/varprune.py", """\
        class P:
            def on_delete(self, ns, name):
                for series in ("job_var_total",):
                    self.metrics.remove_series(
                        series, labels={"namespace": ns, "name": name})
        """)
    assert lifecycle.run(tmp_path) == []


def test_stage_labeled_metrics_are_not_job_identity(tmp_path):
    write(tmp_path, "tpu_operator/controller/stagemetrics.py", """\
        class C:
            def tick(self, stage, v):
                self.metrics.observe("job_startup_seconds", v,
                                     labels={"stage": stage})
        """)
    assert lifecycle.run(tmp_path) == []


# --- the witness itself ------------------------------------------------------

def test_track_returns_raw_containers_when_disabled():
    assert joblife.enabled()  # conftest turns it on for the suite
    joblife.enable(False)
    try:
        import collections
        assert type(joblife.track("X._d")) is dict
        assert type(joblife.track("X._o", kind="ordered")) is \
            collections.OrderedDict
        assert type(joblife.track("X._s", kind="set")) is set
    finally:
        joblife.enable(True)


def test_sweep_finds_residuals_across_key_shapes():
    d = joblife.track("W._by_key")
    o = joblife.track("W._seen", kind="ordered")
    s = joblife.track("W._marks", kind="set")
    d["default/j1"] = 1
    o[("default", "j1", "Reason", "msg")] = ("ev", 1)
    s.add("uid-123")
    before = joblife.violation_count()
    leaks = joblife.sweep(("default/j1", ("default", "j1"), "uid-123"),
                          where="test deletion")
    assert len(leaks) == 3
    assert joblife.violation_count() == before + 3
    assert any("W._by_key" in v for v in leaks)
    assert any("W._seen" in v for v in leaks)
    assert any("W._marks" in v for v in leaks)
    # Entries for OTHER jobs are untouched and unreported.
    d.clear(), o.clear(), s.clear()
    d["default/j2"] = 1
    joblife.reset()  # absolve the seeded violations for the autouse guard
    assert joblife.sweep(("default/j1", ("default", "j1"))) == []


def test_epoch_isolates_previous_tests_containers():
    stale = joblife.track("Old._m")
    stale["default/j1"] = 1
    joblife.new_epoch()
    assert joblife.sweep(("default/j1",)) == []
    assert "Old._m" not in joblife.counts()


def test_counts_sums_live_entries_per_name():
    a = joblife.track("C._m")
    b = joblife.track("C._m")
    a["default/x"] = 1
    b["default/y"] = 1
    b["default/z"] = 1
    assert joblife.counts()["C._m"] == 3


# --- integration: the deletion sweep over a live controller ------------------

def job_dict(name="lc-job", replicas=1):
    return t.TPUJob(
        metadata={"name": name, "namespace": "default"},
        spec=t.TPUJobSpec(
            replica_specs=[
                t.TPUReplicaSpec(replicas=replicas, template=make_template(),
                                 tpu_replica_type=t.TPUReplicaType.WORKER)
            ],
            runtime_id="lc01",
        ),
    ).to_dict()


@pytest.fixture
def harness():
    cs = FakeClientset()
    factory = SharedInformerFactory(cs, resync_period=0)
    controller = Controller(cs, factory)
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()
    yield cs, controller
    stop.set()
    runner.join(timeout=5.0)


def test_deleted_job_prunes_statusserver_heartbeats_eagerly(harness):
    """Regression: before the deletion-listener hook, a deleted job's
    stashed heartbeat survived in StatusServer._heartbeats until the
    next scrape ran the lazy informer diff — the first leak the joblife
    deletion sweep caught on the real tree."""
    cs, controller = harness
    server = StatusServer(0, controller=controller,
                          metrics=controller.metrics)
    server.start()  # stop() blocks in shutdown() unless serving
    try:
        cs.tpujobs.create("default", job_dict("hb-job"))
        assert wait_for(lambda: "default/hb-job" in controller.jobs)
        ok, msg = server.record_heartbeat(
            {"namespace": "default", "name": "hb-job", "step": 5,
             "stepTimeSeconds": 0.1, "loss": 1.5})
        assert ok, msg
        with server._heartbeats_lock:
            assert ("default", "hb-job") in server._heartbeats
        before = joblife.violation_count()
        cs.tpujobs.delete("default", "hb-job")
        assert wait_for(lambda: "default/hb-job" not in controller.jobs)
        # The listener pruned the stash ON the deletion reconcile — no
        # scrape ran — and the sweep recorded nothing.
        def stash_empty():
            with server._heartbeats_lock:
                return ("default", "hb-job") not in server._heartbeats
        assert wait_for(stash_empty)
        assert joblife.violation_count() == before, joblife.report()
    finally:
        server.stop()


def test_deletion_sweep_catches_a_seeded_leak(harness):
    """The witness end to end: a tracked container that does NOT clean up
    on deletion is reported by the controller's sweep."""
    cs, controller = harness
    leak = joblife.track("Seeded._leak")
    cs.tpujobs.create("default", job_dict("doomed"))
    assert wait_for(lambda: "default/doomed" in controller.jobs)
    leak["default/doomed"] = {"stale": True}
    cs.tpujobs.delete("default", "doomed")
    assert wait_for(
        lambda: any("Seeded._leak" in v for v in joblife.violations()))
    joblife.reset()  # absolve: the leak was the point of the test


def test_deletion_prunes_serving_elastic_autotune_series(harness):
    """Regression for the PR 10/12/13 metric families: every registry
    series carrying the deleted job's identity — serving gauges, world
    size, autotune counters — leaves on the deletion reconcile (the
    sweep's job_series probe turns any miss into a violation)."""
    cs, controller = harness
    m = controller.metrics
    cs.tpujobs.create("default", job_dict("metr"))
    assert wait_for(lambda: "default/metr" in controller.jobs)
    ident = {"namespace": "default", "name": "metr"}
    m.set_gauge("job_world_size", 4, labels=ident)
    m.set_gauge("job_serving_replicas_ready", 2, labels=ident)
    m.set_gauge("job_serving_latency_seconds", 0.1,
                labels={**ident, "quantile": "0.95"})
    m.inc("job_weight_reloads_total", 1, labels=ident)
    m.inc("job_autotune_adjustments_total", 2,
          labels={**ident, "knob": "prefetch", "direction": "up"})
    m.set_gauge("job_prefetch_depth", 3, labels=ident)
    assert m.job_series("default", "metr")
    before = joblife.violation_count()
    cs.tpujobs.delete("default", "metr")
    assert wait_for(lambda: "default/metr" not in controller.jobs)
    assert wait_for(lambda: not m.job_series("default", "metr"))
    assert joblife.violation_count() == before, joblife.report()


# --- the real tree -----------------------------------------------------------

def test_real_tree_lifecycle_is_clean_under_allowlist():
    active, _suppressed, stale = run_analysis(REPO, rules=["lifecycle"])
    assert active == [], "\n".join(f.render() for f in active)
    assert not stale, f"stale lifecycle allowlist entries: {stale}"
