"""Rate-limited workqueue semantics tests (client-go-equivalent behavior the
reference depended on but never tested; backoff constants from
controller.go:60-63)."""

from tpu_operator.client.workqueue import RateLimitingQueue


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def make_queue():
    clock = FakeClock()
    return clock, RateLimitingQueue(base_delay=10.0, max_delay=360.0, clock=clock)


def test_add_get_done():
    _clock, q = make_queue()
    q.add("a")
    q.add("b")
    assert q.get(timeout=0) == "a"
    assert q.get(timeout=0) == "b"
    assert q.get(timeout=0) is None


def test_dedup_while_queued():
    _clock, q = make_queue()
    q.add("a")
    q.add("a")
    assert q.get(timeout=0) == "a"
    assert q.get(timeout=0) is None


def test_readd_while_processing_requeues_after_done():
    # The invariant that makes concurrent reconciles of one key impossible.
    _clock, q = make_queue()
    q.add("a")
    item = q.get(timeout=0)
    q.add("a")  # event arrives mid-reconcile
    assert q.get(timeout=0) is None  # not handed out again yet
    q.done(item)
    assert q.get(timeout=0) == "a"  # re-delivered exactly once


def test_rate_limited_backoff_progression():
    clock, q = make_queue()
    q.add_rate_limited("a")  # 10s
    assert q.get(timeout=0) is None
    clock.advance(10.1)
    assert q.get(timeout=0) == "a"
    q.done("a")

    q.add_rate_limited("a")  # 20s now
    clock.advance(10.1)
    assert q.get(timeout=0) is None
    clock.advance(10.1)
    assert q.get(timeout=0) == "a"
    q.done("a")

    assert q.num_requeues("a") == 2
    q.forget("a")
    assert q.num_requeues("a") == 0
    q.add_rate_limited("a")  # back to 10s
    clock.advance(10.1)
    assert q.get(timeout=0) == "a"


def test_backoff_capped_at_max():
    clock, q = make_queue()
    for _ in range(10):  # 10 * 2^9 = 5120s uncapped
        q.add_rate_limited("a")
        clock.advance(400.0)
        assert q.get(timeout=0) == "a"
        q.done("a")
    q.add_rate_limited("a")
    clock.advance(360.1)  # capped at 360s
    assert q.get(timeout=0) == "a"


def test_add_after():
    clock, q = make_queue()
    q.add_after("x", 5.0)
    assert q.get(timeout=0) is None
    clock.advance(5.1)
    assert q.get(timeout=0) == "x"


def test_shutdown_unblocks():
    _clock, q = make_queue()
    q.shutdown()
    assert q.get(timeout=None) is None
    q.add("a")  # ignored after shutdown
    assert q.get(timeout=0) is None
