"""Packaging-layer tests: examples/, deploy/chart/, build/, hack/.

Reference parity: SURVEY.md §2 components 18 (helm chart), 19 (examples),
20 (dev tooling). The reference shipped these unvalidated (its chart's test
hook pointed at a missing binary, its cleanup script used a stale label
selector); here every example must pass the operator's own
defaulting+validation, and every chart template must render to valid YAML.
"""

from __future__ import annotations

import pathlib
import subprocess
import sys

import pytest
import yaml

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "hack"))

import render_chart  # noqa: E402  (hack/render_chart.py)

from tpu_operator.apis.tpujob.v1alpha1 import defaults, types  # noqa: E402
from tpu_operator.apis.tpujob import validation  # noqa: E402

EXAMPLES = sorted((REPO / "examples").glob("*.yml"))
TPUJOB_EXAMPLES = [p for p in EXAMPLES if p.name.startswith("tpujob-")]


def load_docs(path: pathlib.Path):
    with open(path, encoding="utf-8") as f:
        return [d for d in yaml.safe_load_all(f) if d]


def test_examples_exist():
    names = {p.name for p in EXAMPLES}
    assert {"crd.yml", "operator.yml", "tpujob-linear.yml",
            "tpujob-cifar-v4-32.yml", "tpujob-compat-ps.yml",
            "tpujob-multislice.yml", "tpujob-gang-pair.yml"} <= names


def tpujob_docs(path: pathlib.Path):
    """The TPUJob documents of an example. Companion resources (e.g. the
    serve-ingress example's Ingress) ride in the same file; every
    example must still ship at least one TPUJob."""
    docs = [d for d in load_docs(path)
            if d.get("apiVersion") == types.CRD_API_VERSION]
    assert docs, f"{path.name}: no TPUJob document"
    return docs


@pytest.mark.parametrize("path", TPUJOB_EXAMPLES, ids=lambda p: p.name)
def test_tpujob_examples_default_and_validate(path):
    for doc in tpujob_docs(path):
        assert doc["kind"] == types.CRD_KIND
        job = types.TPUJob.from_dict(doc)
        defaults.set_defaults(job.spec)
        validation.validate_tpujob_spec(job.spec)  # raises on invalid


@pytest.mark.parametrize("path", TPUJOB_EXAMPLES, ids=lambda p: p.name)
def test_tpujob_examples_pass_structural_schema_strict(path):
    from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod

    for doc in tpujob_docs(path):
        ok, message = schema_mod.validate_tpujob_strict(doc)
        assert ok, f"{path.name}: {message}"


def test_structural_schema_rejects_typos_and_bad_values():
    from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod

    base = load_docs(REPO / "examples" / "tpujob-linear.yml")[0]

    def mutated(**spec_over):
        import copy

        doc = copy.deepcopy(base)
        doc["spec"].update(spec_over)
        return doc

    # the VERDICT round-1 case: a typo'd field name must be *rejected*
    ok, msg = schema_mod.validate_tpujob_strict(mutated(maxRestart=5))
    assert not ok and "maxRestart" in msg and "unknown field" in msg
    # enum violation
    ok, msg = schema_mod.validate_tpujob_strict(
        mutated(restartPolicy="SometimesMaybe"))
    assert not ok and "restartPolicy" in msg
    # integer bound
    ok, msg = schema_mod.validate_tpujob_strict(mutated(numSlices=0))
    assert not ok and "numSlices" in msg
    # topology pattern
    ok, msg = schema_mod.validate_tpujob_strict(mutated(tpuTopology="huge"))
    assert not ok and "tpuTopology" in msg
    # unknown field nested in a replica spec
    import copy

    doc = copy.deepcopy(base)
    doc["spec"]["replicaSpecs"][0]["replica"] = 3  # typo'd "replicas"
    ok, msg = schema_mod.validate_tpujob_strict(doc)
    assert not ok and "replica" in msg
    # ...but arbitrary fields inside the PodTemplateSpec pass through
    doc = copy.deepcopy(base)
    doc["spec"]["replicaSpecs"][0]["template"]["spec"]["anything"] = {"x": 1}
    ok, msg = schema_mod.validate_tpujob_strict(doc)
    assert ok, msg


def test_apiserver_rejects_typod_field_with_422():
    from tpu_operator.client import errors, rest
    from tpu_operator.testing.apiserver import ApiServerHarness

    base = load_docs(REPO / "examples" / "tpujob-linear.yml")[0]
    base["metadata"]["name"] = "typo-job"
    base["spec"]["maxRestart"] = 5  # typo: schema says maxRestarts
    with ApiServerHarness() as srv:
        cs = rest.Clientset(rest.RestConfig(host=srv.url, timeout=5.0))
        with pytest.raises(errors.ApiError) as exc:
            cs.tpujobs.create("default", base)
        assert exc.value.code == 422
        assert "maxRestart" in exc.value.message
        # the fixed spelling is accepted
        del base["spec"]["maxRestart"]
        base["spec"]["maxRestarts"] = 5
        created = cs.tpujobs.create("default", base)
        assert created["spec"]["maxRestarts"] == 5


def test_generated_crd_manifests_not_drifted():
    proc = subprocess.run(
        [sys.executable, str(REPO / "hack" / "gen_crd.py"), "--check"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_example_roles_and_policies():
    # config 1 (compat PS): chief defaults to SCHEDULER, restart PerPod.
    job = types.TPUJob.from_dict(load_docs(REPO / "examples" / "tpujob-compat-ps.yml")[0])
    defaults.set_defaults(job.spec)
    assert job.spec.termination_policy.chief_replica_name == types.TPUReplicaType.SCHEDULER
    assert job.spec.restart_policy == types.RestartPolicy.PER_POD
    # configs 2-4 (worker-only): chief WORKER, whole-group restart.
    job = types.TPUJob.from_dict(load_docs(REPO / "examples" / "tpujob-cifar-v4-32.yml")[0])
    defaults.set_defaults(job.spec)
    assert job.spec.termination_policy.chief_replica_name == types.TPUReplicaType.WORKER
    assert job.spec.restart_policy == types.RestartPolicy.WHOLE_GROUP


def test_multislice_example_divides_evenly():
    job = types.TPUJob.from_dict(load_docs(REPO / "examples" / "tpujob-multislice.yml")[0])
    defaults.set_defaults(job.spec)
    validation.validate_tpujob_spec(job.spec)
    assert job.spec.num_slices == 2
    worker = job.spec.replica_specs[0]
    assert worker.replicas % job.spec.num_slices == 0


def test_crd_manifest_matches_api_constants():
    crd = load_docs(REPO / "examples" / "crd.yml")[0]
    assert crd["metadata"]["name"] == f"{types.CRD_KIND_PLURAL}.{types.CRD_GROUP}"
    assert crd["spec"]["group"] == types.CRD_GROUP
    assert crd["spec"]["names"]["kind"] == types.CRD_KIND
    versions = [v["name"] for v in crd["spec"]["versions"]]
    assert types.CRD_VERSION in versions


# --- chart ------------------------------------------------------------------

def test_chart_renders_to_valid_yaml():
    rendered = render_chart.render_chart(namespace="tpu-system", include_tests=True)
    assert {"crd.yaml", "deployment.yaml", "config.yaml", "rbac.yaml",
            "service-account.yaml", "dashboard.yaml",
            "tests/basic-test.yaml"} <= set(rendered)
    kinds = {}
    for rel, text in rendered.items():
        for doc in yaml.safe_load_all(text):
            if doc:
                kinds.setdefault(doc["kind"], []).append(rel)
    assert set(kinds) == {"CustomResourceDefinition", "Deployment", "ConfigMap",
                          "ClusterRole", "ClusterRoleBinding", "ServiceAccount",
                          "Pod", "Service"}
    # The dashboard Service targets the status port the Deployment exposes.
    (dep,) = list(yaml.safe_load_all(rendered["deployment.yaml"]))
    container = dep["spec"]["template"]["spec"]["containers"][0]
    assert any(p["name"] == "status" for p in container.get("ports", []))
    assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert container["readinessProbe"]["httpGet"]["path"] == "/readyz"


def test_chart_rbac_covers_operator_verbs():
    rendered = render_chart.render_chart()
    docs = list(yaml.safe_load_all(rendered["rbac.yaml"]))
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    by_group = {}
    for rule in role["rules"]:
        for g in rule["apiGroups"]:
            by_group.setdefault(g, set()).update(rule["resources"])
    assert "tpujobs" in by_group[types.CRD_GROUP]
    assert "tpujobs/status" in by_group[types.CRD_GROUP]
    assert {"pods", "services"} <= by_group[""]
    # Least privilege (round-2 decision): no configmaps (controller config is
    # a mounted file; no per-job PS ConfigMap analog) and no endpoints
    # (election uses the Lease lock).
    assert "configmaps" not in by_group[""]
    assert "endpoints" not in by_group[""]
    assert "leases" in by_group["coordination.k8s.io"]
    binding = next(d for d in docs if d["kind"] == "ClusterRoleBinding")
    assert binding["subjects"][0]["namespace"] == "default"


def test_chart_configmap_parses_as_controller_config():
    rendered = render_chart.render_chart()
    cm = next(iter(yaml.safe_load_all(rendered["config.yaml"])))
    body = yaml.safe_load(cm["data"]["controller_config_file.yaml"])
    cfg = types.ControllerConfig.from_dict(body)
    assert "cloud-tpus.google.com/v4" in cfg.accelerators
    assert cfg.accelerators["cloud-tpus.google.com/v4"].env_vars[
        "TPU_ACCELERATOR_TYPE"] == "v4"


def test_chart_deployment_wires_config_and_identity_env():
    rendered = render_chart.render_chart()
    dep = next(iter(yaml.safe_load_all(rendered["deployment.yaml"])))
    pod = dep["spec"]["template"]["spec"]
    container = pod["containers"][0]
    assert "--controller-config-file" in container["command"]
    assert "--json-log-format" in container["command"]
    env = {e["name"] for e in container["env"]}
    assert {"MY_POD_NAMESPACE", "MY_POD_NAME"} <= env
    assert pod["volumes"][0]["configMap"]["name"] == "tpu-job-operator-config"


# --- tooling ----------------------------------------------------------------

def test_cleanup_script_uses_real_label_selector():
    # The reference's cleanup script greps a stale selector (kubeflow.org=,
    # hack/scripts/cleanup_clusters.sh:5-7) that matches nothing. Ours must
    # use the label the operator actually stamps.
    text = (REPO / "hack" / "cleanup_clusters.sh").read_text()
    kubectl_lines = [ln for ln in text.splitlines()
                     if ln.strip().startswith("kubectl")]
    assert any("-l " + types.LABEL_GROUP_KEY + "=" in ln for ln in kubectl_lines)
    assert not any("kubeflow.org" in ln for ln in kubectl_lines)


def test_dockerfiles_reference_real_entrypoints():
    op = (REPO / "build" / "images" / "tpu_operator" / "Dockerfile").read_text()
    assert "tpu_operator.cmd.main" in op
    payload = (REPO / "build" / "images" / "tpu_payload" / "Dockerfile").read_text()
    assert "jax[tpu]" in payload


def test_render_chart_cli_outputs_multi_doc_yaml():
    out = subprocess.run(
        [sys.executable, str(REPO / "hack" / "render_chart.py"), "tpu-system"],
        capture_output=True, text=True, check=True,
    ).stdout
    docs = [d for d in yaml.safe_load_all(out) if d]
    assert len(docs) >= 5
