"""Fake cluster tests: node/kubelet state machines, seeded storm plans,
and the chaos-composition soak (ISSUE 17).

Three layers:

1. **State machine units** — a pod created in the backing store walks
   Pending/ContainerCreating → Running (bound to a node, heartbeating
   through a status-server stub) → Succeeded, driven entirely by the
   cluster's pump/timer threads; NotReady nodes hold pods unbound;
   preemption produces the exact kubelet-level shape
   (``Failed``/``Preempted``, **no** container record) trainer/policy.py
   classifies as a preemption-kind restart.

2. **Storm determinism** — the entire kill/flap schedule derives from
   ``(seed, sorted identities, waves)``: same seed → bit-identical
   ``repr``, plan unchanged by live cluster mutation, paired end events
   always emitted. This is what makes a failing soak seed reproducible
   from its printed number alone (docs/design.md).

3. **Chaos composition** — FlakyClientset at 10% × a pod-kill storm × a
   blob fault hook, simultaneously, against a small fake cluster: a
   checkpointed job still reaches Done *through Backoff* with
   preemption-kind (never application-kind) ledger records.

Plus the inventory flap-debounce regression (a NotReady→Ready flap
inside ``--node-debounce-seconds`` drives ZERO FleetScheduler
churn) — one of the two named scale-risk surfaces in the issue.
"""

import random
import threading
import time

from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.workqueue import RateLimitingQueue
from tpu_operator.controller.chaos import ChaosMonkey, FlakyClientset
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import Metrics
from tpu_operator.scheduler.inventory import SliceInventory
from tpu_operator.store.blob import FakeBackend
from tpu_operator.testing.cluster import (
    FakeCluster,
    FakeNode,
    KubeletProfile,
    StormController,
    make_nodes,
)
from tpu_operator.testing.waiting import make_wait_for

wait_for = make_wait_for(timeout=10.0, interval=0.02)


def bare_pod(name, job="j", idx=0, attempt=0):
    """A pod exactly as the operator creates it: labeled, no status."""
    return {
        "metadata": {"name": name, "labels": {
            t.LABEL_JOB_NAME: job,
            t.LABEL_TASK_INDEX: str(idx),
            t.LABEL_ATTEMPT: str(attempt),
        }},
        "spec": {"containers": [{"name": "tpu"}]},
    }


def pod_status(cs, name):
    try:
        return cs.pods.get("default", name).get("status") or {}
    except Exception:  # noqa: BLE001 — deleted
        return {}


# --- node identity feeds discovery -------------------------------------------

def test_fake_nodes_feed_slice_inventory_discovery():
    nodes = make_nodes(4, slices=2)
    inv = SliceInventory.from_node_objects([n.manifest() for n in nodes])
    assert inv.capacities() == {"cloud-tpus.google.com/v4:2x2x2": 2}
    # NotReady nodes drop out of the discovered model — the condition
    # the kubelet layer flips is the condition discovery reads.
    half = [n.manifest(ready=(i % 2 == 0)) for i, n in enumerate(nodes)]
    assert SliceInventory.from_node_objects(half).capacities() == {
        "cloud-tpus.google.com/v4:2x2x2": 1}


# --- pod state machine -------------------------------------------------------

def test_pod_walks_kubelet_state_machine():
    backing = FakeClientset()
    with FakeCluster(backing, nodes=tuple(make_nodes(2, slices=2)),
                     profile=KubeletProfile(create_latency=0.3,
                                            run_seconds=0.3)):
        backing.pods.create("default", bare_pod("p-0"))
        # Pending + ContainerCreating, already bound to a node.
        wait_for(lambda: pod_status(backing, "p-0").get("phase") == "Pending")
        pod = backing.pods.get("default", "p-0")
        assert pod["spec"]["nodeName"].startswith("node-")
        waiting = pod["status"]["containerStatuses"][0]["state"]["waiting"]
        assert waiting["reason"] == "ContainerCreating"
        # Running/Ready after the create latency.
        wait_for(lambda: pod_status(backing, "p-0").get("phase") == "Running")
        status = pod_status(backing, "p-0")
        assert status["containerStatuses"][0]["ready"] is True
        # Terminal with a clean container record after run_seconds.
        wait_for(lambda: pod_status(backing, "p-0").get("phase")
                 == "Succeeded")
        status = pod_status(backing, "p-0")
        term = status["containerStatuses"][0]["state"]["terminated"]
        assert term["exitCode"] == 0


def test_instant_profile_is_a_single_status_write():
    backing = FakeClientset()
    with FakeCluster(backing, profile=KubeletProfile()):
        backing.pods.create("default", bare_pod("p-0"))
        wait_for(lambda: pod_status(backing, "p-0").get("phase")
                 == "Succeeded")
        writes = [a for a in backing.actions
                  if a[0] == "update" and a[3] == "p-0"]
        # The budget benches depend on this: no intermediate phases.
        assert len(writes) == 1, backing.actions


def test_not_ready_nodes_hold_pods_unbound():
    backing = FakeClientset()
    nodes = tuple(make_nodes(1, slices=1))
    with FakeCluster(backing, nodes=nodes,
                     profile=KubeletProfile()) as cluster:
        cluster.set_node_ready(nodes[0].name, False)
        backing.pods.create("default", bare_pod("p-0"))
        time.sleep(0.5)  # several bind-retry rounds
        assert pod_status(backing, "p-0") == {}  # still Pending, unbound
        assert cluster.tracked_pods() == 1
        # The node recovers: the held pod binds and completes.
        cluster.set_node_ready(nodes[0].name, True)
        wait_for(lambda: pod_status(backing, "p-0").get("phase")
                 == "Succeeded")


def test_heartbeats_flow_through_status_server():
    beats = []

    class ServerStub:
        def record_heartbeat(self, body):
            beats.append(body)

    backing = FakeClientset()
    with FakeCluster(backing, nodes=tuple(make_nodes(1, slices=1)),
                     profile=KubeletProfile(run_seconds=0.5,
                                            heartbeat_interval=0.05),
                     status_server=ServerStub()):
        backing.pods.create("default",
                            bare_pod("p-0", job="train", idx=1, attempt=2))
        wait_for(lambda: len(beats) >= 3)
        assert beats[0]["name"] == "train"
        assert beats[0]["processId"] == 1
        assert beats[0]["attempt"] == 2
        steps = [b["step"] for b in beats[:3]]
        assert steps == sorted(steps) and len(set(steps)) == 3


def test_preemption_has_kubelet_level_shape():
    backing = FakeClientset()
    nodes = tuple(make_nodes(2, slices=2))
    with FakeCluster(backing, nodes=nodes,
                     profile=KubeletProfile(run_seconds=30.0)) as cluster:
        backing.pods.create("default", bare_pod("p-0"))
        wait_for(lambda: pod_status(backing, "p-0").get("phase") == "Running")
        bound = backing.pods.get("default", "p-0")["spec"]["nodeName"]
        slice_id = cluster.get_node(bound).slice_id
        victims = cluster.preempt_slices([slice_id])
        assert victims == ["p-0"]
        status = pod_status(backing, "p-0")
        # The exact shape trainer/policy.py reads as PREEMPTION-kind:
        # kubelet-level Failed, reason Preempted, NO container record.
        assert status["phase"] == "Failed"
        assert status["reason"] == "Preempted"
        assert "containerStatuses" not in status
        # Pods on other slices are untouched.
        assert cluster.preempt_slices(["no-such-slice"]) == []


def test_deleted_pod_leaves_the_state_machine():
    backing = FakeClientset()
    with FakeCluster(backing, nodes=tuple(make_nodes(1, slices=1)),
                     profile=KubeletProfile(run_seconds=30.0)) as cluster:
        backing.pods.create("default", bare_pod("p-0"))
        wait_for(lambda: cluster.tracked_pods() == 1)
        wait_for(lambda: pod_status(backing, "p-0").get("phase") == "Running")
        backing.pods.delete("default", "p-0")
        wait_for(lambda: cluster.tracked_pods() == 0)


# --- seeded storms -----------------------------------------------------------

STORM_WAVES = (
    (0.0, "preempt", {"count": 4, "sweeps": 3, "interval": 0.5}),
    (1.0, "flap", {"count": 3, "down_seconds": 0.4}),
    (2.0, "drain", {"down_seconds": 1.0}),
    (3.0, "api_fault", {"rate": 0.2, "seconds": 1.5}),
    (4.0, "slow_kubelet", {"scale": 4.0, "seconds": 1.0}),
    (5.0, "pod_kill", {}),
    (6.0, "blob_fault", {"seconds": 0.5}),
)


def storm_on(cluster, seed):
    return StormController(cluster, seed, STORM_WAVES)


def test_storm_plan_replays_bit_identically():
    backing = FakeClientset()
    cluster = FakeCluster(backing, nodes=tuple(make_nodes(32, slices=16)))
    plan = [repr(e) for e in storm_on(cluster, 1234).plan()]
    # Same seed, same cluster shape → bit-identical schedule; a second
    # controller instance sees the same world the failing run printed.
    assert [repr(e) for e in storm_on(cluster, 1234).plan()] == plan
    assert [repr(e) for e in storm_on(cluster, 4321).plan()] != plan
    # Paired end events exist for every window-shaped wave.
    kinds = [e.kind for e in storm_on(cluster, 1234).plan()]
    for on, off in (("flap_down", "flap_up"), ("drain", "return"),
                    ("api_fault_on", "api_fault_off"),
                    ("slow_on", "slow_off"), ("blob_on", "blob_off")):
        assert kinds.count(on) == 1 and kinds.count(off) == 1
    # A preempt window sweeps the SAME seeded targets, not fresh draws.
    sweeps = [e for e in storm_on(cluster, 1234).plan()
              if e.kind == "preempt"]
    assert len(sweeps) == 3
    assert len({tuple(e.params["slice_ids"]) for e in sweeps}) == 1


def test_storm_plan_ignores_live_cluster_mutation():
    backing = FakeClientset()
    cluster = FakeCluster(backing, nodes=tuple(make_nodes(8, slices=4)))
    storm = storm_on(cluster, 7)
    before = [repr(e) for e in storm.plan()]
    # The identity snapshot is taken at construction: draining a node
    # mid-storm must not shift later waves of the SAME plan.
    cluster.drain_node(cluster.node_names()[0])
    assert [repr(e) for e in storm.plan()] == before


def test_storm_run_applies_and_unwinds_fault_windows():
    backing = FakeClientset()
    nodes = tuple(make_nodes(4, slices=2))
    flaky = FlakyClientset(FakeClientset(), error_rate=0.0,
                           rng=random.Random(3))
    blob_log = []
    with FakeCluster(backing, nodes=nodes) as cluster:
        storm = StormController(
            cluster, seed=5,
            waves=((0.0, "api_fault", {"rate": 0.5, "seconds": 0.1}),
                   (0.1, "drain", {"down_seconds": 0.1}),
                   (0.3, "blob_fault", {"seconds": 0.1}),
                   (0.5, "slow_kubelet", {"scale": 9.0, "seconds": 0.1})),
            flaky=flaky,
            blob_arm=lambda: blob_log.append("armed"),
            blob_disarm=lambda: blob_log.append("disarmed"))
        storm.run()
        assert storm.window is not None
        assert flaky.error_rate == 0.0          # fault window unwound
        assert blob_log == ["armed", "disarmed"]
        assert sorted(cluster.node_names()) == sorted(
            n.name for n in nodes)              # drained node returned
        backing_nodes = {n["metadata"]["name"]
                         for n in backing.nodes.list("")}
        assert backing_nodes == {n.name for n in nodes}


# --- inventory flap debounce (named scale-risk regression) -------------------

def test_node_flap_inside_debounce_window_causes_zero_inventory_churn():
    """A NotReady→Ready flap inside --node-debounce-seconds must drive
    ZERO FleetScheduler.update_inventory calls: without the window every
    kubelet heartbeat blip would release/re-admit the Queued head at
    fleet scale. A shrink that OUTLIVES the window still applies, and
    recovery growth applies immediately."""
    backing = FakeClientset()
    cluster = FakeCluster(backing, nodes=tuple(make_nodes(2, slices=2)))
    config = t.ControllerConfig(discover_slice_inventory=True,
                                node_debounce_seconds=0.6)
    factory = SharedInformerFactory(backing, "default", resync_period=0)
    controller = Controller(backing, factory, config, "default", shards=1)

    calls = []
    orig = controller.scheduler.update_inventory

    def counting(caps):
        calls.append(dict(caps))
        return orig(caps)

    controller.scheduler.update_inventory = counting
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(1, stop),
                              daemon=True)
    runner.start()
    key = "cloud-tpus.google.com/v4:2x2x2"
    try:
        wait_for(lambda: controller.scheduler.summary()["inventory"]
                 .get(key, {}).get("capacity") == 2)
        time.sleep(0.2)  # let the initial add burst fully settle
        settled = len(calls)

        flapped = cluster.node_names()[0]
        cluster.set_node_ready(flapped, False)
        time.sleep(0.2)  # well inside the 0.6 s window
        cluster.set_node_ready(flapped, True)
        time.sleep(1.2)  # past where the withheld shrink would fire
        assert calls[settled:] == [], calls[settled:]
        assert controller.scheduler.summary()["inventory"][key][
            "capacity"] == 2

        # A real outage (shrink outliving the window) DOES apply...
        cluster.set_node_ready(flapped, False)
        wait_for(lambda: controller.scheduler.summary()["inventory"]
                 .get(key, {}).get("capacity") == 1, timeout=5.0)
        # ...and recovery growth applies on the very node event.
        cluster.set_node_ready(flapped, True)
        wait_for(lambda: controller.scheduler.summary()["inventory"]
                 .get(key, {}).get("capacity") == 2, timeout=2.0)
    finally:
        stop.set()
        runner.join(timeout=5.0)


# --- chaos composition -------------------------------------------------------

def soak_job():
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": "soak", "namespace": "default"},
        "spec": {
            "replicaSpecs": [{
                "replicas": 2, "tpuReplicaType": "WORKER", "tpuPort": 8476,
                "template": {"spec": {"containers": [{"name": "tpu"}]}},
            }],
            # ONE application restart: two preemptions only fit the
            # preemption budget — any application-kind classification
            # fails the job before Done.
            "maxRestarts": 1,
            "checkpointDir": "/ckpt/soak",
            "restartBackoff": {"baseSeconds": 1, "maxSeconds": 4},
        },
    }


def test_storm_coop_drain_deadline_expiry_hard_kills_and_reaches_done():
    """Seeded storm wave for the cooperative-drain backstop: a
    ``coop_drain`` wave stamps a maintenance drain against a Running
    fake-cluster gang whose pods never speak the drain protocol (no ACK,
    no planned exit). The 1 s ``spec.drain.deadlineSeconds`` expires via
    the DeadlineManager wakeup, the gang is hard-killed exactly like the
    pre-drain behavior (billed preemption), and the re-ganged attempt
    still runs to Done — a wedged payload degrades, never hangs."""
    backing = FakeClientset()
    metrics = Metrics()
    factory = SharedInformerFactory(backing, "default", resync_period=1.0)
    controller = Controller(
        backing, factory, namespace="default", metrics=metrics,
        queue=RateLimitingQueue(base_delay=0.1, max_delay=0.5))
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()

    nodes = tuple(make_nodes(2, slices=2))
    cluster = FakeCluster(backing, nodes=nodes,
                          profile=KubeletProfile(create_latency=0.02,
                                                 run_seconds=3.0))
    cluster.start()

    job = soak_job()
    job["metadata"]["name"] = "cdr"
    job["spec"]["drain"] = {"deadlineSeconds": 1}

    def request_drain():
        tj = controller.jobs.get("default/cdr")
        if tj is not None:
            tj.request_maintenance_drain("node-0",
                                         tj.job.status.attempt)
            controller.queue.add("default/cdr")

    storm = StormController(cluster, seed=99,
                            waves=((0.1, "coop_drain", {}),),
                            drain_request=request_drain)

    def job_status():
        try:
            return backing.tpujobs.get("default", "cdr").get("status") or {}
        except Exception:  # noqa: BLE001 — racing creation
            return {}

    try:
        backing.tpujobs.create("default", job)
        assert wait_for(lambda: job_status().get("phase") == "Running",
                        timeout=15.0)
        storm.run()
        assert storm.stats.get("coop_drains") == 1
        # The directive lands, the payload never reacts, the deadline
        # hard-kills: attempt bumps with a preemption-kind record.
        assert wait_for(lambda: job_status().get("attempt", 0) >= 1,
                        timeout=15.0), job_status()
        assert wait_for(lambda: job_status().get("phase") == "Done",
                        timeout=30.0), job_status()
        status = job_status()
        assert status["state"] == "Succeeded"
        assert (status.get("drain") or {}).get("state") == "Expired"
        kinds = [f["kind"] for f in status.get("failures") or []]
        assert kinds and set(kinds) == {"preemption"}, status.get("failures")
        reasons = [e.get("reason") for e in backing.events.list("default")]
        assert "DrainRequested" in reasons
        assert "DrainDeadlineExpired" in reasons
    finally:
        stop.set()
        cluster.stop()
        runner.join(timeout=10.0)


def test_chaos_composition_checkpointed_job_survives_storm():
    """FlakyClientset (10% injected 429/500s) × pod-kill storm × blob
    fault hook, all live at once over a small fake cluster: the
    checkpointed job reaches Done through Backoff, and the ledger holds
    preemption-kind records only."""
    backing = FakeClientset()
    metrics = Metrics()
    flaky = FlakyClientset(backing, error_rate=0.10,
                           rng=random.Random(7), metrics=metrics)
    factory = SharedInformerFactory(flaky, "default", resync_period=1.0)
    controller = Controller(
        flaky, factory, namespace="default", metrics=metrics,
        queue=RateLimitingQueue(base_delay=0.2, max_delay=1.0))
    stop = threading.Event()
    runner = threading.Thread(target=controller.run, args=(2, stop),
                              daemon=True)
    runner.start()

    blob = FakeBackend()

    def blob_fault(op, key):
        raise IOError(f"chaos: injected blob fault on {op} {key}")

    nodes = tuple(make_nodes(4, slices=2))
    cluster = FakeCluster(backing, nodes=nodes,
                          profile=KubeletProfile(create_latency=0.02,
                                                 run_seconds=0.6))
    cluster.start()
    monkey = ChaosMonkey(backing, "default", level=1,
                         rng=random.Random(11), metrics=metrics)
    storm = StormController(
        cluster, seed=1234,
        waves=tuple([(0.3 * i, "pod_kill", {}) for i in range(6)]
                    + [(0.2, "blob_fault", {"seconds": 1.2})]),
        monkey=monkey,
        blob_arm=lambda: setattr(blob, "fault_hook", blob_fault),
        blob_disarm=lambda: setattr(blob, "fault_hook", None))
    storm_thread = threading.Thread(target=storm.run, daemon=True)

    def job_status():
        try:
            return backing.tpujobs.get("default", "soak").get("status") or {}
        except Exception:  # noqa: BLE001 — racing creation
            return {}

    try:
        backing.tpujobs.create("default", soak_job())
        storm_thread.start()

        # The blob window is REAL: while armed, the store layer fails.
        wait_for(lambda: blob.fault_hook is not None, timeout=5.0)
        try:
            blob.put("ckpt/probe", b"x")
            raise AssertionError("armed blob backend accepted a put")
        except IOError:
            pass

        # Deterministic preemption pressure, exactly the chaos-soak
        # pattern: generations 0 and 1 die Preempted (kubelet-level, via
        # the cluster's own injector so the sims stay coherent); the
        # storm's kill/blob/API faults rage around them the whole time.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline \
                and job_status().get("attempt", 0) < 2:
            early = [
                p["metadata"]["name"]
                for p in backing.pods.list("default")
                if (p["metadata"].get("labels") or {})
                .get("attempt") in ("0", "1")
                and (p.get("status") or {}).get("phase")
                not in ("Failed", "Succeeded")]
            cluster.preempt_pods(early)
            time.sleep(0.05)
        assert job_status().get("attempt", 0) >= 2, job_status()

        wait_for(lambda: job_status().get("phase") == "Done",
                 timeout=30.0)
        status = job_status()
        assert status["state"] == "Succeeded"
        # Both restarts were spaced through Backoff...
        assert "Backoff" in (status.get("phaseTimeline") or {}), status
        # ...and classified as preemption-kind: the application budget
        # (maxRestarts=1) was never touched despite the monkey and the
        # injected API faults running throughout.
        kinds = [f["kind"] for f in status.get("failures") or []]
        assert kinds and set(kinds) == {"preemption"}, status.get("failures")

        storm_thread.join(timeout=10.0)
        assert not storm_thread.is_alive()
        # The composition actually happened: API faults were injected,
        # and the blob window armed + disarmed around real failures.
        assert metrics.snapshot()["chaos_api_errors_total"] > 0
        assert blob.fault_hook is None
    finally:
        stop.set()
        cluster.stop()
        runner.join(timeout=10.0)
