"""Data-plane tests on the virtual 8-device CPU mesh.

Covers the BASELINE configs' compute side: linear regression convergence
(config 2), data-parallel CIFAR ResNet (config 3) including the loss-parity
check (sharded run matches single-device run), tensor-parallel sharding, and
the bootstrap env-contract parsing (the consumer of replicas.py's injection).
"""

import numpy as np
import pytest

import jax

from tpu_operator.payload import bootstrap
from tpu_operator.payload import data as data_mod
from tpu_operator.payload import train


@pytest.fixture(scope="module")
def devices():
    ds = jax.devices()
    assert len(ds) >= 8, "conftest must provide 8 virtual CPU devices"
    return ds


# --- bootstrap env contract ---------------------------------------------------

def test_process_info_parses_operator_env():
    env = {
        "JAX_COORDINATOR_ADDRESS": "train-worker-ab12-0:8476",
        "JAX_PROCESS_ID": "2",
        "JAX_NUM_PROCESSES": "4",
        "TPU_WORKER_ID": "2",
        "TPU_WORKER_HOSTNAMES": "w0,w1,w2,w3",
        "TPUJOB_NAME": "train",
        "TPUJOB_REPLICA_TYPE": "worker",
        "TPUJOB_ATTEMPT": "1",
    }
    info = bootstrap.process_info_from_env(env)
    assert info.coordinator_address == "train-worker-ab12-0:8476"
    assert info.process_id == 2
    assert info.num_processes == 4
    assert info.worker_hostnames == ("w0", "w1", "w2", "w3")
    assert info.attempt == 1


def test_initialize_single_process_skips_distributed():
    info = bootstrap.initialize(bootstrap.ProcessInfo(
        coordinator_address="", process_id=0, num_processes=1,
        worker_id=0, worker_hostnames=()))
    assert info.num_processes == 1


def test_run_payload_exit_codes():
    assert bootstrap.run_payload(lambda info: None) == 0
    assert bootstrap.run_payload(
        lambda info: (_ for _ in ()).throw(RuntimeError("boom"))) == 1
    assert bootstrap.run_payload(
        lambda info: (_ for _ in ()).throw(SystemExit(143))) == 143


# --- mesh construction --------------------------------------------------------

def test_make_mesh_shapes(devices):
    mesh = train.make_mesh(8)
    assert mesh.devices.shape == (8, 1)
    assert mesh.axis_names == ("data", "model")
    mesh_tp = train.make_mesh(8, model_parallel=2)
    assert mesh_tp.devices.shape == (4, 2)
    with pytest.raises(ValueError, match="divisible"):
        train.make_mesh(6, model_parallel=4)


# --- linear regression (BASELINE config 2) -----------------------------------

def test_linear_regression_converges_on_mesh(devices):
    from tpu_operator.payload.linear import parse_args, run

    args = parse_args(["--steps", "150", "--batch", "256", "--dim", "4"])
    info = bootstrap.ProcessInfo("", 0, 1, 0, ())
    loss = run(info, args)
    assert loss < 1e-3


# --- CIFAR ResNet (BASELINE config 3) ----------------------------------------

def tiny_args(extra=()):
    from tpu_operator.payload.cifar import parse_args

    return parse_args([
        "--steps", "6", "--batch", "32", "--blocks", "1",
        "--widths", "8", "8", "8", "--log-every", "0", *extra,
    ])


def test_cifar_resnet_loss_descends(devices):
    from tpu_operator.payload.cifar import build

    args = tiny_args()
    mesh, _model, state, step, batches = build(args)
    first = None
    for i in range(args.steps):
        arrays = data_mod.put_global_batch(mesh, *next(batches))
        state, metrics = step(state, *arrays)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert last < first, f"loss did not descend: {first} -> {last}"


def test_cifar_batch_is_sharded_over_data_axis(devices):
    from tpu_operator.payload.cifar import build

    args = tiny_args()
    mesh, *_ = build(args)
    images, _labels = data_mod.put_global_batch(
        mesh, *next(data_mod.synthetic_cifar(0, 32)))
    # 8-way data mesh → each device holds batch/8
    assert len(images.addressable_shards) == 8
    assert images.addressable_shards[0].data.shape[0] == 4


def test_loss_parity_single_vs_sharded(devices):
    """BASELINE correctness: the 8-device data-parallel run computes the
    same math as a single-device run (same seed, same batches)."""
    from tpu_operator.payload.cifar import build

    losses = {}
    for n in (1, 8):
        args = tiny_args()
        mesh = train.make_mesh(n)
        mesh, _m, state, step, batches = build(args, mesh=mesh)
        for _ in range(4):
            arrays = data_mod.put_global_batch(mesh, *next(batches))
            state, metrics = step(state, *arrays)
        losses[n] = float(metrics["loss"])
    assert losses[1] == pytest.approx(losses[8], rel=2e-2), losses


def test_tensor_parallel_head_is_sharded(devices):
    from tpu_operator.payload.cifar import build

    args = tiny_args(["--model-parallel", "2"])
    mesh, _model, state, step, batches = build(args)
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    state, metrics = step(state, *arrays)  # compiles + runs with TP constraint
    head_kernel = state.params["head"]["kernel"]
    # sharded over the model axis: each shard holds half the classes
    shards = head_kernel.addressable_shards
    assert any(s.data.shape[1] == head_kernel.shape[1] // 2 for s in shards)
    assert np.isfinite(float(metrics["loss"]))


def test_train_step_donation_no_leak(devices):
    """Donated state means the old buffers are consumed — re-using the stale
    handle must raise, proving in-place HBM update."""
    from tpu_operator.payload.cifar import build

    args = tiny_args()
    mesh, _m, state, step, batches = build(args)
    arrays = data_mod.put_global_batch(mesh, *next(batches))
    new_state, _ = step(state, *arrays)
    with pytest.raises(RuntimeError):
        _ = np.asarray(jax.tree_util.tree_leaves(state.params)[0])


def test_npz_classification_deterministic_and_trains(tmp_path):
    import numpy as np

    from tpu_operator.payload import cifar, data as data_mod

    rng = np.random.default_rng(0)
    labels = np.arange(64) % 4
    # learnable: images carry their label in a constant channel offset
    images = (rng.normal(0.5, 0.05, (64, 32, 32, 3))
              + labels[:, None, None, None] * 0.2)
    path = tmp_path / "d.npz"
    np.savez(path, images=(images * 255).clip(0, 255).astype(np.uint8),
             labels=labels.astype(np.int64))

    a = data_mod.npz_classification(str(path), seed=3, batch=16)
    b = data_mod.npz_classification(str(path), seed=3, batch=16)
    for _ in range(6):  # crosses an epoch boundary at 4 batches/epoch
        ia, la = next(a)
        ib, lb = next(b)
        np.testing.assert_array_equal(la, lb)
        np.testing.assert_array_equal(ia, ib)
    assert ia.dtype == np.float32 and ia.max() <= 1.0 and la.dtype == np.int32

    args = cifar.parse_args(["--batch", "16", "--blocks", "1",
                             "--widths", "8", "8", "8",
                             "--data", str(path)])
    mesh, _m, state, step, batches = cifar.build(args)
    (imgs, lbls) = data_mod.put_global_batch(mesh, *next(batches))
    state, metrics = step(state, imgs, lbls)
    assert np.isfinite(float(metrics["loss"]))


def test_npz_classification_rejects_tiny_dataset(tmp_path):
    import numpy as np

    import pytest

    from tpu_operator.payload import data as data_mod

    path = tmp_path / "tiny.npz"
    np.savez(path, images=np.zeros((4, 32, 32, 3), np.uint8),
             labels=np.zeros(4, np.int64))
    with pytest.raises(ValueError, match="examples"):
        next(data_mod.npz_classification(str(path), seed=0, batch=16))


def test_npz_classification_validates_eagerly(tmp_path):
    import numpy as np

    import pytest

    from tpu_operator.payload import data as data_mod

    # out-of-range labels
    p1 = tmp_path / "badlabels.npz"
    np.savez(p1, images=np.zeros((32, 32, 32, 3), np.uint8),
             labels=np.full(32, 12, np.int64))
    with pytest.raises(ValueError, match="classes"):
        data_mod.npz_classification(str(p1), 0, 16, num_classes=10)
    # image/label length mismatch
    p2 = tmp_path / "ragged.npz"
    np.savez(p2, images=np.zeros((32, 32, 32, 3), np.uint8),
             labels=np.zeros(24, np.int64))
    with pytest.raises(ValueError, match="labels"):
        data_mod.npz_classification(str(p2), 0, 16)
    # wrong image shape
    p3 = tmp_path / "shape.npz"
    np.savez(p3, images=np.zeros((32, 28, 28, 1), np.uint8),
             labels=np.zeros(32, np.int64))
    with pytest.raises(ValueError, match="expects"):
        data_mod.npz_classification(str(p3), 0, 16,
                                    image_shape=data_mod.CIFAR_SHAPE)
    # pre-normalized floats are NOT rescaled
    p4 = tmp_path / "floats.npz"
    np.savez(p4, images=np.full((32, 32, 32, 3), 2.0, np.float32),
             labels=np.zeros(32, np.int64))
    imgs, _ = next(data_mod.npz_classification(str(p4), 0, 16))
    assert float(imgs.max()) == 2.0


def test_device_prefetch_preserves_order_and_bounds_lookahead():
    import jax

    from tpu_operator.payload import data as data_mod, train

    mesh = train.make_mesh(4)
    produced = []

    def stream(n):
        for i in range(n):
            produced.append(i)
            yield (np.full((4, 2), i, np.float32),)

    # Order: device batches come back exactly in stream order.
    out = [int(np.asarray(b[0])[0, 0])
           for b in data_mod.device_prefetch(mesh, stream(7), depth=2)]
    assert out == list(range(7))

    # Look-ahead bound: after consuming k batches, at most k + depth have
    # been pulled from the host stream.
    produced.clear()
    it = data_mod.device_prefetch(mesh, stream(10), depth=3)
    for k in range(1, 5):
        b = next(it)
        assert isinstance(b[0], jax.Array)
        assert len(produced) <= k + 3, (k, produced)

    # Streams shorter than depth still drain completely.
    assert len(list(data_mod.device_prefetch(mesh, stream(2), depth=5))) == 2
    assert list(data_mod.device_prefetch(mesh, stream(0), depth=2)) == []


def test_device_prefetch_depth_zero_is_strict_lockstep():
    from tpu_operator.payload import data as data_mod, train

    mesh = train.make_mesh(4)
    produced = []

    def stream(n):
        for i in range(n):
            produced.append(i)
            yield (np.full((4, 2), i, np.float32),)

    it = data_mod.device_prefetch(mesh, stream(5), depth=0)
    for k in range(1, 4):
        next(it)
        assert len(produced) == k  # no look-ahead at all


def test_token_file_lm_deterministic_and_resumable(tmp_path):
    """The memory-mapped token stream is an exact function of (file, seed):
    two iterators agree across epoch boundaries, and skipping N batches
    (train_loop's resume fast-forward) lands exactly where an uninterrupted
    run would be."""
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 97, size=2048).astype(np.uint16)
    path = tmp_path / "tokens.npy"
    np.save(path, tokens)

    mk = lambda: data_mod.token_file_lm(str(path), seed=5, batch=8,
                                        seq_len=32, vocab=97)
    a, b = mk(), mk()
    drawn = []
    for _ in range(12):  # 64 windows / 8 = 8 batches per epoch: crosses one
        (ta,) = next(a)
        (tb,) = next(b)
        np.testing.assert_array_equal(ta, tb)
        assert ta.shape == (8, 32) and ta.dtype == np.int32
        drawn.append(ta)
    # windows within one epoch never repeat
    first_epoch = np.concatenate([d.reshape(-1, 32) for d in drawn[:8]])
    assert len(np.unique(first_epoch[:, 0], axis=0)) >= 8

    resumed = mk()
    for _ in range(5):
        next(resumed)  # the fast-forward train_loop does on resume
    fresh = mk()
    for _ in range(5):
        next(fresh)
    np.testing.assert_array_equal(next(fresh)[0], next(resumed)[0])


def test_token_file_lm_validates_eagerly(tmp_path):
    p1 = tmp_path / "big.npy"
    np.save(p1, np.full(512, 300, np.int32))
    with pytest.raises(ValueError, match="vocab"):
        data_mod.token_file_lm(str(p1), 0, 4, 32, vocab=256)
    p2 = tmp_path / "short.npy"
    np.save(p2, np.zeros(64, np.int32))
    with pytest.raises(ValueError, match="windows"):
        data_mod.token_file_lm(str(p2), 0, 8, 32)
    p3 = tmp_path / "shape.npy"
    np.save(p3, np.zeros((8, 8), np.int32))
    with pytest.raises(ValueError, match="1-D"):
        data_mod.token_file_lm(str(p3), 0, 2, 4)


def test_transformer_trains_on_token_file(tmp_path):
    """--data end to end: the LM fits a strongly-structured real token file
    through the mmap path (loss must drop hard, proving the stream feeds
    actual file contents, not noise)."""
    from tpu_operator.payload import transformer

    # a file full of the same affine recurrence the synthetic stream uses
    a, b, vocab = 5, 17, 64
    seq = np.empty(4096, np.int64)
    seq[0] = 1
    for t in range(1, len(seq)):
        seq[t] = (a * seq[t - 1] + b) % vocab
    path = tmp_path / "corpus.npy"
    np.save(path, seq.astype(np.uint16))

    args = transformer.parse_args([
        "--batch", "8", "--seq-len", "32", "--dim", "64", "--heads", "2",
        "--layers", "2", "--vocab", str(vocab), "--lr", "1e-2",
        "--data", str(path)])
    mesh, _m, state, step, batches = transformer.build(args)
    losses = []
    for _ in range(30):
        (tok,) = data_mod.put_global_batch(mesh, *next(batches), spec=None)
        state, metrics = step(state, tok)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, losses[::6]
