"""End-to-end telemetry: payload heartbeat → CRD status → rollup → metrics,
plus reconcile trace IDs in spans and log records.

The operator runs in-process against the HTTP test apiserver (real REST
client, real informers, real status-subresource schema admission — so the
new ``status.phaseTimeline``/``status.lastHeartbeat`` fields prove they
pass a strict structural schema), while a simulated payload posts step
heartbeats exactly the way payload/heartbeat.py does in a pod.
"""

import json
import logging
import threading
import time
import urllib.request

import pytest

from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.util import tracing
from tpu_operator.testing.waiting import make_wait_for


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=20.0, interval=0.05)


def get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def worker_job(name, replicas=1):
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"replicaSpecs": [{
            "replicas": replicas, "tpuReplicaType": "WORKER",
            "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu",
                                                  "image": "x"}]}}}]},
    }


@pytest.fixture()
def harness():
    tracing.clear_spans()
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    # interval 0: persist every heartbeat immediately (the coalescing path
    # has its own test below)
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop), daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


def _run_job(api, cs, name):
    cs.tpujobs.create("default", worker_job(name))
    assert wait_for(lambda: len(api.clientset.pods.list("default")) >= 1)
    for pod in api.clientset.pods.list("default"):
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: cs.tpujobs.get("default", name)
                    .get("status", {}).get("phase") == "Running")


def test_heartbeat_flows_to_status_rollup_and_metrics(harness):
    api, cs, controller, server = harness
    _run_job(api, cs, "hb")

    # simulated payload: process 0 posts through the real reporter with the
    # env contract the operator injects into pods
    reporter = heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "hb", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": "0", "TPUJOB_ATTEMPT": "0",
    }, tokens_per_batch=2048)
    assert reporter is not None
    assert reporter.report(10, {"loss": 3.25})

    # → CRD status via the operator's normal write-back (strict schema!)
    assert wait_for(lambda: (cs.tpujobs.get("default", "hb")
                             .get("status", {}).get("lastHeartbeat")
                             or {}).get("step") == 10)
    status = cs.tpujobs.get("default", "hb")["status"]
    assert status["lastHeartbeat"]["loss"] == 3.25
    assert status["lastHeartbeat"]["time"]

    # phase timeline recorded Creating and Running, in order
    timeline = status["phaseTimeline"]
    assert set(timeline) >= {"Creating", "Running"}
    assert timeline["Creating"] <= timeline["Running"]

    # → /api/jobs rollup with derived durations
    jobs = json.loads(get(server.port, "/api/jobs"))
    (job,) = [j for j in jobs if j["name"] == "hb"]
    assert job["lastHeartbeat"]["step"] == 10
    assert "receivedAt" not in job["lastHeartbeat"]  # internal field
    assert job["phaseTimeline"]["Running"]
    assert job["durations"]["timeToRunningSeconds"] >= 0

    # → per-job gauges in /metrics
    body = get(server.port, "/metrics")
    assert ('tpu_operator_job_last_step{name="hb",namespace="default"} 10'
            in body)
    assert "tpu_operator_heartbeats_total 1" in body
    assert "tpu_operator_job_last_heartbeat_timestamp_seconds" in body

    # a second report carries derived step-time/tokens-per-sec
    reporter._clock = lambda: time.monotonic()  # keep real clock monotonic
    assert reporter.report(20, {"loss": 3.0})
    assert wait_for(lambda: (cs.tpujobs.get("default", "hb")
                             .get("status", {}).get("lastHeartbeat")
                             or {}).get("step") == 20)

    # negative loss is legal (some objectives); only loss is unbounded
    ok, _ = server.record_heartbeat({"namespace": "default", "name": "hb",
                                     "loss": -0.5})
    assert ok

    # failover: a fresh server (empty in-memory map) still emits the gauge,
    # seeded from persisted status.lastHeartbeat — stale, not absent
    failover = StatusServer(0, metrics=controller.metrics)
    failover.start()
    try:
        failover.set_controller(controller)
        body = get(failover.port, "/metrics")
        assert 'tpu_operator_job_last_step{name="hb",namespace="default"}' \
            in body
        assert "tpu_operator_job_last_heartbeat_timestamp_seconds" in body
    finally:
        failover.stop()


def test_heartbeat_rejects_garbage(harness):
    _api, _cs, _controller, server = harness
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/api/heartbeat",
        data=b"not json", headers={"Content-Type": "application/json"},
        method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 400
    ok, msg = server.record_heartbeat({"namespace": "default"})
    assert not ok and "name" in msg
    ok, msg = server.record_heartbeat({"name": "x", "step": "NaN-ish"})
    assert not ok
    # non-finite floats would poison CRD status JSON on a real apiserver
    ok, msg = server.record_heartbeat({"name": "x", "loss": float("nan")})
    assert not ok and "non-finite" in msg
    ok, msg = server.record_heartbeat({"name": "x",
                                       "tokensPerSec": float("inf")})
    assert not ok
    # negatives violate the CRD's minimum: 0 and would wedge status writes
    ok, msg = server.record_heartbeat({"name": "x", "step": -1})
    assert not ok and "negative" in msg
    # a heartbeat for a job the informer doesn't know is an error, not a
    # silent 200 — the payload's log must surface the misconfig
    ok, msg = server.record_heartbeat({"name": "x", "step": 1})
    assert not ok and "unknown job" in msg
    # a standby (no controller) must not blackhole heartbeats with a 200 —
    # 503 tells the payload to retry (and hit the leader next interval)
    solo = StatusServer(0)
    try:
        ok, msg = solo.record_heartbeat({"name": "x", "loss": -0.5})
        assert not ok and msg.startswith("standby")
    finally:
        solo.server.server_close()
    # oversized bodies are rejected before buffering
    big = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/api/heartbeat",
        data=b"x" * (65 * 1024),
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(big, timeout=5)
    assert ei.value.code == 413
    # bad ?limit= on the traces endpoint is a client error, not a 500
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/traces?limit=abc", timeout=5)
    assert ei.value.code == 400
    # a diverged payload still heartbeats, minus the loss field
    posts = []
    r = heartbeat_mod.HeartbeatReporter(
        "http://x:1", "j", poster=lambda _u, b: posts.append(b),
        clock=lambda: 0.0)
    assert r.report(1, {"loss": float("nan")})
    assert "loss" not in posts[0]


def test_reconcile_traces_and_log_tagging(harness):
    api, cs, _controller, server = harness
    _run_job(api, cs, "traced")

    spans = json.loads(get(server.port, "/api/traces"))["spans"]
    reconciles = [s for s in spans if s["name"] == "reconcile"]
    assert reconciles, spans
    root = reconciles[0]
    assert root["traceId"] and root["parentId"] == ""
    assert root["attrs"]["key"] == "default/traced"
    # nested @traced children share the root's trace id
    children = [s for s in spans
                if s["traceId"] == root["traceId"] and s is not root]
    assert any(s["name"].endswith("reconcile") or "sync" in s["name"]
               or "training" in s["name"] for s in children), spans
    for child in children:
        assert child["parentId"], child

    # ?limit= caps the response
    limited = json.loads(get(server.port, "/api/traces?limit=2"))["spans"]
    assert len(limited) == 2


def test_log_records_carry_trace_id():
    tracing.clear_spans()
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture()
    handler.addFilter(tracing._FilenameFilter())
    logger = logging.getLogger("test.trace.tag")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        with tracing.span("reconcile", key="ns/job") as sp:
            logger.info("inside")
        logger.info("outside")
    finally:
        logger.removeHandler(handler)
    inside, outside = records
    assert inside.trace_id == sp.trace_id
    assert f"trace={sp.trace_id} " == inside.trace_tag
    assert outside.trace_id == "" and outside.trace_tag == ""


def test_trace_flag_enter_exit_stream_still_works(caplog):
    tracing.enable(True)
    try:
        with caplog.at_level(logging.INFO, logger="tpu_operator.trace"):
            with tracing.span("outer"):
                with tracing.span("inner"):
                    pass
    finally:
        tracing.enable(False)
    text = caplog.text
    assert "[0]ENTER: outer" in text
    assert "[1]ENTER: inner" in text
    assert "[1]EXIT:  inner" in text
    assert "[0]EXIT:  outer" in text


def test_span_ring_buffer_bounded():
    tracing.configure(span_buffer=8)
    try:
        for i in range(50):
            with tracing.span(f"s{i}"):
                pass
        spans = tracing.recent_spans()
        assert len(spans) == 8
        assert spans[0]["name"] == "s49"  # newest first
    finally:
        tracing.configure(span_buffer=tracing.DEFAULT_SPAN_BUFFER)
        tracing.clear_spans()


def test_heartbeat_reporter_rate_limit_and_failure_isolation():
    clock_now = [0.0]
    posts = []

    def poster(url, body):
        posts.append((url, dict(body)))

    r = heartbeat_mod.HeartbeatReporter(
        "http://x:1", "job", interval=10.0, tokens_per_batch=100,
        clock=lambda: clock_now[0], poster=poster)
    assert r.maybe_report(1, {"loss": 1.0})
    assert not r.maybe_report(2)          # rate-limited
    clock_now[0] += 10.0
    assert r.maybe_report(11, {"loss": 0.5})
    assert len(posts) == 2
    second = posts[1][1]
    assert second["stepTimeSeconds"] == pytest.approx(1.0)  # 10s / 10 steps
    assert second["tokensPerSec"] == pytest.approx(100.0)
    assert second["loss"] == 0.5

    # a dead sink never raises into the training loop
    def exploding(_url, _body):
        raise OSError("connection refused")

    r2 = heartbeat_mod.HeartbeatReporter(
        "http://x:1", "job", poster=exploding, clock=lambda: 0.0)
    assert r2.report(1) is False

    # non-zero process → cadence-only reporter (straggler detection feed:
    # identity + step cadence + stepTiming, no loss/checkpoint/startup);
    # missing URL → disabled entirely.
    rn = heartbeat_mod.from_env({"TPUJOB_STATUS_URL": "http://x",
                                 "TPUJOB_NAME": "j",
                                 "JAX_PROCESS_ID": "1"})
    assert rn is not None and rn.cadence_only and rn.process_id == 1
    assert heartbeat_mod.from_env({"TPUJOB_NAME": "j"}) is None

    # a malformed interval knob must not kill training (best-effort contract)
    r3 = heartbeat_mod.from_env({"TPUJOB_STATUS_URL": "http://x",
                                 "TPUJOB_NAME": "j",
                                 "TPUJOB_HEARTBEAT_INTERVAL": "10s"})
    assert r3 is not None and r3.interval == heartbeat_mod.DEFAULT_INTERVAL


def test_heartbeat_persistence_coalesced():
    """Telemetry must not multiply apiserver load: within the persist
    interval, heartbeats update the in-memory status only; the first
    heartbeat and an attempt change enqueue an immediate write."""
    from tpu_operator.apis.tpujob.v1alpha1.types import TPUJob
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.trainer.training import TrainingJob

    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=3600.0)
    job = TPUJob.from_dict(worker_job("co"))
    controller.jobs["default/co"] = TrainingJob(cs, None, job)

    hb = {"time": "2026-08-03T00:00:00.000000Z", "step": 1, "attempt": 0}
    assert controller.record_heartbeat("default", "co", hb)
    assert controller.queue.get(timeout=0) == "default/co"  # first: persist
    controller.queue.done("default/co")

    hb2 = {"time": "2026-08-03T00:00:10.000000Z", "step": 2, "attempt": 0}
    assert controller.record_heartbeat("default", "co", hb2)
    assert len(controller.queue) == 0  # within interval: in-memory only
    assert controller.jobs["default/co"].job.status.last_heartbeat["step"] == 2

    hb3 = {"time": "2026-08-03T00:00:20.000000Z", "step": 0, "attempt": 1}
    assert controller.record_heartbeat("default", "co", hb3)
    assert controller.queue.get(timeout=0) == "default/co"  # attempt bump
    controller.queue.done("default/co")

    # steady sub-interval cadence must STILL persist once the interval has
    # elapsed since the last *persisted* stamp (not the last received one)
    controller.heartbeat_persist_interval = 25.0
    for sec, expect_queued in ((30, False), (40, False), (50, True)):
        hbn = {"time": f"2026-08-03T00:00:{sec}.000000Z",
               "step": sec, "attempt": 1}
        assert controller.record_heartbeat("default", "co", hbn)
        assert (len(controller.queue) > 0) == expect_queued, sec

    assert not controller.record_heartbeat("default", "nope", hb)


def test_stale_generation_heartbeat_dropped():
    """A terminating pod from the previous generation keeps posting during
    its grace period; its heartbeat must not refresh the stall watchdog's
    liveness baseline for the new (possibly hung) attempt."""
    from tpu_operator.apis.tpujob.v1alpha1.types import TPUJob
    from tpu_operator.client.fake import FakeClientset
    from tpu_operator.trainer.training import TrainingJob

    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0))
    job = TPUJob.from_dict(worker_job("gen"))
    job.status.attempt = 2
    controller.jobs["default/gen"] = TrainingJob(cs, None, job)

    stale = {"time": "2026-08-03T00:00:00.000000Z", "step": 9, "attempt": 1}
    # None (not False): the status server must tell a stale drop apart from
    # an unknown job — only the former skips the liveness-gauge stash
    assert controller.record_heartbeat("default", "gen", stale) is None
    assert job.status.last_heartbeat is None

    # a payload that doesn't post attempt must not be stall-looped after
    # the first restart: missing attempt is treated as current
    legacy = {"time": "2026-08-03T00:00:00.500000Z", "step": 9}
    assert controller.record_heartbeat("default", "gen", legacy) is True
    assert job.status.last_heartbeat["step"] == 9

    current = {"time": "2026-08-03T00:00:01.000000Z", "step": 0, "attempt": 2}
    assert controller.record_heartbeat("default", "gen", current)
    assert job.status.last_heartbeat["step"] == 0

    # newer-than-status (informer cache lagging a just-bumped attempt) is
    # accepted — dropping it would blind the watchdog on the live attempt
    newer = {"time": "2026-08-03T00:00:02.000000Z", "step": 1, "attempt": 3}
    assert controller.record_heartbeat("default", "gen", newer)
    assert job.status.last_heartbeat["attempt"] == 3


def test_tokens_per_batch_inference():
    import numpy as np

    from tpu_operator.payload import train

    # LM-shaped: one [B, T] integer array
    assert train._infer_tokens_per_batch(
        (np.zeros((4, 128), dtype=np.int32),)) == 512
    # classifier-shaped: (images, labels) → no token notion
    assert train._infer_tokens_per_batch(
        (np.zeros((4, 32, 32, 3), dtype=np.float32),
         np.zeros((4,), dtype=np.int32))) == 0
    # float batch → not tokens
    assert train._infer_tokens_per_batch(
        (np.zeros((4, 128), dtype=np.float32),)) == 0
