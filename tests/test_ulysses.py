"""Ulysses all-to-all sequence parallelism tests (8-device CPU mesh).

The second SP strategy (payload/ulysses.py) must be drop-in equal to ring
attention and the dense oracle — forward and gradients — and the
transformer payload must train under --sp-mode ulysses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_operator.payload import ring_attention as ring
from tpu_operator.payload import transformer, ulysses


def qkv(seed: int, b=2, t=64, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.fixture(scope="module")
def mesh():
    return transformer.make_lm_mesh(8, seq_parallel=4)  # (data=2, seq=4)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
def test_ulysses_matches_reference_forward(mesh, causal):
    q, k, v = qkv(0)
    want = ring.reference_attention(q, k, v, causal=causal)
    got = ulysses.ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_matches_reference_gradients(mesh):
    q, k, v = qkv(1)

    def loss_uly(q, k, v):
        out = ulysses.ulysses_attention(q, k, v, mesh, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        out = ring.reference_attention(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_matches_ring(mesh):
    q, k, v = qkv(2, t=32)
    a = ulysses.ulysses_attention(q, k, v, mesh, causal=True)
    b = ring.ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=1e-5, rtol=1e-5)


def test_ulysses_rejects_indivisible_heads(mesh):
    q, k, v = qkv(3, h=2)  # 2 heads, 4 seq shards
    with pytest.raises(ValueError, match="divisible"):
        ulysses.ulysses_attention(q, k, v, mesh, causal=True)


def test_ulysses_gqa_matches_reference(mesh):
    """GQA under ulysses: K/V all-to-all at kv_heads size (here 4 kv heads
    over 4 seq shards — one kv head per shard), forward + gradients vs the
    repeat-based oracle."""
    q, _, _ = qkv(4, h=8)
    _, k, v = qkv(5, h=4)

    def loss_uly(q, k, v):
        out = ulysses.ulysses_attention(q, k, v, mesh, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        out = ring.reference_attention(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_uly = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert g_uly[1].shape == k.shape
    for got, want in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_kv_heads(mesh):
    q, _, _ = qkv(6, h=4)
    _, k, v = qkv(7, h=2)  # 2 kv heads, 4 seq shards
    with pytest.raises(ValueError, match="kv_heads"):
        ulysses.ulysses_attention(q, k, v, mesh, causal=True)


def test_transformer_ulysses_matches_single_device_loss(mesh):
    argv = ["--batch", "4", "--seq-len", "64", "--dim", "32", "--heads", "4",
            "--layers", "2"]
    args_u = transformer.parse_args(
        argv + ["--seq-parallel", "4", "--sp-mode", "ulysses"])
    args_1 = transformer.parse_args(argv + ["--seq-parallel", "1"])
    mesh_1 = transformer.make_lm_mesh(1, seq_parallel=1)
    _, _, state_u, step_u, batches = transformer.build(args_u, mesh=mesh)
    _, _, state_1, step_1, _ = transformer.build(args_1, mesh=mesh_1)

    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod

    (tokens,) = next(batches)
    (dev_u,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", "seq"))
    (dev_1,) = data_mod.put_global_batch(mesh_1, tokens, spec=P())
    _, m_u = step_u(state_u, dev_u)
    _, m_1 = step_1(state_1, dev_1)
    assert abs(float(m_u["loss"]) - float(m_1["loss"])) < 2e-2


def test_transformer_ulysses_loss_descends(mesh):
    args = transformer.parse_args([
        "--steps", "30", "--batch", "8", "--seq-len", "64", "--dim", "64",
        "--heads", "4", "--layers", "2", "--seq-parallel", "4",
        "--sp-mode", "ulysses", "--log-every", "0", "--lr", "1e-2",
    ])
    _mesh, _model, state, step, batches = transformer.build(args, mesh=mesh)

    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import data as data_mod

    losses = []
    for _ in range(args.steps):
        (tokens,) = next(batches)
        (dev,) = data_mod.put_global_batch(mesh, tokens, spec=P("data", "seq"))
        state, metrics = step(state, dev)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, losses[::5]
