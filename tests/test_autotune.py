"""Self-tuning data plane: the autotune controller (hill climb,
hysteresis, regression backoff, clamps), dynamic prefetch-depth resize,
the background host pipeline, the async host path, the spec.dataPlane
wiring (types/schema/validation/env), and the dataPlane heartbeat chain
(payload → statusserver sanitization → controller fold → CRD status /
metrics / describe).

The e2e section drives the REAL operator over the in-process HTTP
apiserver (strict status-subresource schema admission) with a payload
reporter posting knob state, and asserts status.dataPlane, the
``job_prefetch_depth`` gauge, the ``job_autotune_adjustments_total``
counters, and the ``tpujobctl describe`` DataPlane lines.
"""

import contextlib
import io
import threading
import time

import numpy as np
import pytest

from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod
from tpu_operator.apis.tpujob.v1alpha1 import types
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.apis.tpujob.validation import (
    ValidationError,
    validate_tpujob_spec,
)
from tpu_operator.client.fake import FakeClientset
from tpu_operator.client.informer import SharedInformerFactory
from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.cmd import ctl
from tpu_operator.controller.controller import Controller
from tpu_operator.controller.statusserver import StatusServer
from tpu_operator.payload import autotune
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import steptrace
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for
from tpu_operator.trainer.training import TrainingJob

wait_for = make_wait_for(timeout=20.0, interval=0.05)


def worker_job(name, replicas=1, spec_extra=None):
    spec = {"replicaSpecs": [{
        "replicas": replicas, "tpuReplicaType": "WORKER", "tpuPort": 8476,
        "template": {"spec": {"containers": [{"name": "tpu",
                                              "image": "x"}]}}}]}
    spec.update(spec_extra or {})
    return {
        "apiVersion": "tpuoperator.dev/v1alpha1", "kind": "TPUJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def feed_window(ctl_, steps, data=0.0, host=0.0, ckpt=0.0, compute=0.010):
    """Drive one controller window of identical synthetic step records."""
    for _ in range(steps):
        ctl_.on_step({"seconds": compute + data + host + ckpt,
                      steptrace.DATA: data, steptrace.HOST: host,
                      steptrace.CHECKPOINT: ckpt,
                      steptrace.COMPUTE: compute})


# --- depth convention --------------------------------------------------------

def test_resolve_prefetch_depth_convention():
    assert autotune.resolve_prefetch_depth(0) == \
        autotune.DEFAULT_PREFETCH_DEPTH
    assert autotune.resolve_prefetch_depth(5) == 5
    with pytest.raises(ValueError):
        autotune.resolve_prefetch_depth(-1)


def test_device_prefetch_rejects_negative_depth():
    from tpu_operator.payload import data as data_mod, train

    mesh = train.make_mesh()
    with pytest.raises(ValueError):
        list(data_mod.device_prefetch(mesh, iter([]), depth=-2))


def test_from_env_gating():
    # no env → inert: caller's depth verbatim (0 stays unbuffered for
    # direct train_loop callers), no controller, no pipeline
    rt = autotune.from_env(prefetch=0, env={})
    assert rt.depth == 0 and not rt.active and not rt.pipeline
    assert rt.controller is None and rt.wire() is None
    # spec block without autotune → static depth + pipeline + wire
    rt = autotune.from_env(prefetch=0, env={
        autotune.ENV_PREFETCH_DEPTH: "5"})
    assert rt.depth == 5 and rt.active and rt.pipeline
    assert rt.controller is None
    assert rt.wire() == {"prefetchDepth": 5, "hostAsync": False}
    # autotune on: controller with env bounds, auto depth resolves
    rt = autotune.from_env(prefetch=0, env={
        autotune.ENV_PREFETCH_DEPTH: "0",
        autotune.ENV_AUTOTUNE: "1",
        autotune.ENV_MIN_DEPTH: "2",
        autotune.ENV_MAX_DEPTH: "6",
        autotune.ENV_WINDOW_STEPS: "16"})
    assert rt.controller is not None and rt.control is not None
    assert rt.controller.min_depth == 2 and rt.controller.max_depth == 6
    assert rt.controller.window_steps == 16
    assert rt.control.depth == autotune.DEFAULT_PREFETCH_DEPTH
    # an explicit --prefetch-depth wins over the env value
    rt = autotune.from_env(prefetch=3, env={
        autotune.ENV_PREFETCH_DEPTH: "5"})
    assert rt.depth == 3


def test_add_prefetch_argument_defaults_from_env():
    import argparse

    p = argparse.ArgumentParser()
    autotune.add_prefetch_argument(p, env={
        autotune.ENV_PREFETCH_DEPTH: "7"})
    assert p.parse_args([]).prefetch_depth == 7
    assert p.parse_args(["--prefetch-depth", "2"]).prefetch_depth == 2
    # malformed env never kills the payload at arg-parse time
    p2 = argparse.ArgumentParser()
    autotune.add_prefetch_argument(p2, env={
        autotune.ENV_PREFETCH_DEPTH: "lots"})
    assert p2.parse_args([]).prefetch_depth == 0


# --- controller --------------------------------------------------------------

def test_controller_converges_up_on_data_bound_digests():
    """DATA-bound windows climb the depth until the (synthetic) data wait
    stops dominating — the plant rewards depth, so the controller keeps
    each move and converges without a single revert."""
    control = autotune.PrefetchControl(1)
    c = autotune.DataPlaneController(control, min_depth=1, max_depth=8,
                                     window_steps=8)
    for _ in range(16):
        d = control.depth
        # data wait shrinks as depth covers the burst; compute 10 ms
        data = max(0.0, 0.006 - (d - 1) * 0.002)
        feed_window(c, 8, data=data)
    assert control.depth == 4  # climbed until DATA fell under the floor
    adj = c.adjustments()
    assert adj["prefetchUp"] == 3 and adj["prefetchDown"] == 0


def test_controller_backs_off_on_regression():
    control = autotune.PrefetchControl(2)
    c = autotune.DataPlaneController(control, window_steps=8)
    feed_window(c, 8, data=0.005)            # DATA dominant → depth 3
    assert control.depth == 3
    feed_window(c, 8, data=0.005, compute=0.025)  # step time regressed
    assert control.depth == 2                # reverted
    adj = c.adjustments()
    assert adj["prefetchUp"] == 1 and adj["prefetchDown"] == 1
    # the knob is now held: the same DATA-bound signal does not re-climb
    # within the hold window
    feed_window(c, 8, data=0.005)
    assert control.depth == 2


def test_controller_hysteresis_no_flap():
    """Steady digests with sub-hysteresis noise after convergence make NO
    adjustments — the no-flap contract."""
    control = autotune.PrefetchControl(2)
    c = autotune.DataPlaneController(control, window_steps=8)
    feed_window(c, 8, data=0.005)
    feed_window(c, 8, data=0.001)            # improved → accepted
    settled = dict(c.adjustments())
    for i in range(12):
        # ±1% step-time noise, residue under the materiality floor
        feed_window(c, 8, data=0.00005,
                    compute=0.010 * (1.0 + (0.01 if i % 2 else -0.01)))
    assert c.adjustments() == settled
    assert control.depth == 3


def test_controller_verdict_ignores_gang_wide_noise():
    """The verdict is the LOCAL share (step minus compute wait): a
    modest whole-step slowdown during the verdict window — a peer
    hiccup, equalized into COMPUTE by the gang's collectives — must not
    revert a change that improved the knob's own signal, else recurring
    gang noise pins every member's knobs. A large whole-step regression
    still reverts via the coarse step guard (the backs-off test)."""
    control = autotune.PrefetchControl(2)
    c = autotune.DataPlaneController(control, window_steps=8)
    feed_window(c, 8, data=0.005)                 # climb -> depth 3
    feed_window(c, 8, data=0.001, compute=0.010 * 1.05)
    assert control.depth == 3                     # kept
    adj = c.adjustments()
    assert adj["prefetchUp"] == 1 and adj["prefetchDown"] == 0


def test_controller_clamps_to_min_max():
    control = autotune.PrefetchControl(1)
    c = autotune.DataPlaneController(control, min_depth=1, max_depth=3,
                                     window_steps=8)
    for _ in range(10):
        feed_window(c, 8, data=0.008)        # permanently DATA-bound
    assert control.depth == 3                # never past maxDepth
    assert c.adjustments()["prefetchUp"] == 2
    # construction clamps an out-of-range starting depth too
    control2 = autotune.PrefetchControl(9)
    autotune.DataPlaneController(control2, min_depth=2, max_depth=4)
    assert control2.depth == 4


def test_controller_falls_through_to_next_knob_when_capped():
    """A clamped dominant knob must not dead-end the climb: the
    next-most-material phase's knob gets the window's action."""
    control = autotune.PrefetchControl(3)
    calls = []
    c = autotune.DataPlaneController(control, min_depth=1, max_depth=3,
                                     window_steps=8,
                                     enable_host_async=calls.append)
    # DATA dominates but depth is already at max; HOST is material too.
    feed_window(c, 8, data=0.006, host=0.004)
    assert control.depth == 3 and calls == [True]
    adj = c.adjustments()
    assert adj["hostUp"] == 1 and adj["prefetchUp"] == 0


def test_controller_host_knob_enables_async_path():
    control = autotune.PrefetchControl(2)
    calls = []
    c = autotune.DataPlaneController(control, window_steps=8,
                                     enable_host_async=calls.append)
    feed_window(c, 8, host=0.004)            # HOST dominates the residue
    assert c.host_async and calls == [True]
    feed_window(c, 8, host=0.0001)           # improved → accepted
    assert c.host_async
    adj = c.adjustments()
    assert adj["hostUp"] == 1 and adj["hostDown"] == 0


def test_controller_checkpoint_cadence_stretches_within_cap(tmp_path):
    from tpu_operator.payload import checkpoint

    class _State:
        pass

    ck = checkpoint.Checkpointer(str(tmp_path / "ck"), save_every=10)
    control = autotune.PrefetchControl(2)
    c = autotune.DataPlaneController(control, window_steps=8,
                                     checkpointer=ck)
    for _ in range(8):
        feed_window(c, 8, ckpt=0.004)        # CHECKPOINT-stall bound
    assert ck.cadence_multiplier == autotune.CHECKPOINT_CADENCE_CAP
    assert c.adjustments()["checkpointUp"] == 2  # 1 → 2 → 4, capped
    # maybe_save honors the stretched cadence: only every mult'th
    # interval boundary saves
    saved = []
    ck._save = lambda step, state, force: saved.append(step) or True
    ck.maybe_save(10, None)
    ck.maybe_save(20, None)
    ck.maybe_save(40, None)
    assert saved == [40]


def test_controller_survives_observer_exceptions():
    rec = steptrace.StepRecorder(capacity=16)

    def boom(_record):
        raise RuntimeError("observer bug")

    rec.on_commit = boom
    rec.begin(0)
    rec.lap(steptrace.COMPUTE)
    rec.commit()                              # must not raise
    assert rec.on_commit is None              # detached after the failure
    assert rec.steps_recorded == 1


# --- dynamic resize + pipeline ----------------------------------------------

def _byte_stream(n, rows=8):  # rows divisible by the 8-device test mesh
    rng = np.random.default_rng(7)
    for _ in range(n):
        yield (rng.normal(size=(rows, 3)).astype(np.float32),)


def test_dynamic_depth_resize_preserves_order_byte_identically():
    from tpu_operator.payload import data as data_mod, train

    mesh = train.make_mesh()
    static = [b[0].tobytes() for b in _byte_stream(20)]
    control = autotune.PrefetchControl(1)
    out = []
    for i, b in enumerate(data_mod.device_prefetch(
            mesh, _byte_stream(20), depth=1, control=control)):
        out.append(np.asarray(b[0]).tobytes())
        if i == 3:
            control.set_depth(7)              # grow mid-stream
        if i == 11:
            control.set_depth(1)              # shrink mid-stream
    assert out == static


def test_pipeline_preserves_order_and_propagates_errors():
    fed = list(range(10))

    def failing():
        for v in fed:
            yield v
        raise RuntimeError("stream died")

    it = iter(failing())
    pl = autotune.HostPipeline(fill=lambda: next(it), depth=3)
    got = [pl.get() for _ in range(10)]
    assert got == fed
    with pytest.raises(RuntimeError, match="stream died"):
        pl.get()
    pl.close()

    # clean end-of-stream raises StopIteration, close() never hangs
    it2 = iter([1, 2])
    pl2 = autotune.HostPipeline(fill=lambda: next(it2), depth=2)
    assert pl2.get() == 1 and pl2.get() == 2
    with pytest.raises(StopIteration):
        pl2.get()
    pl2.close()


def test_pipelined_device_prefetch_matches_sync_stream():
    from tpu_operator.payload import data as data_mod, train

    mesh = train.make_mesh()
    sync = [np.asarray(b[0]).tobytes() for b in data_mod.device_prefetch(
        mesh, _byte_stream(16), depth=2)]
    piped = [np.asarray(b[0]).tobytes() for b in data_mod.device_prefetch(
        mesh, _byte_stream(16), depth=2, pipeline=True)]
    assert piped == sync


def test_pipeline_thread_stops_when_consumer_abandons():
    it = iter(range(1000))
    gen_closed = threading.Event()

    def fill():
        try:
            return next(it)
        except StopIteration:
            gen_closed.set()
            raise

    pl = autotune.HostPipeline(fill=fill, depth=2)
    assert pl.get() == 0
    pl.close()
    assert not pl._thread.is_alive()
    # A post-close get() must raise, not park on a condition no worker
    # will ever signal (buffered leftovers still drain first).
    while True:
        try:
            pl.get()
        except StopIteration:
            break


# --- async host path ---------------------------------------------------------

def test_async_host_runs_work_in_order_and_bounds_queue():
    host = autotune.AsyncHost(capacity=64)
    ran = []
    done = threading.Event()
    for i in range(10):
        assert host.submit(ran.append, i)
    host.submit(lambda: done.set())
    assert done.wait(5)
    assert ran == list(range(10))
    host.close()
    assert not host.submit(ran.append, 99)    # closed → refused

    # a wedged worker bounds the queue and counts drops
    gate = threading.Event()
    slow = autotune.AsyncHost(capacity=2)
    slow.submit(gate.wait)                    # parks the worker
    time.sleep(0.05)
    assert slow.submit(lambda: None)
    assert slow.submit(lambda: None)
    assert not slow.submit(lambda: None)      # over capacity → dropped
    assert slow.dropped == 1
    gate.set()
    slow.close()


def test_heartbeat_async_sink_defers_posts_but_not_startup():
    posts = []
    gate = threading.Event()

    def poster(_url, body):
        gate.wait(5)
        posts.append(body)

    reporter = heartbeat_mod.HeartbeatReporter(
        "http://x", "j", poster=poster, clock=lambda: 0.0)
    host = autotune.AsyncHost()
    reporter.async_sink = host.submit
    # steady beat: accepted for async delivery, nothing posted yet
    assert reporter.report(5, {"loss": 1.0})
    assert posts == []
    # startup-carrying beat: synchronous (its ACK protocol needs the
    # real verdict) — the poster runs on THIS thread once ungated
    gate.set()
    assert reporter.report(6, {"loss": 0.9},
                           startup={"compileSeconds": 1.0})
    assert any("startup" in p for p in posts)
    host.close()
    assert len(posts) == 2                    # the deferred beat drained
    assert any(p.get("startup") == {"compileSeconds": 1.0} for p in posts)


def test_interval_of_is_the_single_cadence_source():
    class _NoInterval:
        pass

    class _Bad:
        interval = "soon"

    class _Neg:
        interval = -3

    assert heartbeat_mod.interval_of(None) == heartbeat_mod.DEFAULT_INTERVAL
    assert heartbeat_mod.interval_of(_NoInterval()) == \
        heartbeat_mod.DEFAULT_INTERVAL
    assert heartbeat_mod.interval_of(_Bad()) == heartbeat_mod.DEFAULT_INTERVAL
    assert heartbeat_mod.interval_of(_Neg()) == heartbeat_mod.DEFAULT_INTERVAL
    reporter = heartbeat_mod.HeartbeatReporter("http://x", "j",
                                               interval=3.5)
    assert heartbeat_mod.interval_of(reporter) == 3.5


def test_attach_withholds_checkpoint_knob_in_multiprocess():
    """A gang's save is a collective: the cadence knob must not be wired
    when the gang has more than one process (a unilaterally stretched
    maybe_save gate wedges the save barrier); the per-process-local
    knobs stay available."""
    class _Ck:
        cadence_multiplier = 1
        save_every = 10

    for procs, wired in ((1, True), (4, False)):
        rt = autotune.from_env(prefetch=0, env={
            autotune.ENV_PREFETCH_DEPTH: "0", autotune.ENV_AUTOTUNE: "1"})
        ck = _Ck()
        rt.attach(recorder=steptrace.StepRecorder(capacity=8),
                  checkpointer=ck, processes=procs)
        assert (rt.controller._checkpointer is ck) is wired, procs
        assert rt.controller._enable_host_async is not None
        rt.close()


def test_runtime_wire_and_host_toggle():
    rt = autotune.from_env(prefetch=0, env={
        autotune.ENV_PREFETCH_DEPTH: "0", autotune.ENV_AUTOTUNE: "1",
        autotune.ENV_WINDOW_STEPS: "8"})
    posts = []
    reporter = heartbeat_mod.HeartbeatReporter(
        "http://x", "j", poster=lambda _u, b: posts.append(b),
        clock=lambda: 0.0)
    rec = steptrace.StepRecorder(capacity=16)
    rt.attach(recorder=rec, heartbeat=reporter)
    assert rec.on_commit == rt.controller.on_step
    wire = rt.wire()
    assert wire["prefetchDepth"] == autotune.DEFAULT_PREFETCH_DEPTH
    assert wire["hostAsync"] is False
    assert wire["adjustments"]["prefetchUp"] == 0
    # the controller's host knob swaps the reporter's sink live
    rt._apply_host_async(True)
    assert reporter.async_sink is not None
    rt._apply_host_async(False)
    assert reporter.async_sink is None
    rt.close()


# --- spec wiring -------------------------------------------------------------

def test_dataplane_spec_roundtrip_defaults_validation():
    doc = worker_job("t", spec_extra={
        "dataPlane": {"prefetchDepth": 4,
                      "autotune": {"minDepth": 2, "maxDepth": 6,
                                   "windowSteps": 16}}})
    spec = types.TPUJobSpec.from_dict(doc["spec"])
    assert spec.data_plane.prefetch_depth == 4
    assert spec.data_plane.autotune.enabled is True
    assert spec.data_plane.autotune.min_depth == 2
    assert spec.to_dict()["dataPlane"] == {
        "prefetchDepth": 4,
        "autotune": {"enabled": True, "minDepth": 2, "maxDepth": 6,
                     "windowSteps": 16}}
    validate_tpujob_spec(set_defaults(spec))

    # absent block round-trips absent (None = static shipped config)
    bare = types.TPUJobSpec.from_dict(worker_job("t")["spec"])
    assert bare.data_plane is None and "dataPlane" not in bare.to_dict()

    # strict schema admits the block and rejects unknown keys inside it
    ok, _ = schema_mod.validate_tpujob_strict(doc)
    assert ok
    bad = worker_job("t", spec_extra={"dataPlane": {"prefetchDeep": 1}})
    ok, msg = schema_mod.validate_tpujob_strict(bad)
    assert not ok and "prefetchDeep" in msg

    # explicit junk reaches validation and fails loudly (never clamped)
    for block in ({"prefetchDepth": -1},
                  {"autotune": {"minDepth": 5, "maxDepth": 2}},
                  {"autotune": {"windowSteps": 4}},
                  {"prefetchDepth": 9, "autotune": {"maxDepth": 8}}):
        junk = types.TPUJobSpec.from_dict(
            worker_job("t", spec_extra={"dataPlane": block})["spec"])
        with pytest.raises(ValidationError):
            validate_tpujob_spec(set_defaults(junk))
    # …but a pinned depth outside the range is fine with autotune OFF
    pinned = types.TPUJobSpec.from_dict(worker_job("t", spec_extra={
        "dataPlane": {"prefetchDepth": 9,
                      "autotune": {"enabled": False}}})["spec"])
    validate_tpujob_spec(set_defaults(pinned))


def test_dataplane_env_injection():
    from tpu_operator.trainer.replicas import build_replica_env

    spec = types.TPUJobSpec.from_dict(worker_job("j", spec_extra={
        "dataPlane": {"prefetchDepth": 3,
                      "autotune": {"minDepth": 1, "maxDepth": 5,
                                   "windowSteps": 64}}})["spec"])
    set_defaults(spec)
    env = build_replica_env("j", "rt1", spec, types.TPUReplicaType.WORKER,
                            0, 0)
    assert env["TPUJOB_DATAPLANE_PREFETCH_DEPTH"] == "3"
    assert env["TPUJOB_DATAPLANE_AUTOTUNE"] == "1"
    assert env["TPUJOB_DATAPLANE_MIN_DEPTH"] == "1"
    assert env["TPUJOB_DATAPLANE_MAX_DEPTH"] == "5"
    assert env["TPUJOB_DATAPLANE_WINDOW_STEPS"] == "64"

    # depth-only block: no autotune vars (payload runtime stays static)
    spec2 = types.TPUJobSpec.from_dict(worker_job("j", spec_extra={
        "dataPlane": {"prefetchDepth": 2}})["spec"])
    env2 = build_replica_env("j", "rt1", spec2,
                             types.TPUReplicaType.WORKER, 0, 0)
    assert env2["TPUJOB_DATAPLANE_PREFETCH_DEPTH"] == "2"
    assert "TPUJOB_DATAPLANE_AUTOTUNE" not in env2

    # no block → no injection (inert runtime, pre-dataplane behavior)
    bare = types.TPUJobSpec.from_dict(worker_job("j")["spec"])
    env3 = build_replica_env("j", "rt1", bare,
                             types.TPUReplicaType.WORKER, 0, 0)
    assert not any(k.startswith("TPUJOB_DATAPLANE") for k in env3)


# --- statusserver door -------------------------------------------------------

class _ControllerStub:
    class _Store:
        def get(self, _ns, name):
            return {"metadata": {"namespace": "default", "name": name}} \
                if name == "jb" else None

        def list(self):
            return []

    class _Informer:
        def __init__(self):
            self.store = _ControllerStub._Store()

    def __init__(self):
        self.job_informer = self._Informer()
        self.heartbeats = []

    def record_heartbeat(self, _ns, _name, hb):
        self.heartbeats.append(hb)
        return True


@pytest.fixture()
def sanitizing_server():
    server = StatusServer(0)
    server.start()
    stub = _ControllerStub()
    server.set_controller(stub)
    try:
        yield server, stub
    finally:
        server.stop()


def test_dataplane_sanitization_rejects_bad_knob_reports(sanitizing_server):
    server, _stub = sanitizing_server
    base = {"namespace": "default", "name": "jb", "step": 1}
    for bad, frag in (
            ("deep", "must be an object"),
            ({"prefetchDepth": -1}, "prefetchDepth"),
            ({"prefetchDepth": float("nan")}, "prefetchDepth"),
            ({"prefetchDepth": float("inf")}, "prefetchDepth"),
            ({"prefetchDepth": True}, "prefetchDepth"),
            ({"checkpointIntervalSteps": 0}, "checkpointIntervalSteps"),
            ({"hostDropped": -1}, "hostDropped"),
            ({"hostAsync": "false"}, "hostAsync"),
            ({"adjustments": "three"}, "adjustments"),
            ({"adjustments": {"prefetchUp": -1}}, "prefetchUp"),
            ({"adjustments": {"hostUp": float("nan")}}, "hostUp")):
        ok, msg = server.record_heartbeat({**base, "dataPlane": bad})
        assert not ok and frag in msg, (bad, msg)


def test_dataplane_sanitization_keeps_known_drops_unknown(sanitizing_server):
    server, stub = sanitizing_server
    ok, _ = server.record_heartbeat({
        "namespace": "default", "name": "jb", "step": 1,
        "dataPlane": {"prefetchDepth": 3, "hostAsync": True,
                      "checkpointIntervalSteps": 200, "hostDropped": 4,
                      "adjustments": {"prefetchUp": 2,
                                      "quantumKnob": 9}}})
    assert ok
    (hb,) = stub.heartbeats
    assert hb["dataPlane"] == {
        "prefetchDepth": 3, "hostAsync": True,
        "checkpointIntervalSteps": 200, "hostDropped": 4,
        "adjustments": {"prefetchUp": 2}}


# --- controller fold ---------------------------------------------------------

def _controller_with_job(name="dj", attempt=0):
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0),
                            heartbeat_persist_interval=3600.0)
    job = types.TPUJob.from_dict(worker_job(name))
    job.metadata["uid"] = "u1"
    job.status.attempt = attempt
    controller.jobs[f"default/{name}"] = TrainingJob(
        cs, controller.recorder, job)
    return cs, controller, controller.jobs[f"default/{name}"]


def _dp_beat(step, dataplane, attempt=0,
             time_="2026-08-04T00:00:00.000000Z"):
    return {"time": time_, "step": step, "attempt": attempt,
            "processId": 0, "dataPlane": dataplane}


def test_dataplane_folds_into_status_gauge_and_counters():
    _cs, controller, tj = _controller_with_job()
    assert controller.record_heartbeat("default", "dj", _dp_beat(
        10, {"prefetchDepth": 3, "hostAsync": False,
             "adjustments": {"prefetchUp": 1}}))
    dp = tj.job.status.data_plane
    assert dp["prefetchDepth"] == 3 and dp["attempt"] == 0
    assert dp["adjustments"] == {"prefetchUp": 1}
    labels = {"namespace": "default", "name": "dj"}
    assert controller.metrics.counter_value("job_prefetch_depth",
                                            labels=labels) == 3
    assert controller.metrics.counter_value(
        "job_autotune_adjustments_total",
        labels={**labels, "knob": "prefetch", "direction": "up"}) == 1

    # delta accounting: lifetime totals accumulate against the baseline
    assert controller.record_heartbeat("default", "dj", _dp_beat(
        20, {"prefetchDepth": 4,
             "adjustments": {"prefetchUp": 3, "hostUp": 1}}))
    dp = tj.job.status.data_plane
    assert dp["adjustments"] == {"prefetchUp": 3, "hostUp": 1}
    assert controller.metrics.counter_value(
        "job_autotune_adjustments_total",
        labels={**labels, "knob": "prefetch", "direction": "up"}) == 3
    assert controller.metrics.counter_value(
        "job_autotune_adjustments_total",
        labels={**labels, "knob": "host", "direction": "up"}) == 1

    # attempt bump: the payload's counters reset; deltas count in full
    # and the lifetime totals keep growing (never double, never lost)
    tj.job.status.attempt = 1
    assert controller.record_heartbeat("default", "dj", _dp_beat(
        5, {"prefetchDepth": 2, "adjustments": {"prefetchUp": 2}},
        attempt=1))
    dp = tj.job.status.data_plane
    assert dp["adjustments"]["prefetchUp"] == 5
    assert dp["attemptAdjustments"]["prefetchUp"] == 2
    assert controller.metrics.counter_value(
        "job_autotune_adjustments_total",
        labels={**labels, "knob": "prefetch", "direction": "up"}) == 5


def test_dataplane_per_job_series_removed_on_job_deletion():
    cs = FakeClientset()
    controller = Controller(cs, SharedInformerFactory(cs, resync_period=0))
    labels = {"namespace": "default", "name": "gone"}
    controller.metrics.set_gauge("job_prefetch_depth", 4, labels=labels)
    controller.metrics.inc("job_autotune_adjustments_total", 2, labels={
        **labels, "knob": "prefetch", "direction": "up"})
    controller.metrics.inc("job_autotune_adjustments_total", 1, labels={
        **labels, "knob": "checkpoint", "direction": "down"})
    rendered = "\n".join(controller.metrics.render_lines())
    assert 'name="gone"' in rendered
    assert controller.sync_tpujob("default/gone") is True
    rendered = "\n".join(controller.metrics.render_lines())
    assert 'name="gone"' not in rendered


# --- e2e over the in-process apiserver --------------------------------------

@pytest.fixture()
def harness():
    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop), daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


def _get(port, path):
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_e2e_dataplane_status_metrics_describe(harness):
    api, cs, _controller, server = harness
    cs.tpujobs.create("default", worker_job("tuned", spec_extra={
        "dataPlane": {"prefetchDepth": 0,
                      "autotune": {"minDepth": 1, "maxDepth": 8,
                                   "windowSteps": 16}}}))
    assert wait_for(lambda: len(api.clientset.pods.list("default")) == 1)
    for pod in api.clientset.pods.list("default"):
        # the env contract reached the pod spec
        tpu = [c for c in pod["spec"]["containers"] if c["name"] == "tpu"][0]
        env = {e["name"]: e.get("value") for e in tpu.get("env", [])}
        assert env["TPUJOB_DATAPLANE_PREFETCH_DEPTH"] == "0"
        assert env["TPUJOB_DATAPLANE_AUTOTUNE"] == "1"
        pod["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", pod)
    assert wait_for(lambda: cs.tpujobs.get("default", "tuned")
                    .get("status", {}).get("phase") == "Running")

    # a payload reporter posts knob state through the REAL status server
    env = {"TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
           "TPUJOB_NAME": "tuned", "TPUJOB_NAMESPACE": "default",
           "TPUJOB_ATTEMPT": "0", "JAX_PROCESS_ID": "0"}
    reporter = heartbeat_mod.from_env(env)
    assert reporter.report(
        100, {"loss": 1.5},
        dataplane={"prefetchDepth": 5, "hostAsync": True,
                   "checkpointIntervalSteps": 200, "hostDropped": 2,
                   "adjustments": {"prefetchUp": 3, "hostUp": 1}})

    # → status.dataPlane through the strict status schema
    def dp():
        return (cs.tpujobs.get("default", "tuned").get("status", {})
                .get("dataPlane") or {})
    assert wait_for(lambda: dp().get("prefetchDepth") == 5,
                    describe=lambda: cs.tpujobs.get(
                        "default", "tuned").get("status"))
    assert dp()["adjustments"] == {"prefetchUp": 3, "hostUp": 1}
    assert dp()["hostAsync"] is True
    assert dp()["hostDropped"] == 2

    # → /metrics: the depth gauge and the adjustment counters
    body = _get(server.port, "/metrics")
    assert ('tpu_operator_job_prefetch_depth'
            '{name="tuned",namespace="default"} 5' in body)
    assert ('tpu_operator_job_autotune_adjustments_total'
            '{direction="up",knob="prefetch",name="tuned",'
            'namespace="default"} 3' in body)

    # → tpujobctl describe prints the DataPlane + Autotuned lines
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = ctl.main(["--master", api.url, "describe", "tuned"])
    assert rc == 0
    text = out.getvalue()
    assert "DataPlane:  prefetch depth 5 (auto" in text
    assert "host path async" in text and "ckpt every 200" in text
    assert "host drops 2" in text
    assert "Autotuned:  prefetch +3/-0, host +1/-0" in text


# --- train_loop integration --------------------------------------------------

@pytest.mark.slow
def test_train_loop_with_active_dataplane_posts_knobs_and_tunes():
    from tpu_operator.payload import train
    from tpu_operator.payload.cifar import build, parse_args

    args = parse_args(["--steps", "6", "--batch", "16",
                       "--blocks", "1", "--widths", "8", "8", "8",
                       "--log-every", "0"])
    mesh, _m, state, step, batches = build(args)
    rec = steptrace.StepRecorder(capacity=32)
    posts = []
    reporter = heartbeat_mod.HeartbeatReporter(
        "http://x", "lj", poster=lambda _u, b: posts.append(b),
        interval=0.0)  # every step is due
    runtime = autotune.from_env(prefetch=0, env={
        autotune.ENV_PREFETCH_DEPTH: "0", autotune.ENV_AUTOTUNE: "1",
        autotune.ENV_WINDOW_STEPS: "8"})
    train.train_loop(mesh, step, state, batches, steps=5,
                     heartbeat=reporter, steptrace=rec, overlap=False,
                     dataplane=runtime)
    carried = [p["dataPlane"] for p in posts if "dataPlane" in p]
    assert carried and carried[0]["prefetchDepth"] >= 1
    assert "adjustments" in carried[0]
    assert rec.steps_recorded == 5


# --- gang-agreed checkpoint cadence (the PR-12 knob-table future work) -------


def _tiny_state(step=0):
    import jax.numpy as jnp

    return {"step": jnp.int32(step), "w": jnp.arange(16, dtype=jnp.float32)}


def test_gang_agreed_cadence_disagreeing_gang(tmp_path):
    """The disagreeing-gang regression: one member's controller proposes
    a 4x stretch while a peer still proposes 1 — the allgather-min
    agreement lands on 1, every member saves at the base interval, and
    the save barrier never mismatches. The collective runs ONLY at
    base-interval boundaries (spec-uniform), so participation is
    identical on every process regardless of local proposals."""
    from tpu_operator.payload import checkpoint

    calls = []

    def peer_agrees_one(mult):  # a gang peer still proposes 1 → min 1
        calls.append(mult)
        return min(int(mult), 1)

    ck = checkpoint.Checkpointer(str(tmp_path / "a"), save_every=10,
                                 agree_fn=peer_agrees_one)
    try:
        ck.cadence_multiplier = 4
        # Without gang mode the local proposal applies directly and the
        # collective NEVER runs (single-process back-compat).
        assert ck._effective_cadence_multiplier(20) == 4
        assert calls == []
        ck.enable_gang_cadence()
        # Non-boundary steps skip the collective on every process alike.
        assert ck._effective_cadence_multiplier(25) == 4
        assert calls == []
        # Boundary: agreement → the gang saves at the base cadence.
        assert ck._effective_cadence_multiplier(20) == 1
        assert calls == [4]
        # The un-withheld knob is live end to end: maybe_save at a base
        # boundary SAVES despite the local 4x proposal.
        assert ck.maybe_save(10, _tiny_state(10)) is True
        ck.flush()
        assert ck.last_verified_step() == 10
    finally:
        ck.close()


def test_gang_agreed_cadence_uniform_gang_stretches(tmp_path):
    """The agreeing gang actually gets the stretch: every member proposes
    2, the min is 2, and only every 2nd base boundary saves."""
    from tpu_operator.payload import checkpoint

    ck = checkpoint.Checkpointer(str(tmp_path / "u"), save_every=10,
                                 agree_fn=lambda m: m)
    try:
        ck.enable_gang_cadence()
        ck.cadence_multiplier = 2
        assert ck.maybe_save(10, _tiny_state(10)) is False  # stretched away
        assert ck.maybe_save(20, _tiny_state(20)) is True
        ck.flush()
        assert ck.last_verified_step() == 20
    finally:
        ck.close()


def test_attach_unwithholds_cadence_knob_via_gang_agreement():
    """DataPlaneRuntime.attach: a multi-process job's checkpointer is no
    longer withheld — it is switched into gang-agreed cadence mode; an
    object WITHOUT the agreement surface stays withheld (the pre-PR
    behavior, never a wedged barrier)."""

    class AgreedCk:
        cadence_multiplier = 1
        save_every = 10
        enabled = False

        def enable_gang_cadence(self):
            self.enabled = True

    class LegacyCk:
        cadence_multiplier = 1
        save_every = 10

    control = autotune.PrefetchControl(2)
    ctl_ = autotune.DataPlaneController(control)
    runtime = autotune.DataPlaneRuntime(2, control=control,
                                        controller=ctl_, pipeline=True,
                                        active=True)
    ck = AgreedCk()
    runtime.attach(checkpointer=ck, processes=4)
    assert ck.enabled is True
    assert ctl_._checkpointer is ck
    # Single-process: wired directly, gang mode NOT flipped on.
    ck2 = AgreedCk()
    ctl2 = autotune.DataPlaneController(autotune.PrefetchControl(2))
    runtime2 = autotune.DataPlaneRuntime(2, control=runtime.control,
                                         controller=ctl2, pipeline=True,
                                         active=True)
    runtime2.attach(checkpointer=ck2, processes=1)
    assert ck2.enabled is False
    assert ctl2._checkpointer is ck2
    # No agreement surface: withheld in a gang (barrier safety first).
    ctl3 = autotune.DataPlaneController(autotune.PrefetchControl(2))
    runtime3 = autotune.DataPlaneRuntime(2, control=runtime.control,
                                         controller=ctl3, pipeline=True,
                                         active=True)
    runtime3.attach(checkpointer=LegacyCk(), processes=4)
    assert ctl3._checkpointer is None
