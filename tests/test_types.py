"""Round-trip and schema tests for the v1alpha1 API types
(ref test model: pkg/apis tests, SURVEY.md §4 tier-1 tables)."""

from tpu_operator.apis.tpujob.v1alpha1 import types as t


def make_template(container_name=t.DEFAULT_CONTAINER_NAME, tpu_chips=None):
    container = {"name": container_name, "image": "img:latest"}
    if tpu_chips is not None:
        container["resources"] = {"limits": {"cloud-tpus.google.com/v4": tpu_chips}}
    return {"spec": {"containers": [container], "restartPolicy": "OnFailure"}}


def make_spec(**kw):
    spec = t.TPUJobSpec(
        replica_specs=[
            t.TPUReplicaSpec(
                replicas=2,
                template=make_template(),
                tpu_port=t.DEFAULT_TPU_PORT,
                tpu_replica_type=t.TPUReplicaType.WORKER,
            )
        ]
    )
    for k, v in kw.items():
        setattr(spec, k, v)
    return spec


def test_job_roundtrip():
    job = t.TPUJob(
        metadata={"name": "mnist", "namespace": "team-a", "uid": "u-123"},
        spec=make_spec(runtime_id="a1b2"),
    )
    job.status.phase = t.TPUJobPhase.RUNNING
    job.status.attempt = 2
    wire = job.to_dict()
    assert wire["apiVersion"] == "tpuoperator.dev/v1alpha1"
    assert wire["kind"] == "TPUJob"

    back = t.TPUJob.from_dict(wire)
    assert back.name == "mnist"
    assert back.namespace == "team-a"
    assert back.uid == "u-123"
    assert back.spec.runtime_id == "a1b2"
    assert back.spec.replica_specs[0].replicas == 2
    assert back.status.phase == t.TPUJobPhase.RUNNING
    assert back.status.attempt == 2
    assert back.to_dict() == wire


def test_deepcopy_isolation():
    job = t.TPUJob(metadata={"name": "j"}, spec=make_spec())
    cp = job.deepcopy()
    cp.spec.replica_specs[0].template["spec"]["containers"][0]["image"] = "other"
    cp.metadata["name"] = "changed"
    assert job.spec.replica_specs[0].template["spec"]["containers"][0]["image"] == "img:latest"
    assert job.name == "j"


def test_replica_status_roundtrip():
    st = t.TPUJobStatus(
        phase=t.TPUJobPhase.CREATING,
        state=t.State.RUNNING,
        replica_statuses=[
            t.TPUReplicaStatus(
                tpu_replica_type=t.TPUReplicaType.WORKER,
                state=t.ReplicaState.RUNNING,
                replicas_states={t.ReplicaState.RUNNING: 3, t.ReplicaState.STARTING: 1},
            )
        ],
    )
    back = t.TPUJobStatus.from_dict(st.to_dict())
    assert back.replica_statuses[0].replicas_states[t.ReplicaState.RUNNING] == 3


def test_controller_config_from_dict_map_and_list_env():
    cfg = t.ControllerConfig.from_dict(
        {
            "accelerators": {
                "cloud-tpus.google.com/v4": {
                    "envVars": {"TPU_RUNTIME": "tpu-vm"},
                },
                "alpha.kubernetes.io/nvidia-gpu": {
                    "volumes": [
                        {"name": "lib", "hostPath": "/usr/lib/nvidia", "mountPath": "/usr/local/nvidia/lib64"}
                    ],
                    "envVars": [{"name": "LD_LIBRARY_PATH", "value": "/usr/local/nvidia/lib64"}],
                },
            }
        }
    )
    assert cfg.accelerators["cloud-tpus.google.com/v4"].env_vars == {"TPU_RUNTIME": "tpu-vm"}
    gpu = cfg.accelerators["alpha.kubernetes.io/nvidia-gpu"]
    assert gpu.volumes[0].mount_path == "/usr/local/nvidia/lib64"
    assert gpu.env_vars["LD_LIBRARY_PATH"] == "/usr/local/nvidia/lib64"


def test_termination_policy_default_none():
    assert t.TerminationPolicySpec.from_dict(None) is None
    assert t.TerminationPolicySpec.from_dict({}) is None
    tp = t.TerminationPolicySpec.from_dict({"chief": {"replicaName": "SCHEDULER", "replicaIndex": 0}})
    assert tp.chief_replica_name == "SCHEDULER"
