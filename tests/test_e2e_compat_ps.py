"""HTTP-tier e2e: the reference's canonical PS topology through the
operator binary (VERDICT round-3 missing item 2).

`examples/tpujob-compat-ps.yml` is the TPUJob expression of the
reference's PR1 config (`/root/reference/examples/mxjob-linear-dist.yml`:
1 SCHEDULER + 1 SERVER + 1 WORKER). This test drives that exact manifest
— not a hand-built spec — through the real operator binary against the
HTTP apiserver harness, to Running, and asserts the per-role contract:

- one pod and one per-index Service per role;
- env: the coordinator is SCHEDULER[0]'s service (the reference's
  hardcoded Replicas[0] bug fixed — replicas.go:240-243), process ids
  follow spec order, every role joins the same jax.distributed group;
- chief semantics: the job is Done when the SCHEDULER (default chief,
  reference training.go:252-257) exits 0.
"""

from __future__ import annotations

import pathlib
import signal
import subprocess
import sys
import time

import pytest
import yaml

from tpu_operator.client.rest import Clientset, RestConfig
from tpu_operator.testing.apiserver import ApiServerHarness
from tpu_operator.testing.waiting import make_wait_for

REPO = pathlib.Path(__file__).resolve().parent.parent
EXAMPLE = REPO / "examples" / "tpujob-compat-ps.yml"


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=60.0, interval=0.25)


@pytest.fixture
def operator_env():
    harness = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=harness.url, timeout=5.0))
    op = subprocess.Popen(
        [sys.executable, "-m", "tpu_operator.cmd.main", "--master",
         harness.url, "--namespace", "default"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    yield cs
    op.send_signal(signal.SIGINT)
    try:
        op.wait(timeout=10)
    except subprocess.TimeoutExpired:
        op.kill()
    harness.stop()


def _env_of(pod):
    env_list = pod["spec"]["containers"][0].get("env", [])
    return {e["name"]: e["value"] for e in env_list}


def _set_pod_state(cs, pod, phase, container_state):
    pod["status"] = {
        "phase": phase,
        "containerStatuses": [{"name": "tpu", "state": container_state}],
    }
    cs.pods.update("default", pod)


def test_compat_ps_example_runs_end_to_end(operator_env):
    cs = operator_env
    with open(EXAMPLE, encoding="utf-8") as f:
        (doc,) = [d for d in yaml.safe_load_all(f) if d]
    doc["metadata"]["namespace"] = "default"
    cs.tpujobs.create("default", doc)

    def pods_by_role():
        out = {}
        for p in cs.pods.list("default"):
            role = p["metadata"]["labels"].get("job_type")
            out.setdefault(role, []).append(p)
        return out

    assert wait_for(lambda: sum(len(v) for v in pods_by_role().values()) == 3)
    roles = pods_by_role()
    assert set(roles) == {"scheduler", "server", "worker"}
    assert all(len(v) == 1 for v in roles.values())

    # per-index Services: one per process, plus the job-scoped headless one
    services = cs.services.list("default")
    svc_names = {s["metadata"]["name"] for s in services}
    job = cs.tpujobs.get("default", "linear-dist")
    rid = job["spec"]["runtimeId"]
    for role in ("scheduler", "server", "worker"):
        assert f"linear-dist-{role}-{rid}-0" in svc_names, svc_names

    # env contract per role: coordinator = SCHEDULER[0]'s service; global
    # process ids in spec order (scheduler, server, worker); one group.
    sched_env = _env_of(roles["scheduler"][0])
    server_env = _env_of(roles["server"][0])
    worker_env = _env_of(roles["worker"][0])
    coord = f"linear-dist-scheduler-{rid}-0:8476"
    for env in (sched_env, server_env, worker_env):
        assert env["JAX_COORDINATOR_ADDRESS"] == coord, env
        assert env["JAX_NUM_PROCESSES"] == "3"
        assert env["TPUJOB_ATTEMPT"] == "0"
    assert sched_env["JAX_PROCESS_ID"] == "0"
    assert server_env["JAX_PROCESS_ID"] == "1"
    assert worker_env["JAX_PROCESS_ID"] == "2"
    assert sched_env["TPUJOB_REPLICA_TYPE"] == "scheduler"
    # the lone worker is slice-local worker 0 and the only hostname
    assert worker_env["TPU_WORKER_ID"] == "0"
    assert worker_env["TPU_WORKER_HOSTNAMES"] == f"linear-dist-worker-{rid}-0"
    # PS roles are not TPU workers: no TPU_WORKER_* leaks into them
    assert "TPU_WORKER_ID" not in sched_env
    assert "TPU_WORKER_ID" not in server_env

    # all three Running -> job Running
    for pods in roles.values():
        _set_pod_state(cs, pods[0], "Running", {"running": {}})
    assert wait_for(lambda: cs.tpujobs.get("default", "linear-dist")
                    .get("status", {}).get("phase") == "Running")

    # chief (SCHEDULER, the reference default) exits 0 -> job Done/Succeeded,
    # even with SERVER/WORKER still running (chief-based GetStatus,
    # reference training.go:132-168)
    _set_pod_state(cs, pods_by_role()["scheduler"][0], "Succeeded",
                   {"terminated": {"exitCode": 0}})
    assert wait_for(lambda: cs.tpujobs.get("default", "linear-dist")
                    .get("status", {}).get("phase") == "Done", timeout=90.0)
    assert (cs.tpujobs.get("default", "linear-dist")["status"].get("state")
            == "Succeeded")
