"""Warm-restart fast path: ``spec.compilationCache`` wiring end to end,
the overlapped restore+compile prologue (PR 4 restore semantics preserved
exactly), best-effort cache enablement, the DNS backoff, and the
startup-phase breakdown flowing heartbeat → statusserver → controller →
``status.startup`` + ``/metrics``.
"""

import socket
import threading
import time
import urllib.request

import pytest

from tpu_operator.apis.tpujob import validation
from tpu_operator.apis.tpujob.v1alpha1 import schema as schema_mod
from tpu_operator.apis.tpujob.v1alpha1 import types as t
from tpu_operator.apis.tpujob.v1alpha1.defaults import set_defaults
from tpu_operator.controller.statusserver import Metrics, StatusServer
from tpu_operator.payload import bootstrap
from tpu_operator.payload import heartbeat as heartbeat_mod
from tpu_operator.payload import startup as startup_mod
from tpu_operator.trainer import replicas as replicas_mod
from tpu_operator.trainer.training import TrainingJob
from tpu_operator.testing.waiting import make_wait_for
from tests.test_types import make_template


# --- spec field: types/schema/defaults/validation ----------------------------

def cache_spec(**kw):
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=2, template=make_template())],
        compilation_cache=t.CompilationCacheSpec(**kw),
    )
    return set_defaults(spec)


def test_compilation_cache_roundtrip():
    spec = cache_spec(path="/mnt/xla", medium="emptyDir")
    wire = spec.to_dict()
    assert wire["compilationCache"] == {
        "enabled": True, "path": "/mnt/xla", "medium": "emptyDir"}
    back = t.TPUJobSpec.from_dict(wire)
    assert back.compilation_cache == spec.compilation_cache
    # absent block stays absent (opt-in)
    bare = t.TPUJobSpec.from_dict({"replicaSpecs": []})
    assert bare.compilation_cache is None
    assert "compilationCache" not in bare.to_dict()


def test_compilation_cache_defaults_fill_empty_block():
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=1, template=make_template())],
        compilation_cache=t.CompilationCacheSpec(path="", medium=""),
    )
    set_defaults(spec)
    assert spec.compilation_cache.path == t.DEFAULT_CACHE_PATH
    assert spec.compilation_cache.medium == t.CacheMedium.HOSTPATH
    assert spec.compilation_cache.enabled
    validation.validate_tpujob_spec(spec)


@pytest.mark.parametrize("kw, needle", [
    ({"medium": "persistentVolume"}, "medium"),
    ({"path": "relative/path"}, "path"),
    ({"path": ""}, "path"),
])
def test_compilation_cache_validation_rejects(kw, needle):
    spec = cache_spec(**kw)
    # set_defaults fills empty path; force the invalid value back
    for key, value in kw.items():
        setattr(spec.compilation_cache, key, value)
    with pytest.raises(validation.ValidationError, match=needle):
        validation.validate_tpujob_spec(spec)


def test_compilation_cache_disabled_block_is_inert():
    spec = cache_spec(enabled=False)
    spec.compilation_cache.medium = "bogus"  # disabled → not validated
    validation.validate_tpujob_spec(spec)


def job_body(cache=None):
    body = {
        "apiVersion": t.CRD_API_VERSION, "kind": t.CRD_KIND,
        "metadata": {"name": "warm"},
        "spec": {"replicaSpecs": [{
            "replicas": 1, "tpuReplicaType": "WORKER", "tpuPort": 8476,
            "template": {"spec": {"containers": [{"name": "tpu",
                                                  "image": "x"}]}}}]},
    }
    if cache is not None:
        body["spec"]["compilationCache"] = cache
    return body


def test_schema_strict_compilation_cache():
    ok, msg = schema_mod.validate_tpujob_strict(
        job_body({"enabled": True, "path": "/var/cache/tpujob/xla",
                  "medium": "hostPath"}))
    assert ok, msg
    ok, msg = schema_mod.validate_tpujob_strict(
        job_body({"medium": "nfs"}))
    assert not ok and "medium" in msg
    ok, msg = schema_mod.validate_tpujob_strict(
        job_body({"hostPath": "/x"}))
    assert not ok and "unknown field" in msg


def test_schema_status_startup_and_heartbeat_stage():
    body = job_body()
    body["status"] = {
        "phase": "Running", "state": "Running",
        "startup": {"rendezvousSeconds": 0.1, "restoreSeconds": 1.5,
                    "compileSeconds": 30.2, "firstStepSeconds": 0.4,
                    "cacheHit": True, "attempt": 2, "time": "2026-01-01T00:00:00Z"},
        "lastHeartbeat": {"startupStage": "COMPILE",
                          "startup": {"compileSeconds": 30.2}},
    }
    ok, msg = schema_mod.validate_tpujob_strict(body)
    assert ok, msg
    body["status"]["lastHeartbeat"]["startupStage"] = "WAITING"
    ok, msg = schema_mod.validate_tpujob_strict(body)
    assert not ok and "startupStage" in msg


# --- operator injection: env + volume ----------------------------------------

def build_pod(cache=None):
    spec = t.TPUJobSpec(
        replica_specs=[t.TPUReplicaSpec(replicas=2, template=make_template())],
        runtime_id="wr01", compilation_cache=cache,
    )
    set_defaults(spec)
    job = t.TPUJob(metadata={"name": "warm", "namespace": "default",
                             "uid": "u1"}, spec=spec)
    tj = TrainingJob(None, None, job)
    rs = replicas_mod.TPUReplicaSet(None, None, tj, spec.replica_specs[0])
    return rs.pod_spec_with_index(0)


def tpu_container(pod):
    return next(c for c in pod["spec"]["containers"] if c["name"] == "tpu")


def test_cache_env_and_hostpath_volume_injected():
    pod = build_pod(t.CompilationCacheSpec())
    env = {e["name"]: e["value"] for e in tpu_container(pod)["env"]}
    assert env["JAX_COMPILATION_CACHE_DIR"] == t.DEFAULT_CACHE_PATH
    assert env["TPUJOB_CACHE_ENABLED"] == "1"
    assert env["TPUJOB_CACHE_PATH"] == t.DEFAULT_CACHE_PATH
    assert env["TPUJOB_CACHE_MEDIUM"] == "hostPath"
    vols = {v["name"]: v for v in pod["spec"]["volumes"]}
    vol = vols[replicas_mod.CACHE_VOLUME_NAME]
    assert vol["hostPath"] == {"path": t.DEFAULT_CACHE_PATH,
                               "type": "DirectoryOrCreate"}
    mounts = {m["name"]: m for m in tpu_container(pod)["volumeMounts"]}
    assert mounts[replicas_mod.CACHE_VOLUME_NAME]["mountPath"] == \
        t.DEFAULT_CACHE_PATH


def test_cache_emptydir_fallback():
    pod = build_pod(t.CompilationCacheSpec(path="/xla-cache",
                                           medium="emptyDir"))
    vol = next(v for v in pod["spec"]["volumes"]
               if v["name"] == replicas_mod.CACHE_VOLUME_NAME)
    assert vol == {"name": replicas_mod.CACHE_VOLUME_NAME, "emptyDir": {}}
    mounts = tpu_container(pod)["volumeMounts"]
    assert mounts[0]["mountPath"] == "/xla-cache"


def test_no_cache_spec_injects_nothing():
    pod = build_pod(None)
    env_names = {e["name"] for e in tpu_container(pod)["env"]}
    assert "JAX_COMPILATION_CACHE_DIR" not in env_names
    assert not any(v.get("name") == replicas_mod.CACHE_VOLUME_NAME
                   for v in pod["spec"].get("volumes", []))


def test_disabled_cache_spec_injects_nothing():
    pod = build_pod(t.CompilationCacheSpec(enabled=False))
    env_names = {e["name"] for e in tpu_container(pod)["env"]}
    assert "JAX_COMPILATION_CACHE_DIR" not in env_names


# --- bootstrap: best-effort enablement + DNS backoff --------------------------

def test_enable_compilation_cache_sets_config(tmp_path):
    import jax

    cache = tmp_path / "xla"
    env = {"JAX_COMPILATION_CACHE_DIR": str(cache)}
    prev = jax.config.jax_compilation_cache_dir
    try:
        assert bootstrap.enable_compilation_cache(env) == str(cache)
        assert jax.config.jax_compilation_cache_dir == str(cache)
        assert jax.config.jax_persistent_cache_min_entry_size_bytes == 0
        assert cache.is_dir()
        assert startup_mod.cache_dir() == str(cache)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_enable_compilation_cache_unusable_dir_proceeds_cold(tmp_path):
    # The "corrupt cache dir" case: the path exists but is a FILE — mkdir
    # and the write probe both fail. Must log-and-return, never raise.
    clobber = tmp_path / "not-a-dir"
    clobber.write_text("junk")
    env = {"JAX_COMPILATION_CACHE_DIR": str(clobber)}
    assert bootstrap.enable_compilation_cache(env) == ""


def test_enable_compilation_cache_respects_disable(tmp_path):
    env = {"JAX_COMPILATION_CACHE_DIR": str(tmp_path),
           "TPUJOB_CACHE_ENABLED": "0"}
    assert bootstrap.enable_compilation_cache(env) == ""
    assert bootstrap.enable_compilation_cache({}) == ""


def test_wait_for_coordinator_tight_then_backed_off(monkeypatch):
    failures = [8]
    def fake_getaddrinfo(_host, _port):
        if failures[0] > 0:
            failures[0] -= 1
            raise socket.gaierror("not yet")
        return []
    monkeypatch.setattr(socket, "getaddrinfo", fake_getaddrinfo)
    sleeps = []
    now = [0.0]
    def fake_sleep(dt):
        sleeps.append(dt)
        now[0] += dt
    bootstrap.wait_for_coordinator("coord:8476", timeout=300.0, interval=2.0,
                                   sleep=fake_sleep, clock=lambda: now[0])
    # 8 failed polls → 8 sleeps: 0.05, 0.1, ..., capped at the interval.
    assert sleeps == [0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 2.0, 2.0]


def test_wait_for_coordinator_warm_service_is_instant(monkeypatch):
    monkeypatch.setattr(socket, "getaddrinfo", lambda _h, _p: [])
    sleeps = []
    bootstrap.wait_for_coordinator("coord:8476", sleep=sleeps.append)
    assert sleeps == []


def test_wait_for_coordinator_times_out(monkeypatch):
    def nope(_h, _p):
        raise socket.gaierror("never")
    monkeypatch.setattr(socket, "getaddrinfo", nope)
    now = [0.0]
    def fake_sleep(dt):
        now[0] += dt
    with pytest.raises(TimeoutError):
        bootstrap.wait_for_coordinator("coord:8476", timeout=10.0,
                                       sleep=fake_sleep,
                                       clock=lambda: now[0])


# --- the overlapped prologue ---------------------------------------------------

def tiny_build(lr=0.1):
    import jax
    import optax

    from tpu_operator.payload import models, train

    mesh = train.make_mesh(num_devices=2)
    model = models.LinearRegressor()
    tx = optax.sgd(lr)
    sample = jax.numpy.zeros((8, 4), jax.numpy.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    shardings = train.state_shardings(mesh, state)
    state = train.place_state(mesh, state, shardings)
    step = train.make_regression_train_step(model, tx, mesh, state, shardings)
    return mesh, state, step


def counting_linear_stream(counter):
    from tpu_operator.payload import data as data_mod

    def stream():
        for batch in data_mod.synthetic_linear(0, 8, 4):
            counter.append(1)
            yield batch
    return stream()


def test_overlap_prologue_trains_and_uses_aot(tmp_path):
    import jax

    from tpu_operator.payload import train

    mesh, state, step = tiny_build()
    counter = []
    tracker = startup_mod.StartupTracker()
    out, _metrics = train.train_loop(
        mesh, step, state, counting_linear_stream(counter), steps=3,
        heartbeat=None, startup=tracker, prefetch=0)
    assert int(jax.device_get(out.step)) == 3
    assert len(counter) == 3  # batch 0 peeked for AOT shapes, then consumed
    b = tracker.breakdown()
    assert b["compileSeconds"] > 0  # the AOT path actually ran
    assert b["firstStepSeconds"] > 0


def test_overlap_resume_restores_and_fast_forwards(tmp_path):
    import jax

    from tpu_operator.payload import checkpoint as ckpt_mod
    from tpu_operator.payload import train

    mesh, state, step = tiny_build()
    # Attempt 0: run 3 steps, leave a verified checkpoint at 3.
    ck = ckpt_mod.Checkpointer(str(tmp_path / "ck"), save_every=1000)
    counter = []
    state0, _ = train.train_loop(mesh, step, state,
                                 counting_linear_stream(counter), steps=3,
                                 checkpointer=ck, heartbeat=None, prefetch=0)
    ck.close()
    assert len(counter) == 3

    # Attempt 1: fresh init state; the overlapped prologue must restore
    # step 3 (restore result WINS over the AOT-compiled init state) and
    # fast-forward the stream so batches 0-2 are drawn-but-discarded.
    mesh2, fresh, step2 = tiny_build()
    ck2 = ckpt_mod.Checkpointer(str(tmp_path / "ck"), save_every=1000)
    counter2 = []
    tracker = startup_mod.StartupTracker()
    out, _ = train.train_loop(mesh2, step2, fresh,
                              counting_linear_stream(counter2), steps=5,
                              checkpointer=ck2, heartbeat=None,
                              startup=tracker, prefetch=0)
    ck2.close()
    assert int(jax.device_get(out.step)) == 5
    assert len(counter2) == 5  # 3 fast-forwarded + 2 trained
    assert tracker.breakdown()["restoreSeconds"] > 0
    # The restored trajectory must equal the uninterrupted one: params of
    # a 5-step run from scratch vs 3+2 across the restore.
    mesh3, fresh3, step3 = tiny_build()
    ref, _ = train.train_loop(mesh3, step3, fresh3,
                              counting_linear_stream([]), steps=5,
                              heartbeat=None)
    for a, b in zip(jax.tree_util.tree_leaves(out.params),
                    jax.tree_util.tree_leaves(ref.params)):
        assert jax.numpy.allclose(a, b, atol=1e-6)


def test_overlap_failed_restore_falls_back_per_pr4(tmp_path):
    import os

    import jax

    from tpu_operator.payload import checkpoint as ckpt_mod
    from tpu_operator.payload import train

    mesh, state, step = tiny_build()
    ck = ckpt_mod.Checkpointer(str(tmp_path / "ck"), save_every=2,
                               max_to_keep=5)
    state0, _ = train.train_loop(mesh, step, state,
                                 counting_linear_stream([]), steps=4,
                                 checkpointer=ck, heartbeat=None)
    ck.close()
    # Corrupt the newest checkpoint (step 4): flip bytes in its largest
    # file so the manifest checksum fails and the walk quarantines it.
    step_dir = str(tmp_path / "ck" / "4")
    victim = max(
        (os.path.join(root, fn) for root, _d, files in os.walk(step_dir)
         for fn in files if fn != ckpt_mod.MANIFEST_NAME),
        key=os.path.getsize)
    with open(victim, "r+b") as f:
        f.write(b"\xde\xad\xbe\xef")

    mesh2, fresh, step2 = tiny_build()
    ck2 = ckpt_mod.Checkpointer(str(tmp_path / "ck"), save_every=1000)
    counter = []
    out, _ = train.train_loop(mesh2, step2, fresh,
                              counting_linear_stream(counter), steps=5,
                              checkpointer=ck2, heartbeat=None, prefetch=0)
    # PR 4 semantics through the overlapped path: corrupt 4 quarantined,
    # resume from verified 2, train 3 more.
    assert ck2.restore_fallbacks == 1
    assert int(jax.device_get(out.step)) == 5
    assert len(counter) == 5
    ck2.close()


def test_cache_dir_corruption_still_cold_starts(tmp_path):
    """Best-effort end to end: a payload whose cache dir is a corrupt
    non-directory still trains (cold) — enablement returns "" and the
    loop runs exactly as without a cache."""
    import jax

    from tpu_operator.payload import train

    clobber = tmp_path / "cache"
    clobber.write_text("junk")
    assert bootstrap.enable_compilation_cache(
        {"JAX_COMPILATION_CACHE_DIR": str(clobber)}) == ""
    mesh, state, step = tiny_build()
    out, _ = train.train_loop(mesh, step, state, counting_linear_stream([]),
                              steps=2, heartbeat=None)
    assert int(jax.device_get(out.step)) == 2


def test_aot_mismatch_falls_back_to_jit_dispatch():
    """A step jitted WITHOUT explicit in_shardings lowers from the host
    batch's (absent) sharding; the AOT executable then rejects the
    device-placed sharded batch at call time. The first step must fall
    back to ordinary jit dispatch instead of failing the attempt."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_operator.payload import models, train

    mesh = train.make_mesh(num_devices=2)
    model = models.LinearRegressor()
    tx = optax.sgd(0.1)
    sample = jnp.zeros((8, 4), jnp.float32)
    state = train.create_train_state(model, jax.random.key(0), sample, tx)
    state = train.place_state(mesh, state)

    def step(state, x, y):
        def loss_fn(params):
            pred = model.apply({"params": params}, x, train=True)
            return jnp.mean((pred - y) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        return train.TrainState(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            batch_stats=state.batch_stats, opt_state=new_opt,
        ), {"loss": loss}

    bare_jit = jax.jit(step)  # no in_shardings: the mismatch case
    out, metrics = train.train_loop(
        mesh, bare_jit, state, counting_linear_stream([]), steps=2,
        heartbeat=None, spec=P("data"))
    assert int(jax.device_get(out.step)) == 2
    assert "loss" in metrics


def test_serial_prologue_unchanged(tmp_path):
    """overlap=False is the PR-4 serial path, byte for byte."""
    import jax

    from tpu_operator.payload import train

    mesh, state, step = tiny_build()
    counter = []
    out, _ = train.train_loop(mesh, step, state,
                              counting_linear_stream(counter), steps=2,
                              heartbeat=None, overlap=False, prefetch=0)
    assert int(jax.device_get(out.step)) == 2
    assert len(counter) == 2


# --- heartbeats: startupStage liveness + the breakdown post -------------------

def make_reporter(posts, interval=0.05):
    return heartbeat_mod.HeartbeatReporter(
        "http://x", "warm", interval=interval,
        poster=lambda _url, body: posts.append(body))


def test_report_startup_posts_stage_only():
    posts = []
    rep = make_reporter(posts)
    assert rep.report_startup("COMPILE")
    assert posts[-1]["startupStage"] == "COMPILE"
    assert "step" not in posts[-1]
    # startup posts must not starve the first real step report
    assert rep.due(1)


def test_report_carries_startup_breakdown():
    posts = []
    rep = make_reporter(posts)
    rep.report(1, {"loss": 0.5},
               startup={"compileSeconds": 2.0, "cacheHit": True})
    assert posts[-1]["startup"] == {"compileSeconds": 2.0, "cacheHit": True}


def test_train_loop_posts_startup_stage_and_breakdown():
    from tpu_operator.payload import train

    mesh, state, step = tiny_build()
    posts = []
    rep = make_reporter(posts, interval=0.02)
    tracker = startup_mod.StartupTracker()
    # Slow the compile artificially so the ticker provably fires during it.
    real_compile = train.aot_compile_step

    def slow_compile(*a, **kw):
        time.sleep(0.15)
        return real_compile(*a, **kw)

    try:
        train.aot_compile_step = slow_compile
        train.train_loop(mesh, step, state, counting_linear_stream([]),
                         steps=2, heartbeat=rep, startup=tracker)
    finally:
        train.aot_compile_step = real_compile
    stages = [p["startupStage"] for p in posts if "startupStage" in p]
    assert "COMPILE" in stages
    breakdowns = [p["startup"] for p in posts if "startup" in p]
    assert breakdowns and breakdowns[0]["compileSeconds"] > 0
    assert breakdowns[0]["firstStepSeconds"] > 0


# --- statusserver validation ---------------------------------------------------

def test_statusserver_sanitizes_startup_fields():
    server = StatusServer(0, metrics=Metrics())
    server.start()
    try:
        ok, msg = server.record_heartbeat(
            {"name": "x", "startupStage": "WAITING"})
        assert not ok and "startupStage" in msg
        ok, msg = server.record_heartbeat(
            {"name": "x", "startup": "zzz"})
        assert not ok and "startup" in msg
        ok, msg = server.record_heartbeat(
            {"name": "x", "startup": {"compileSeconds": -1}})
        assert not ok
        ok, msg = server.record_heartbeat(
            {"name": "x", "startup": {"compileSeconds": float("nan")}})
        assert not ok
        # valid fields on a standby: rejected as standby, not as bad body
        ok, msg = server.record_heartbeat(
            {"name": "x", "startupStage": "COMPILE",
             "startup": {"compileSeconds": 1.5, "cacheHit": True,
                         "ignored": "dropped"}})
        assert not ok and msg.startswith("standby")
    finally:
        server.stop()


def test_statusserver_rejects_unrecordable_breakdown_retryably():
    """The breakdown is a one-shot per attempt: if the controller cannot
    record it yet (fresh leader, TrainingJob not built), a 200 would make
    the payload drop it forever — the server must fail retryably instead,
    while ordinary beats keep the old ACK-and-stash behavior."""
    class Store:
        @staticmethod
        def get(_ns, _name):
            return {"metadata": {"name": "x", "namespace": "default"}}

    class Informer:
        store = Store()

    class NotReadyController:
        job_informer = Informer()

        @staticmethod
        def record_heartbeat(_ns, _name, _hb):
            return False  # job known to the cache, TrainingJob not built

    server = StatusServer(0, metrics=Metrics())
    server.start()
    server.set_controller(NotReadyController())
    try:
        ok, msg = server.record_heartbeat(
            {"name": "x", "startup": {"compileSeconds": 3.0}})
        assert not ok and msg.endswith("retry")
        ok, _ = server.record_heartbeat({"name": "x", "step": 1})
        assert ok  # plain beats: gauges stash, status catches up later
    finally:
        server.stop()


# --- stall watchdog: startup beats are liveness --------------------------------

def test_startup_heartbeat_defers_stall():
    from tests.test_time_recovery import (
        FakeNow, all_running, make_job, new_tj)
    from tpu_operator.trainer import training as training_mod

    clock = FakeNow()
    orig = training_mod._now
    training_mod._now = clock
    try:
        job = make_job(stall_timeout_seconds=60,
                       restart_backoff=t.RestartBackoffSpec(base_seconds=0))
        cs, tj = new_tj(job, metrics=Metrics())
        tj.reconcile()
        all_running(cs)
        tj.reconcile()
        assert tj.job.status.phase == t.TPUJobPhase.RUNNING
        # 50 s in: a COMPILE-stage liveness beat lands (operator-stamped
        # time, exactly what the statusserver stores for startup posts).
        clock.advance(50.0)
        tj.job.status.last_heartbeat = {"time": training_mod._now(),
                                        "startupStage": "COMPILE",
                                        "attempt": 0}
        # 59 s after the beat (109 s after Running): still alive.
        clock.advance(59.0)
        tj.reconcile()
        assert tj.job.status.attempt == 0
        assert tj.job.status.phase == t.TPUJobPhase.RUNNING
        # 2 more: the startup stage stopped progressing → stall fires.
        clock.advance(2.0)
        tj.reconcile()
        assert tj.job.status.attempt == 1
        assert tj.job.status.failures[-1].kind == "stall"
    finally:
        training_mod._now = orig


# --- e2e: breakdown → status.startup + /metrics (strict schema) ----------------

@pytest.fixture()
def e2e():
    from tpu_operator.client.informer import SharedInformerFactory
    from tpu_operator.client.rest import Clientset, RestConfig
    from tpu_operator.controller.controller import Controller
    from tpu_operator.testing.apiserver import ApiServerHarness

    api = ApiServerHarness().start()
    cs = Clientset(RestConfig(host=api.url, timeout=5.0))
    # resync_period: NOT 0 here — an object created inside the informer's
    # LIST→WATCH establishment gap is otherwise invisible forever (the
    # pre-existing flake class documented for test_telemetry_e2e); a 1 s
    # re-list heals the miss, and since PR 3 the resync loop no longer
    # re-dispatches unchanged resourceVersions, so it costs nothing here.
    controller = Controller(cs, SharedInformerFactory(cs, "default",
                                                      resync_period=1.0),
                            heartbeat_persist_interval=0.0)
    server = StatusServer(0, metrics=controller.metrics)
    server.start()
    server.set_controller(controller)
    stop = threading.Event()
    th = threading.Thread(target=controller.run, args=(1, stop), daemon=True)
    th.start()
    try:
        yield api, cs, controller, server
    finally:
        stop.set()
        th.join(timeout=5)
        server.stop()
        api.stop()


# Shared polling helper (tpu_operator/testing/waiting.py): a timeout
# raises with the last-observed state instead of a bare assert False.
wait_for = make_wait_for(timeout=45.0, interval=0.05)


def test_startup_breakdown_e2e(e2e):
    api, cs, controller, server = e2e
    cs.tpujobs.create("default", {
        "apiVersion": t.CRD_API_VERSION, "kind": t.CRD_KIND,
        "metadata": {"name": "warm", "namespace": "default"},
        "spec": {
            "compilationCache": {"enabled": True, "path": "/xla",
                                 "medium": "hostPath"},
            "replicaSpecs": [{
                "replicas": 1, "tpuReplicaType": "WORKER", "tpuPort": 8476,
                "template": {"spec": {"containers": [
                    {"name": "tpu", "image": "x"}]}}}]},
    })
    # Poll with a reconcile nudge: this harness class has a pre-existing
    # LIST/WATCH establishment race (documented for test_telemetry_e2e on
    # the baseline tree) where the create event can be missed with
    # resync_period=0; re-adding the key is dedup'd by the workqueue and
    # keeps this test about the startup plumbing, not the watch race.
    def pods_exist():
        if api.clientset.pods.list("default"):
            return True
        controller.queue.add("default/warm")
        return False

    assert wait_for(pods_exist)
    # The injected pod carries the cache contract + volume.
    pod = api.clientset.pods.list("default")[0]
    env = {e["name"]: e.get("value") for c in pod["spec"]["containers"]
           for e in c.get("env", [])}
    assert env["JAX_COMPILATION_CACHE_DIR"] == "/xla"
    assert any(v.get("name") == replicas_mod.CACHE_VOLUME_NAME
               for v in pod["spec"]["volumes"])
    for p in api.clientset.pods.list("default"):
        p["status"] = {"phase": "Running", "containerStatuses": [
            {"name": "tpu", "state": {"running": {}}}]}
        api.clientset.pods.update("default", p)
    def job_running():
        if (cs.tpujobs.get("default", "warm").get("status", {})
                .get("phase") == "Running"):
            return True
        controller.queue.add("default/warm")  # same nudge as above
        return False

    assert wait_for(job_running)

    # The payload's reporter, exactly as train_loop drives it: liveness
    # beats during compile, then the post-first-step breakdown.
    reporter = heartbeat_mod.from_env({
        "TPUJOB_STATUS_URL": f"http://127.0.0.1:{server.port}",
        "TPUJOB_NAME": "warm", "TPUJOB_NAMESPACE": "default",
        "JAX_PROCESS_ID": "0", "TPUJOB_ATTEMPT": "0"})
    assert reporter.report_startup("COMPILE")
    breakdown = {"rendezvousSeconds": 0.2, "restoreSeconds": 1.1,
                 "compileSeconds": 33.0, "firstStepSeconds": 0.7,
                 "cacheHit": True}
    assert reporter.report(1, {"loss": 2.5}, startup=breakdown)

    def persisted_startup():
        return (cs.tpujobs.get("default", "warm").get("status", {})
                .get("startup") or {})
    assert wait_for(lambda: persisted_startup().get("compileSeconds") == 33.0)
    su = persisted_startup()
    assert su["cacheHit"] is True and su["attempt"] == 0
    # Strict schema proof: the write above passed the apiserver's strict
    # structural admission with status.startup + lastHeartbeat.startup.
    hb = cs.tpujobs.get("default", "warm")["status"]["lastHeartbeat"]
    assert hb["startup"]["restoreSeconds"] == 1.1

    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5) as r:
        body = r.read().decode()
    assert 'tpu_operator_job_startup_seconds_bucket{le="60",stage="compile"} 1' in body
    assert ('tpu_operator_compilation_cache_hits_total'
            '{name="warm",namespace="default"} 1') in body
    # One breakdown per attempt: a re-post must not double-observe.
    assert reporter.report(2, {"loss": 2.4}, startup=breakdown)
    time.sleep(0.2)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=5) as r:
        body2 = r.read().decode()
    assert ('tpu_operator_compilation_cache_hits_total'
            '{name="warm",namespace="default"} 1') in body2


# --- tpujobctl describe --------------------------------------------------------

def test_ctl_describe_prints_startup(capsys):
    import argparse

    from tpu_operator.cmd import ctl

    job = {
        "metadata": {"name": "warm", "namespace": "default"},
        "spec": {"replicaSpecs": []},
        "status": {"phase": "Running", "state": "Running", "attempt": 1,
                   "startup": {"rendezvousSeconds": 0.21,
                               "restoreSeconds": 1.18,
                               "compileSeconds": 33.0,
                               "firstStepSeconds": 0.66,
                               "cacheHit": True, "attempt": 1}},
    }

    class Stub:
        class tpujobs:
            @staticmethod
            def get(_ns, _name):
                return job

        class events:
            @staticmethod
            def list(_ns):
                return []

    opts = argparse.Namespace(namespace="default", name="warm")
    assert ctl.cmd_describe(Stub, opts) == 0
    out = capsys.readouterr().out
    assert "Startup:" in out
    assert "compile 33.00s" in out
    assert "warm (compilation cache hit)" in out


# --- throughput satellite ------------------------------------------------------

def test_throughput_uses_device_prefetch(monkeypatch):
    from tpu_operator.payload import data as data_mod
    from tpu_operator.payload import train

    mesh, state, step = tiny_build()
    used = []
    real = data_mod.device_prefetch

    def spy(*a, **kw):
        used.append(kw.get("depth"))
        return real(*a, **kw)

    monkeypatch.setattr(data_mod, "device_prefetch", spy)
    _state, steps_per_sec = train.throughput(
        mesh, step, state, counting_linear_stream([]), steps=3, warmup=1)
    assert steps_per_sec > 0
    assert used == [2]  # the shipped pipelined path, default depth
