"""Test configuration.

JAX payload tests run on a virtual 8-device CPU mesh — the env vars must be
set before the first ``import jax`` anywhere in the process, so they are set
at conftest import time (pytest imports conftest before collecting tests).
"""

import os
import sys

# Force CPU: the surrounding environment may pin a real accelerator platform
# (a tunneled TPU whose PJRT plugin a sitecustomize hook registers — and jax
# imports — at interpreter boot, before any conftest runs). Backend *clients*
# initialize lazily, so overriding the platform config here, before the first
# jax.devices() call, still wins. XLA_FLAGS is read at client creation.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge.backends_are_initialized(), (
    "a plugin initialized JAX backends before conftest; tests would run on "
    "the real accelerator — aborting"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
