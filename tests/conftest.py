"""Test configuration.

JAX payload tests run on a virtual 8-device CPU mesh — the env vars must be
set before the first ``import jax`` anywhere in the process, so they are set
at conftest import time (pytest imports conftest before collecting tests).
"""

import os
import sys

# Force CPU: the surrounding environment may pin a real accelerator platform
# (a tunneled TPU whose PJRT plugin a sitecustomize hook registers — and jax
# imports — at interpreter boot, before any conftest runs). Backend *clients*
# initialize lazily, so overriding the platform config here, before the first
# jax.devices() call, still wins. XLA_FLAGS is read at client creation.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
assert not jax._src.xla_bridge.backends_are_initialized(), (
    "a plugin initialized JAX backends before conftest; tests would run on "
    "the real accelerator — aborting"
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Lockdep witness ON for the whole suite (TPUJOB_LOCKDEP=0 opts out):
# every tpu_operator lock created after this point is order-instrumented,
# so the chaos soak, the fleet e2es, and every unit test double as
# deadlock detectors. Exported into the environment too, so subprocess
# payloads witness their own locks. Must run before any tpu_operator
# module creates its module-level locks — i.e. here, at conftest import.
import pytest  # noqa: E402

from tpu_operator.util import joblife, lockdep  # noqa: E402

if os.environ.get("TPUJOB_LOCKDEP", "") not in ("0", "false"):
    os.environ["TPUJOB_LOCKDEP"] = "1"
    lockdep.enable()

# Job-lifecycle witness ON for the whole suite (TPUJOB_JOBLIFE=0 opts
# out), the lockdep pattern for the per-job-state leak class: every
# `# per-job:` container constructs through joblife.track, and the
# controller's deletion reconcile sweeps the registry — so every test
# that deletes a job doubles as a leak detector.
if os.environ.get("TPUJOB_JOBLIFE", "") not in ("0", "false"):
    os.environ["TPUJOB_JOBLIFE"] = "1"
    joblife.enable()


@pytest.fixture(autouse=True)
def _lockdep_guard():
    """Fail any test on whose watch a lock-order violation was recorded.

    The raise at the offending acquisition is not enough on its own:
    reconcile workers catch broad exceptions by design (an error is a
    requeue), so a violation inside a worker thread would otherwise be
    swallowed into a retry loop and the test could still pass."""
    before = lockdep.violation_count()
    yield
    after = lockdep.violation_count()
    assert after == before, lockdep.report()


@pytest.fixture(autouse=True)
def _joblife_guard():
    """Fail any test on whose watch the controller's deletion sweep found
    per-job state (or a metric series) outliving a deleted job.

    The epoch bump scopes each test's sweeps to containers constructed
    within it — job names recur constantly across the suite, and an
    abandoned previous-test controller must not pollute this test's
    verdict."""
    joblife.new_epoch()
    before = joblife.violation_count()
    yield
    after = joblife.violation_count()
    assert after == before, joblife.report()
